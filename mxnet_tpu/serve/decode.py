"""Continuous batching: token-level decode scheduling over a slot arena.

``ModelServer`` schedules at whole-batch granularity — fine for
one-shot forwards, hostile to autoregressive decode, where one long
sequence holds every co-batched request hostage until it finishes.
:class:`DecodeServer` schedules at TOKEN granularity instead
(iteration-level scheduling, the vLLM/Orca idea) while keeping the
serve tier's closed-compile-surface discipline:

- The decode state is a fixed-capacity **slot arena**: per-model
  KV-cache buffers of shape ``(max_slots, max_len, ...)`` plus host
  cursors, last-token ids, and an active mask.  The per-token step is
  ONE pre-warmed executable (fixed shapes; cache buffers donated across
  iterations on accelerator backends; inactive slots masked), no matter
  how many requests are live — steady traffic does zero XLA compiles.
- New requests are **admitted between tokens** into free slots: the
  group's prompts run through the AOT-warmed prefill :class:`BucketSpec`
  grid with the slot-scatter FUSED into the same executable — ONE
  device dispatch per admission group, however many requests it admits.
  Finished, expired, and cancelled requests free their slot at the next
  token boundary instead of waiting for batch stragglers.
- The serve substrate is reused end to end: the bounded
  :class:`~.batcher.Batcher` admission queue with
  ``ServerOverloadedError`` backpressure (slot exhaustion queues, queue
  exhaustion rejects), per-request deadlines checked at token
  boundaries, graceful drain, hot ``reload_weights()`` between tokens,
  per-request streaming via a :class:`DecodeHandle` token iterator plus
  the usual ``Future`` for the full sequence, and
  ``ServerStats``/telemetry integration (TTFT + per-token latency
  windows, slot-occupancy, the ``decodeServe`` profiler section, and
  ``serve.decode.request`` async spans with prefill/decode phase
  attribution).

Decode model contract (``TinyDecoder`` below is the runnable
reference; docs/serving.md documents it)::

    model.prefill(prompts, lengths) -> (first_tokens, *cache_rows)
        prompts : (batch, L) int32 NDArray, padded to a prefill bucket
        lengths : (batch,) int32 NDArray of real prompt lengths
        first_tokens : (batch,) int32 — the first generated token
        cache_rows   : one or more (batch, L, ...) NDArrays, the
                       per-position state to seed the slot cache with

    model.decode_step(tokens, cursors, active, *cache)
        -> (next_tokens, *new_cache)
        tokens  : (max_slots,) int32 — each slot's last emitted token
        cursors : (max_slots,) int32 — position the incoming token's
                  cache row is written at
        active  : (max_slots,) bool — inactive slots carry garbage and
                  MUST be masked out of writes / kept NaN-safe
        cache   : (max_slots, max_len, ...) buffers

Both methods run under graph capture (``traced_apply``), so parameters
are runtime inputs of the compiled step — a hot reload needs no
recompile — and the step is compiled ONCE via
:class:`~..gluon.block.CachedStepOp` with the cache buffers donated.
"""
from __future__ import annotations

import queue as _queue_mod
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as np

from .. import engine, profiler
from ..base import MXNetError, getenv
from ..gluon.block import Block, CachedStepOp
from ..ndarray.ndarray import NDArray, _wrap, array as _nd_array
from ..telemetry import tracer as _tracer
from .batcher import (Batcher, DeadlineExceededError, _Request,
                      ServerClosedError, ServerOverloadedError)
from .buckets import BucketSpec
from .server import _int8_batch_hook
from .stats import LatencyWindow, ServerStats

#: counter set for the decode tier (same ServerStats machinery as
#: ModelServer, token-granular names; ``batches`` counts admission
#: groups — each is ONE fused prefill+slot-write dispatch — and is
#: what ``record_batch`` tallies)
DECODE_COUNTERS = ("submitted", "served", "rejected_overload",
                   "expired_deadline", "failed", "cancelled", "admitted",
                   "batches", "decode_steps", "tokens",
                   "warmup_batches", "reloads")

_DONE = object()          # stream sentinel: generation finished cleanly


# ---------------------------------------------------------------------------
# window-scoped module counters: the profiler's `decodeServe` section
# (provider: profiler._decode_serve_counters; exported to /metrics as
# mxtpu_decode_serve_* gauges by the section collector)

_sec_lock = threading.Lock()
_sec = {"steps": 0, "tokens": 0, "prefill_batches": 0, "admitted": 0,
        "finished": 0, "expired_deadlines": 0, "occ_ratio_sum": 0.0}


def _sec_bump(live_ratio=None, **deltas):
    with _sec_lock:
        for k, n in deltas.items():
            _sec[k] += n
        if live_ratio is not None:
            _sec["occ_ratio_sum"] += live_ratio


def decode_serve_stats():
    """Window snapshot of the continuous-batching counters;
    ``slot_occupancy`` is the token-step-weighted mean live/max_slots."""
    with _sec_lock:
        d = dict(_sec)
    occ = d.pop("occ_ratio_sum")
    d["slot_occupancy"] = round(occ / d["steps"], 4) if d["steps"] else 0.0
    return d


def reset_decode_serve_stats():
    with _sec_lock:
        for k in _sec:
            _sec[k] = 0.0 if k == "occ_ratio_sum" else 0


_donate_ok = None


def _decode_donate_ok():
    """Donate the cache arena to the step/writer executables (XLA
    updates the KV buffers in place).  Off on CPU — PjRt:CPU has no
    donation and would warn per token; MXTPU_DECODE_DONATE forces it
    either way."""
    global _donate_ok
    if _donate_ok is None:
        forced = getenv("DECODE_DONATE", None)
        if forced is not None:
            _donate_ok = forced not in ("0", "false", "False", "")
        else:
            import jax

            _donate_ok = jax.default_backend() != "cpu"
    return _donate_ok


# ---------------------------------------------------------------------------
# request / handle


class _DecodeRequest(_Request):
    __slots__ = ("max_new_tokens", "generated", "slot", "stream",
                 "cancelled", "admitted_at")

    def __init__(self, prompt, length, future, max_new_tokens,
                 deadline_ms=None):
        super().__init__(prompt, length, future, deadline_ms=deadline_ms)
        self.max_new_tokens = int(max_new_tokens)
        self.generated = []
        self.slot = None
        self.stream = _queue_mod.Queue()
        self.cancelled = False
        self.admitted_at = None


class DecodeHandle:
    """Per-request streaming handle: iterate tokens as they are
    generated, or wait on :attr:`future` for the full sequence.

    Iteration yields each token id (int) the moment its boundary
    completes; it ends with ``StopIteration`` on clean finish and
    re-raises the terminal error (deadline, cancellation, shutdown,
    model failure) otherwise — the same error the future carries.
    """

    def __init__(self, req):
        self._req = req
        self.future = req.future

    def __iter__(self):
        return self

    def __next__(self):
        item = self._req.stream.get()
        if item is _DONE:
            # terminal sentinels stay consumable: a second iteration
            # pass (or an iterator copy) must also terminate
            self._req.stream.put(_DONE)
            raise StopIteration
        if isinstance(item, BaseException):
            self._req.stream.put(item)
            raise item
        return item

    def result(self, timeout=None):
        """The full generated token sequence (np.int32 array)."""
        return self.future.result(timeout)

    def cancel(self):
        """Give up on this request: voided at dequeue if still queued,
        freed at the next token boundary if mid-decode."""
        self._req.cancelled = True
        self._req.future.cancel()


# ---------------------------------------------------------------------------
# graph adapters: the fused admission body and the decode step, each
# behind the gluon capture machinery so the compile surface is counted
# (cached_graph_stats) and parameters stay runtime inputs


class _AdmitAdapter(Block):
    """CachedStepOp body for one admission group: ``model.prefill`` PLUS
    the scatter of every admitted request's cache rows into its slot,
    fused into ONE executable per prefill bucket shape (with the arena
    buffers donated).  A split prefill-then-write design costs
    ``1 + group_size`` dispatches per admission; on a dispatch-bound
    host that overhead eats the scheduling win continuous batching
    exists for — fused, admission is exactly one dispatch."""

    def __init__(self, model, n_cache):
        super().__init__()
        self.model = model
        self._n_cache = int(n_cache)

    def forward(self, prompts, lengths, slots, *cache):
        out = self.model.prefill(prompts, lengths)
        if not isinstance(out, (tuple, list)) or len(out) < 2:
            raise MXNetError(
                "model.prefill must return (first_tokens, *cache_rows)")
        first, rows = out[0], out[1:self._n_cache + 1]
        from jax import lax

        s = slots._data                       # (b,) int32
        outs = []
        for c_nd, r_nd in zip(cache, rows):
            c, r = c_nd._data, r_nd._data
            b = r.shape[0]
            # unrolled per-row scatter, REVERSED: padding rows beyond
            # the real group carry slots[i] == slots[0], so their
            # garbage lands on slot[0] FIRST and row 0's own write
            # (last) fully overwrites it — dead rows never touch a
            # live slot and no per-row mask/select is needed
            for i in reversed(range(b)):
                blk = lax.dynamic_slice_in_dim(r, i, 1, axis=0)
                start = (s[i],) + (0,) * (c.ndim - 1)
                c = lax.dynamic_update_slice(c, blk.astype(c.dtype),
                                             start)
            outs.append(_wrap(c))
        return (first,) + tuple(outs)


class _StepAdapter(Block):
    """CachedStepOp body for ``model.decode_step`` (ONE fixed-shape
    executable for the whole serving lifetime)."""

    def __init__(self, model):
        super().__init__()
        self.model = model

    def forward(self, tokens, cursors, active, *cache):
        out = self.model.decode_step(tokens, cursors, active, *cache)
        if not isinstance(out, (tuple, list)) or len(out) < 2:
            raise MXNetError(
                "model.decode_step must return (next_tokens, *new_cache)")
        return tuple(out)


# ---------------------------------------------------------------------------
# the server


class DecodeServer:
    """Continuous-batching autoregressive decode server.

    Parameters
    ----------
    model : Block implementing the decode model contract (module doc).
    spec : BucketSpec
        The closed prefill grid: ``example_shape=(None,)`` int token
        prompts, ``lengths`` = allowed padded prompt lengths.  Every
        length bucket must fit ``max_len``.
    max_slots : int, optional
        Arena capacity (concurrent sequences); default
        ``MXTPU_DECODE_SLOTS`` (8).
    max_len : int, optional
        Cache length per slot; default ``MXTPU_DECODE_MAX_LEN`` (128).
        A request needs ``prompt_len + max_new_tokens <= max_len``.
    eos_id : int, optional
        Token id that terminates a sequence early (None = run to
        ``max_new_tokens``).
    max_new_tokens : int
        Default generation budget per request (``submit()`` overrides).
    max_queue : int
        Bound on queued admissions before submit() fails fast.
    admission : "continuous" | "batch"
        ``"continuous"`` (the point of this class) backfills free slots
        between tokens.  ``"batch"`` only admits when the arena is
        EMPTY — whole-batch decode semantics, every sequence waits for
        the batch's straggler — kept as the honest A/B baseline for
        ``bench.py serve_decode`` and the parity tests.
    ctx : Context, optional
    checkpoint : CheckpointManager or str, optional
        Source for ``reload_weights()``.
    """

    def __init__(self, model, spec, max_slots=None, max_len=None,
                 eos_id=None, max_new_tokens=32, max_queue=256,
                 admission="continuous", ctx=None, checkpoint=None):
        if not isinstance(spec, BucketSpec):
            raise MXNetError("spec must be a serve.BucketSpec")
        if spec.var_axis is None or len(spec.example_shape) != 1:
            raise MXNetError(
                "DecodeServer prompts are 1-D token sequences: use "
                "BucketSpec(example_shape=(None,), lengths=...)")
        if admission not in ("continuous", "batch"):
            raise MXNetError(
                f"admission must be 'continuous' or 'batch', "
                f"got {admission!r}")
        self._model = model
        self._spec = spec
        # an int8-quantized decode model (quantize_net output) books
        # its prefill groups and token steps into the `quantize`
        # profiler section; reload_weights() re-quantizes fp32
        # checkpoints
        self._int8 = bool(getattr(model, "_int8_quantized", False))
        self._note_int8 = _int8_batch_hook(model)
        if self._int8:
            # the decode path requires CALIBRATED quantization: a
            # dynamic range is a jnp.min/max over the whole slot arena,
            # so one request's quantization would depend on co-resident
            # (including garbage inactive) slots — silently breaking
            # the per-slot independence / continuous==batch parity
            # contract.  Fail at construction, not per-token.
            from ..contrib.quantization import _iter_quantized

            uncal = [w.name for _, w in _iter_quantized(model)
                     if not w._calibrated]
            if uncal:
                raise MXNetError(
                    f"DecodeServer needs CALIBRATED quantization: "
                    f"layer(s) {uncal} quantize with dynamic per-batch "
                    "ranges, which reduce over the whole slot arena "
                    "and couple independent requests; re-run "
                    "quantize_net with calib_data= "
                    "(docs/quantization.md)")
        self._slots = int(max_slots if max_slots is not None
                          else getenv("DECODE_SLOTS", 8, int))
        self._max_len = int(max_len if max_len is not None
                            else getenv("DECODE_MAX_LEN", 128, int))
        if self._slots < 1 or self._max_len < 2:
            raise MXNetError("max_slots must be >= 1 and max_len >= 2")
        if spec.lengths[-1] > self._max_len:
            raise MXNetError(
                f"prefill bucket length {spec.lengths[-1]} exceeds the "
                f"slot cache max_len {self._max_len}")
        self._eos_id = None if eos_id is None else int(eos_id)
        self._default_mnt = int(max_new_tokens)
        self._admission = admission
        self._ctx = ctx
        self._batcher = Batcher(max_queue=max_queue, linger_ms=0.0)
        self._stats = ServerStats(counters=DECODE_COUNTERS)
        self._ttft = LatencyWindow()
        self._token_lat = LatencyWindow()
        self._occ_lock = threading.Lock()
        self._occ_sum = 0.0
        self._occ_steps = 0
        self._exec_lock = threading.Lock()   # token step XOR reload
        self._admit_op = None                # built at start() (need
        self._step_op = None                 # the cache layout first)
        self._n_cache = None
        self._cache_meta = None              # [(tail shape, dtype)]
        self._cache = None                   # list of raw device arrays
        self._tokens = np.zeros(self._slots, np.int32)
        self._cursors = np.zeros(self._slots, np.int32)
        self._active = np.zeros(self._slots, bool)
        self._slot_req = [None] * self._slots
        self._step_count = 0
        self._donate = False                 # resolved at _warmup()
        self._started = False
        self._closing = False
        self._abort = False
        self._worker = None
        self._warmup_compiles = 0
        self._metrics_collector = None
        if isinstance(checkpoint, str):
            from ..checkpoint import CheckpointManager

            checkpoint = CheckpointManager(checkpoint)
        self._ckpt = checkpoint

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        """Warm the whole compile surface (one fused prefill+write
        executable per prompt bucket, the ONE decode step), then start
        the token loop.  A drained server restarts with zero new
        compiles."""
        if self._started:
            raise MXNetError("DecodeServer already started")
        self._abort = False
        self._batcher.reopen()
        if self._cache is None:
            self._warmup()
        self._warmup_compiles = self._graph_stats_raw()["compiles"]
        self._started = True
        self._closing = False
        if self._metrics_collector is None:
            from ..telemetry import metrics as _metrics

            self._metrics_collector = _metrics.register_decode_server(self)
        self._worker = threading.Thread(target=self._loop,
                                        name="mxtpu-decode-loop",
                                        daemon=True)
        self._worker.start()
        return self

    def _warmup(self):
        with profiler.op_scope("serve.decode.warmup", cat="serve"):
            # ONE eager probe call discovers the model's cache layout
            # (buffer count, per-position tail shapes, dtypes) before
            # any arena or executable exists
            min_len = self._spec.lengths[0]
            probe = self._model.prefill(
                _nd_array(np.zeros((1, min_len), np.int32),
                          ctx=self._ctx),
                _nd_array(np.full(1, min_len, np.int32), ctx=self._ctx))
            rows = [o for o in probe[1:] if isinstance(o, NDArray)]
            if not rows:
                raise MXNetError("model.prefill returned no cache rows")
            self._cache_meta = [(r.shape[2:], r.dtype) for r in rows]
            self._n_cache = n = len(self._cache_meta)
            self._cache = self._zero_arena()
            # decided once, on the start() thread; the loop thread only
            # reads the cached flag
            donate = self._donate = _decode_donate_ok()
            self._admit_op = CachedStepOp(
                _AdmitAdapter(self._model, n),
                donate_inputs=tuple(range(3, 3 + n)) if donate else ())
            self._step_op = CachedStepOp(
                _StepAdapter(self._model),
                donate_inputs=tuple(range(3, 3 + n)) if donate else ())
            # one fused prefill+write executable per prompt bucket
            # shape — the whole admission surface, compiled up front
            for shape in self._spec.bucket_shapes():
                b, length = shape[0], shape[1]
                outs = self._admit_op(
                    np.zeros((b, length), np.int32),
                    np.full(b, length, np.int32),
                    np.zeros(b, np.int32), *self._cache)
                np.asarray(outs[0])  # fail in warmup, not mid-token
                self._cache = list(outs[1:])
                self._stats.incr("warmup_batches")
            # the decode step: ONE executable, compiled before traffic
            outs = self._step_op(self._tokens, self._cursors,
                                 self._active, *self._cache)
            self._cache = list(outs[1:])
            # warmup scribbled zero-rows into slot 0; hand traffic a
            # clean arena (committed, same jit key as executed outputs)
            self._cache = self._zero_arena()

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc == (None, None, None))
        return False

    def drain(self, timeout=None):
        """Stop admissions and block until every admitted sequence has
        finished decoding; ends with zero queued work and zero live
        slots."""
        self._closing = True
        self._batcher.close()
        if self._worker is not None:
            self._worker.join(timeout)
            if self._worker.is_alive():
                raise MXNetError("drain timed out with live decode slots")
            self._worker = None
        self._started = False

    def shutdown(self, drain=True, timeout=None):
        if not self._started and self._worker is None:
            return
        if drain:
            self.drain(timeout)
            return
        self._closing = True
        self._abort = True
        self._batcher.close()
        if self._worker is not None:
            self._worker.join(timeout)
            self._worker = None
        self._started = False
        # fail live slots, then sweep the queue
        for slot in np.flatnonzero(self._active):
            self._finish_slot(int(slot), "cancelled",
                              ServerClosedError("server shut down"))
        while True:
            group, expired = self._batcher.next_group(self._slots,
                                                      timeout=0)
            if not group and not expired:
                break
            for req in group + expired:
                self._resolve_error(req, "cancelled",
                                    ServerClosedError("server shut down"))

    # -- request path -------------------------------------------------------

    def submit(self, prompt, max_new_tokens=None, deadline_ms=None):
        """Queue one prompt (1-D int token array); returns a
        :class:`DecodeHandle` (stream iterator + ``.future``)."""
        if not self._started or self._closing:
            raise ServerClosedError(
                "DecodeServer is not accepting requests (not started, "
                "draining, or shut down)")
        if isinstance(prompt, NDArray):
            prompt = prompt.asnumpy()
        prompt = np.asarray(prompt, dtype=np.int32)
        length = self._spec.validate(prompt)
        mnt = int(max_new_tokens if max_new_tokens is not None
                  else self._default_mnt)
        if mnt < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if length + mnt > self._max_len:
            raise MXNetError(
                f"prompt_len {length} + max_new_tokens {mnt} exceeds the "
                f"slot cache max_len {self._max_len}; truncate the "
                f"prompt, lower the budget, or raise MXTPU_DECODE_MAX_LEN")
        req = _DecodeRequest(prompt, length, Future(), mnt,
                             deadline_ms=deadline_ms)
        req.trace_id = _tracer.request_begin(
            "serve.decode.request", cat="serve", prompt_len=length,
            max_new_tokens=mnt,
            deadline_ms=deadline_ms if deadline_ms is not None else -1)
        self._stats.incr("submitted")
        try:
            self._batcher.put(req)
        except MXNetError as e:
            self._stats.incr("submitted", -1)
            if isinstance(e, ServerOverloadedError):
                self._stats.incr("rejected_overload")
            _tracer.request_end("serve.decode.request", req.trace_id,
                                cat="serve", outcome="rejected")
            raise
        return DecodeHandle(req)

    def generate(self, prompt, max_new_tokens=None, deadline_ms=None,
                 timeout=None):
        """Synchronous convenience wrapper: the full token sequence."""
        handle = self.submit(prompt, max_new_tokens=max_new_tokens,
                             deadline_ms=deadline_ms)
        if timeout is None and deadline_ms is not None:
            # same contract as ModelServer.predict: a deadline-only
            # call never blocks indefinitely on a wedged server
            from .server import PREDICT_GRACE_S

            timeout = deadline_ms / 1e3 + PREDICT_GRACE_S
        try:
            return handle.result(timeout)
        except _FutureTimeout:
            # caller gave up: void the request so it stops consuming a
            # queue position / decode slot (same contract as
            # ModelServer.predict)
            handle.cancel()
            raise

    # -- the token loop -----------------------------------------------------

    def _loop(self):
        try:
            while not self._abort:
                live = int(self._active.sum())
                self._admit(timeout=0.05 if live == 0 else 0.0)
                live = int(self._active.sum())
                if live == 0:
                    if self._batcher.drained():
                        return
                    continue
                with self._exec_lock:
                    self._boundary_and_step()
        except Exception as e:  # noqa: BLE001 — a dead loop thread
            # would strand every future forever; fail loudly instead
            for slot in np.flatnonzero(self._active):
                self._finish_slot(int(slot), "failed", e)
            while True:
                group, expired = self._batcher.next_group(self._slots,
                                                          timeout=0)
                if not group and not expired:
                    return
                for req in group + expired:
                    self._resolve_error(req, "failed", e)

    def _free_slots(self):
        return [i for i in range(self._slots) if not self._active[i]]

    def _admit(self, timeout):
        free = self._free_slots()
        if not free:
            return
        if self._admission == "batch" and len(free) < self._slots:
            # whole-batch mode: no backfill until the arena is EMPTY
            return
        group, expired = self._batcher.next_group(
            min(len(free), self._spec.max_batch), timeout=timeout)
        for req in expired:
            self._resolve_error(req, "expired",
                                DeadlineExceededError(
                                    "deadline passed while queued"))
        if not group:
            return
        # void caller-side-cancelled requests at dequeue (they must not
        # consume a prefill row or a slot)
        live = []
        for req in group:
            if req.cancelled or req.future.cancelled():
                self._resolve_error(req, "cancelled",
                                    ServerClosedError("request cancelled"))
            else:
                live.append(req)
        if not live:
            return
        try:
            self._prefill_group(live, free)
        except Exception as e:  # noqa: BLE001 — fail THIS group's
            # futures; the loop (and every live slot) must survive
            for req in live:
                if req.slot is not None:
                    continue   # already admitted before the failure
                self._resolve_error(req, "failed", e)
            if self._donate:
                # the failed admit op may have consumed the donated
                # arena buffers; every live sequence's cache state is
                # unknowable, so fail them too and start clean (a
                # deleted-buffer step would take them all down anyway,
                # with a far less diagnosable error)
                for slot in np.flatnonzero(self._active):
                    self._finish_slot(int(slot), "failed", e)
                self._reset_arena()

    def _prefill_group(self, group, free):
        spec = self._spec
        max_len = max(r.length for r in group)
        batch, length = spec.pick(len(group), max_len)
        key = spec.key(batch, length)
        slots = [free.pop(0) for _ in group]
        with profiler.op_scope("serve.decode.admit", cat="serve"):
            padded = spec.pad_batch([r.example for r in group], batch,
                                    length)
            lengths = np.ones(batch, np.int32)
            lengths[:len(group)] = [r.length for r in group]
            # padding rows beyond the group target slots[0]: the fused
            # scatter writes them first and overwrites with row 0's
            # real rows (see _AdmitAdapter), so they never touch a
            # live slot
            slot_vec = np.full(batch, slots[0], np.int32)
            slot_vec[:len(group)] = slots
            # the exec lock serializes this dispatch with
            # reload_weights(): the admit op fetches p.data() live, so
            # an unserialized restore could hand it a torn mix of old
            # and new parameters
            with self._exec_lock, \
                    profiler.op_scope("serve.prefill", cat="serve"):
                outs = self._admit_op(padded, lengths, slot_vec,
                                      *self._cache)
                first = np.asarray(outs[0])
                self._cache = list(outs[1:])
        self._stats.record_batch(
            key, n_real=len(group), n_rows=batch,
            real_elems=sum(r.length for r in group),
            padded_elems=batch * length)
        _sec_bump(prefill_batches=1)
        if self._int8:
            self._note_int8()
        now = time.monotonic()
        for i, req in enumerate(group):
            slot = slots[i]
            req.slot = slot
            req.admitted_at = now
            self._slot_req[slot] = req
            self._tokens[slot] = first[i]
            self._cursors[slot] = req.length
            self._active[slot] = True
            self._stats.incr("admitted")
            _sec_bump(admitted=1)
            _tracer.request_instant("serve.decode.admitted", req.trace_id,
                                    cat="serve", slot=slot,
                                    bucket=key)
            self._emit_token(req, int(first[i]), now)
            # a 1-token budget (or an immediate EOS) finishes at
            # admission without ever occupying a decode step
            self._maybe_finish(req, now)

    def _emit_token(self, req, token, now):
        if not req.generated:
            ttft_ms = (now - req.enqueued_at) * 1e3
            # _occ_lock guards the ttft/token windows against a
            # concurrent stats(reset=True) rewind (LatencyWindow itself
            # is unlocked; ServerStats routes through its own lock)
            with self._occ_lock:
                self._ttft.record(ttft_ms)
            _tracer.request_instant("serve.decode.first_token",
                                    req.trace_id, cat="serve",
                                    ttft_ms=round(ttft_ms, 3))
        req.generated.append(token)
        req.stream.put(token)
        self._stats.incr("tokens")
        _sec_bump(tokens=1)

    def _boundary_and_step(self):
        """One token boundary: expire/cancel live slots, then run the
        single fixed-shape decode step and fan its tokens out."""
        now = time.monotonic()
        for slot in np.flatnonzero(self._active):
            req = self._slot_req[int(slot)]
            if req.cancelled:
                self._finish_slot(int(slot), "cancelled",
                                  ServerClosedError("request cancelled"))
            elif req.expired(now):
                self._finish_slot(int(slot), "expired",
                                  DeadlineExceededError(
                                      "deadline passed mid-decode"))
        live = int(self._active.sum())
        if live == 0:
            return
        t0 = time.monotonic()
        try:
            engine.fault_point("serve.decode", step=self._step_count,
                               live=live)
            with profiler.op_scope("serve.decode.step", cat="serve"):
                outs = self._step_op(self._tokens, self._cursors,
                                     self._active, *self._cache)
                nxt = np.asarray(outs[0])
                self._cache = list(outs[1:])
        except Exception as e:  # noqa: BLE001 — fail every live
            # sequence (their cache state is gone if buffers were
            # donated), reset the arena, keep serving
            for slot in np.flatnonzero(self._active):
                self._finish_slot(int(slot), "failed", e)
            self._reset_arena()
            return
        now = time.monotonic()
        step_ms = (now - t0) * 1e3
        self._step_count += 1
        self._stats.incr("decode_steps")
        if self._int8:
            self._note_int8()
        with self._occ_lock:
            self._token_lat.record(step_ms)
            self._occ_sum += live / self._slots
            self._occ_steps += 1
        _sec_bump(live_ratio=live / self._slots, steps=1)
        for slot in np.flatnonzero(self._active):
            slot = int(slot)
            req = self._slot_req[slot]
            self._cursors[slot] += 1
            self._tokens[slot] = nxt[slot]
            self._emit_token(req, int(nxt[slot]), now)
            self._maybe_finish(req, now)

    def _maybe_finish(self, req, now):
        done = (len(req.generated) >= req.max_new_tokens
                or (self._eos_id is not None
                    and req.generated[-1] == self._eos_id))
        if done:
            self._finish_slot(req.slot, "served")

    def _finish_slot(self, slot, outcome, error=None):
        req = self._slot_req[slot]
        self._active[slot] = False
        self._tokens[slot] = 0
        self._cursors[slot] = 0
        self._slot_req[slot] = None
        self._resolve(req, outcome, error)

    def _resolve(self, req, outcome, error=None):
        now = time.monotonic()
        counter = {"served": "served", "expired": "expired_deadline",
                   "cancelled": "cancelled", "failed": "failed"}[outcome]
        self._stats.incr(counter)
        if outcome == "served":
            self._stats.record_latency((now - req.enqueued_at) * 1e3)
            _sec_bump(finished=1)
        elif outcome == "expired":
            _sec_bump(expired_deadlines=1)
        decode_ms = ((now - req.admitted_at) * 1e3
                     if req.admitted_at is not None else -1)
        _tracer.request_end(
            "serve.decode.request", req.trace_id, cat="serve",
            outcome=outcome, tokens=len(req.generated),
            slot=req.slot if req.slot is not None else -1,
            queue_ms=round(((req.admitted_at or now)
                            - req.enqueued_at) * 1e3, 3),
            decode_ms=round(decode_ms, 3))
        if error is None:
            req.stream.put(_DONE)
            if req.future.set_running_or_notify_cancel():
                req.future.set_result(np.asarray(req.generated, np.int32))
        else:
            req.stream.put(error)
            if req.future.set_running_or_notify_cancel():
                req.future.set_exception(error)

    def _resolve_error(self, req, outcome, error):
        """Terminal path for requests that never reached a slot."""
        self._resolve(req, outcome, error)

    def _zero_arena(self):
        """Fresh zeroed cache buffers, COMMITTED to the serving device:
        every steady-state cache input is a committed executable
        output, so an uncommitted warmup arena would carve a second jit
        cache key for the first bucket's admit op — one phantom compile
        on first traffic (observed; the decode tests pin executable
        counts)."""
        import jax
        import jax.numpy as jnp

        dev = self._ctx.jax_device() if self._ctx is not None \
            else jax.devices()[0]
        return [jax.device_put(
            jnp.zeros((self._slots, self._max_len) + tuple(tail),
                      dtype=dtype), dev)
            for tail, dtype in self._cache_meta]

    def _reset_arena(self):
        self._cache = self._zero_arena()
        self._tokens[:] = 0
        self._cursors[:] = 0
        self._active[:] = False

    # -- hot reload ---------------------------------------------------------

    def reload_weights(self, step=None):
        """Swap parameters from the checkpoint manager between token
        boundaries: in-flight sequences finish their current token on
        the old weights and continue on the new — no drops, no
        recompile (parameters are runtime inputs of the step)."""
        if self._ckpt is None:
            raise MXNetError(
                "no checkpoint manager: construct DecodeServer("
                "checkpoint=...) to enable reload_weights()")
        with self._exec_lock:
            with profiler.op_scope("serve.reload", cat="serve"):
                if self._int8:
                    # quantized decode model: int8-native checkpoints
                    # restore directly, fp32 training checkpoints
                    # re-quantize against the stored scales — either
                    # way zero recompiles (runtime graph inputs)
                    meta = self._ckpt.restore(step=step,
                                              restore_rng=False)
                    from ..contrib.quantization import \
                        load_serving_params

                    load_serving_params(self._model,
                                        meta.get("params") or {})
                else:
                    meta = self._ckpt.restore(step=step,
                                              params=self._model,
                                              restore_rng=False)
        self._stats.incr("reloads")
        return {"step": meta["step"], "epoch": meta.get("epoch")}

    # -- observability ------------------------------------------------------

    def _graph_stats_raw(self):
        agg = {"compiles": 0, "reuses": 0}
        for op in (self._admit_op, self._step_op):
            if op is not None:
                agg["compiles"] += op.stats.get("compiles", 0)
                agg["reuses"] += op.stats.get("reuses", 0)
        return agg

    def live_slots(self):
        return int(self._active.sum())

    def pending(self):
        """Live load gauge for the router's least-loaded dispatch:
        queued admissions + occupied decode slots."""
        return len(self._batcher) + self.live_slots()

    def probe_example(self):
        """A minimal valid prompt (the smallest bucket's shape) — the
        router's health-probe payload (probed with
        ``max_new_tokens=1``)."""
        shape = self._spec.bucket_shapes()[0][1:]
        return np.full(shape, 0, dtype=self._spec.dtype)

    def stats(self, reset=False):
        """One snapshot of the decode tier, same window-scoping contract
        as ``ModelServer.stats`` — the quiescent invariant::

            submitted == served + expired_deadline + failed + cancelled
                         + queue_depth + live_slots
        """
        g = self._graph_stats_raw()
        graph = dict(g, post_warmup_compiles=g["compiles"]
                     - self._warmup_compiles)
        with self._occ_lock:
            occ = (round(self._occ_sum / self._occ_steps, 4)
                   if self._occ_steps else None)
            ttft = self._ttft.snapshot()
            token = self._token_lat.snapshot()
            if reset:
                self._occ_sum = 0.0
                self._occ_steps = 0
                self._ttft.reset()
                self._token_lat.reset()
        return self._stats.snapshot(
            queue_depth=len(self._batcher),
            in_flight=self.live_slots(), reset=reset,
            extra={"graph": graph, "buckets": repr(self._spec),
                   "slots": {"max": self._slots, "live": self.live_slots(),
                             "occupancy": occ,
                             "max_len": self._max_len},
                   "ttft": ttft, "token_latency": token})


# ---------------------------------------------------------------------------
# reference decode model


class TinyDecoder(Block):
    """Minimal runnable decode model: greedy argmax over a cumulative
    mean of token embeddings — the per-slot state is a genuine
    ``(slots, max_len, embed)`` cache of per-position embeddings, so it
    exercises the arena exactly like a transformer KV cache while
    staying a two-matmul CPU-friendly graph.

    Used by tests/test_decode.py, tools/decode_smoke.py, and the
    ``bench.py serve_decode`` leaf; it doubles as the executable
    documentation of the decode model contract.  Math notes:

    - every per-slot quantity depends only on that slot's row, so
      continuous vs whole-batch decode is bit-identical by construction
      (the acceptance parity gate);
    - inactive slots are masked out of cache writes and divide by
      ``max(cursor+1, 1)``, so garbage slots can never NaN the batch.

    With ``proj_block=True`` the output projection is an ``nn.Dense``
    CHILD block instead of a raw parameter, which makes the model
    quantizable: ``contrib.quantization.quantize_net(model, ...)``
    swaps the projection for a compiled int8 Dense and the whole decode
    step (CachedStepOp) carries the int8 matmul — the INT8 decode path.
    Per-slot independence survives because calibrated ranges are
    runtime constants, not batch reductions.
    """

    def __init__(self, vocab=64, embed=16, proj_block=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self.vocab = int(vocab)
        self.embed_dim = int(embed)
        self._proj_block = bool(proj_block)
        self.embedding = self.params.get("embedding",
                                         shape=(vocab, embed))
        if proj_block:
            from ..gluon import nn as _gnn

            self.proj = _gnn.Dense(vocab, use_bias=False, flatten=False,
                                   in_units=embed)
        else:
            self.proj = self.params.get("proj", shape=(embed, vocab))

    def _logits(self, h):
        """Raw (..., d) hidden -> raw (..., vocab) logits, through the
        Dense child (quantizable) or the raw projection parameter."""
        if self._proj_block:
            return self.proj(_wrap(h))._data
        return h @ self.proj.data()._data

    def prefill(self, prompts, lengths):
        import jax.numpy as jnp

        E = self.embedding.data()._data
        p = prompts._data                      # (B, L) int32
        ln = lengths._data                     # (B,) int32
        emb = jnp.take(E, p, axis=0)           # (B, L, d)
        m = (jnp.arange(emb.shape[1])[None, :] < ln[:, None])
        h = jnp.sum(emb * m[..., None].astype(emb.dtype), axis=1) \
            / jnp.maximum(ln, 1).astype(emb.dtype)[:, None]
        first = jnp.argmax(self._logits(h), axis=-1).astype(jnp.int32)
        return _wrap(first), _wrap(emb)

    def decode_step(self, tokens, cursors, active, cache):
        import jax.numpy as jnp

        E = self.embedding.data()._data
        t, cur = tokens._data, cursors._data
        act, c = active._data, cache._data
        e = jnp.take(E, t, axis=0)             # (S, d)
        pos = jnp.arange(c.shape[1])[None, :]
        write = (pos == cur[:, None]) & act[:, None]
        c = jnp.where(write[..., None], e[:, None, :], c)
        seen = (pos <= cur[:, None])
        h = jnp.sum(c * seen[..., None].astype(c.dtype), axis=1) \
            / jnp.maximum(cur + 1, 1).astype(c.dtype)[:, None]
        nxt = jnp.argmax(self._logits(h), axis=-1).astype(jnp.int32)
        return _wrap(nxt), _wrap(c)
