"""Flash/ring attention tests: pallas kernel (interpret mode on CPU) and
ring SP vs the XLA reference oracle."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _qkv(b=2, h=2, s=256, d=128, seed=0):
    import jax.numpy as jnp

    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.rand(b, h, s, d) * 0.5, jnp.float32)
    return mk(), mk(), mk()


def test_flash_kernel_interpret_matches_reference():
    """Run the pallas kernel in interpreter mode (no TPU needed) and
    compare against the XLA oracle."""
    import functools

    import jax
    from jax.experimental import pallas as pl

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    q, k, v = _qkv(s=256, d=128)
    scale = 1.0 / np.sqrt(q.shape[-1])

    # patch pallas_call into interpret mode for CPU execution
    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        out, _ = fa._flash_forward(q, k, v, causal=False, scale=scale)
        out_causal, _ = fa._flash_forward(q, k, v, causal=True,
                                          scale=scale)
    finally:
        pl.pallas_call = orig

    ref = sdpa_reference(q, k, v)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()
    ref_causal = sdpa_reference(q, k, v, causal=True)
    assert np.allclose(np.asarray(out_causal), np.asarray(ref_causal),
                       atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_kernel_matches_reference(causal):
    """The Pallas dQ/dK/dV kernels == XLA-autodiff oracle grads."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    q, k, v = _qkv(s=256, d=128, seed=3)
    scale = 1.0 / np.sqrt(q.shape[-1])
    g = jnp.asarray(np.random.RandomState(4).rand(*q.shape), jnp.float32)

    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        out, vjp = jax.vjp(
            lambda q_, k_, v_: fa._flash_sdpa(q_, k_, v_, None, causal,
                                              scale),
            q, k, v)
        dq, dk, dv = vjp(g)
    finally:
        pl.pallas_call = orig

    ref_out, ref_vjp = jax.vjp(
        lambda q_, k_, v_: sdpa_reference(q_, k_, v_, None, scale=scale,
                                          causal=causal), q, k, v)
    rq, rk, rv = ref_vjp(g)
    assert np.allclose(np.asarray(out), np.asarray(ref_out), atol=2e-3)
    for a, b, name in [(dq, rq, "dq"), (dk, rk, "dk"), (dv, rv, "dv")]:
        assert np.allclose(np.asarray(a), np.asarray(b), atol=5e-3), \
            (name, np.abs(np.asarray(a) - np.asarray(b)).max())


def test_flash_attention_fallback_unaligned():
    """Unaligned shapes take the XLA fallback silently."""
    from mxnet_tpu.ops.attention import _k_sdpa, sdpa_reference

    q, k, v = _qkv(s=40, d=16)
    out = _k_sdpa(q, k, v, None, scale=None, causal=False)
    ref = sdpa_reference(q, k, v)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_matches_reference():
    """Ring attention over an 8-device sp axis == single-device oracle."""
    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.parallel.ring_attention import ring_attention

    q, k, v = _qkv(b=1, h=2, s=64, d=16)
    out = ring_attention(q, k, v)
    ref = sdpa_reference(q, k, v)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_ring_attention_causal():
    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.parallel.ring_attention import ring_attention

    q, k, v = _qkv(b=1, h=1, s=64, d=16, seed=3)
    out = ring_attention(q, k, v, causal=True)
    ref = sdpa_reference(q, k, v, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_ring_attention_grad_flows():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel.ring_attention import ring_attention
    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.parallel import mesh as mesh_mod

    q, k, v = _qkv(b=1, h=1, s=32, d=16, seed=5)

    def loss_ring(q_, k_, v_):
        return jnp.sum(ring_attention(q_, k_, v_) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(sdpa_reference(q_, k_, v_) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    assert np.allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-3)


# -- Ulysses all-to-all sequence parallelism --------------------------------


def test_ulysses_attention_matches_reference():
    """All-to-all SP over 8 devices == single-device oracle."""
    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    q, k, v = _qkv(b=2, h=8, s=64, d=16, seed=7)
    out = ulysses_attention(q, k, v)
    ref = sdpa_reference(q, k, v)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()


def test_ulysses_attention_causal_exact():
    """Each device holds the FULL sequence for its heads, so causal
    masking is exact (no online-softmax recurrence)."""
    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    q, k, v = _qkv(b=1, h=8, s=64, d=16, seed=9)
    out = ulysses_attention(q, k, v, causal=True)
    ref = sdpa_reference(q, k, v, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_ulysses_attention_grad_flows():
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    q, k, v = _qkv(b=1, h=8, s=32, d=16, seed=11)

    def loss_u(q_, k_, v_):
        return jnp.sum(ulysses_attention(q_, k_, v_) ** 2)

    def loss_ref(q_, k_, v_):
        return jnp.sum(sdpa_reference(q_, k_, v_) ** 2)

    g_u = jax.grad(loss_u)(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    assert np.allclose(np.asarray(g_u), np.asarray(g_ref), atol=1e-3)


def test_ulysses_rejects_indivisible_heads():
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu.parallel.ulysses import ulysses_attention

    q, k, v = _qkv(b=1, h=3, s=64, d=16)  # 3 heads, 8 devices
    with pytest.raises(mx.MXNetError, match="heads"):
        ulysses_attention(q, k, v)


def test_flash_kernel_head_dim_64():
    """head_dim=64 (BERT/GPT heads) must use the Pallas path, fwd+bwd
    (previously fell back to XLA because of a d%128 gate)."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    q, k, v = _qkv(s=256, d=64)
    assert fa._tiles_ok(q, k)  # no longer gated out

    scale = 1.0 / np.sqrt(q.shape[-1])
    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        out, lse = fa._flash_forward(q, k, v, causal=True, scale=scale)
        # backward through the pallas kernels
        g = jnp.ones_like(out)
        dq, dk, dv = fa._flash_backward(q, k, v, out, lse, g,
                                        causal=True, scale=scale)
    finally:
        pl.pallas_call = orig

    ref = sdpa_reference(q, k, v, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3)

    def ref_loss(q, k, v):
        return jnp.sum(sdpa_reference(q, k, v, causal=True))

    rdq, rdk, rdv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in ((dq, rdq, "dq"), (dk, rdk, "dk"),
                            (dv, rdv, "dv")):
        err = np.abs(np.asarray(got) - np.asarray(want)).max()
        assert err < 5e-3, (name, err)


def test_flash_kernel_key_padding_mask():
    """The (b,1,1,sk) additive key-padding mask (BERT's form) rides the
    Pallas kernels fwd+bwd; full-score masks still fall back."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    b, h, s, d = 2, 2, 256, 64
    q, k, v = _qkv(b=b, h=h, s=s, d=d)
    # pad out the tail third of keys per batch row
    valid = np.array([s, s - 96], np.int32)
    add = np.zeros((b, 1, 1, s), np.float32)
    for i in range(b):
        add[i, 0, 0, valid[i]:] = -1e9
    add = jnp.asarray(add)

    km = fa._as_key_padding_mask(add, q, k)
    assert km is not None and km.shape == (b, s)
    # bool masks normalize too
    bmask = jnp.asarray(add == 0)
    np.testing.assert_allclose(
        np.asarray(fa._as_key_padding_mask(bmask, q, k) < -1e8),
        np.asarray(add < -1e8).reshape(b, s))
    # a full (sq, sk) score mask is NOT a key-padding mask
    assert fa._as_key_padding_mask(
        jnp.zeros((b, 1, s, s), jnp.float32), q, k) is None

    scale = 1.0 / np.sqrt(d)
    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        out, lse = fa._flash_forward(q, k, v, causal=False, scale=scale,
                                     kmask=km)
        g = jnp.ones_like(out)
        dq, dk, dv = fa._flash_backward(q, k, v, out, lse, g,
                                        causal=False, scale=scale,
                                        kmask=km)
    finally:
        pl.pallas_call = orig

    ref = sdpa_reference(q, k, v, add)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3), \
        np.abs(np.asarray(out) - np.asarray(ref)).max()

    def ref_loss(q, k, v):
        return jnp.sum(sdpa_reference(q, k, v, add))

    rdq, rdk, rdv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in ((dq, rdq, "dq"), (dk, rdk, "dk"),
                            (dv, rdv, "dv")):
        err = np.abs(np.asarray(got) - np.asarray(want)).max()
        assert err < 5e-3, (name, err)


def test_flash_kernel_causal_plus_padding_mask():
    """Causal early-exit loop bounds must compose with the key-padding
    mask (a decoder over padded batches) — fwd and bwd."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    b, h, s, d = 2, 2, 256, 64
    q, k, v = _qkv(b=b, h=h, s=s, d=d, seed=5)
    add = np.zeros((b, 1, 1, s), np.float32)
    add[0, 0, 0, 200:] = -1e9
    add = jnp.asarray(add)
    km = fa._as_key_padding_mask(add, q, k)
    scale = 1.0 / np.sqrt(d)

    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        out, lse = fa._flash_forward(q, k, v, causal=True, scale=scale,
                                     kmask=km)
        g = jnp.ones_like(out)
        dq, dk, dv = fa._flash_backward(q, k, v, out, lse, g,
                                        causal=True, scale=scale,
                                        kmask=km)
    finally:
        pl.pallas_call = orig

    ref = sdpa_reference(q, k, v, add, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3)

    def ref_loss(q, k, v):
        return jnp.sum(sdpa_reference(q, k, v, add, causal=True))

    rdq, rdk, rdv = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for got, want, name in ((dq, rdq, "dq"), (dk, rdk, "dk"),
                            (dv, rdv, "dv")):
        err = np.abs(np.asarray(got) - np.asarray(want)).max()
        assert err < 5e-3, (name, err)


@pytest.mark.parametrize("causal,masked", [(False, False), (True, False),
                                           (False, True)])
def test_flash_streamed_matches_reference(causal, masked,
                                          interpret_pallas, monkeypatch):
    """Streamed flash attention (K/V swept by a grid dim, the long-KV
    path past the VMEM bound): forward AND all three grads must match
    the XLA oracle exactly. The tiny threshold forces streaming at
    test sizes."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas import flash_attention as fa

    monkeypatch.setenv("MXTPU_FLASH_MAX_KV_VMEM_MB", "0.0001")
    rng = np.random.RandomState(0)
    b, h, d = 2, 2, 64
    sq, sk = (256, 256) if causal else (256, 384)
    q = jnp.asarray(rng.randn(b, h, sq, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, h, sk, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, h, sk, d), jnp.float32)
    km = None
    mask4 = None
    if masked:
        km = jnp.asarray(
            np.where(rng.rand(b, sk) > 0.2, 0.0, -1e9), jnp.float32)
        mask4 = km.reshape(b, 1, 1, sk)

    assert not fa._kv_resident(q, k)  # threshold forces the stream path
    out = fa._flash_sdpa(q, k, v, km, causal, 0.125)
    ref = sdpa_reference(q, k, v, mask4, scale=0.125, causal=causal)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    gf = jax.grad(lambda a, bb, c: (
        fa._flash_sdpa(a, bb, c, km, causal, 0.125) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, bb, c: (
        sdpa_reference(a, bb, c, mask4, scale=0.125,
                       causal=causal) ** 2).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(gf, gr):
        denom = np.abs(np.asarray(r)).max() + 1e-9
        assert np.abs(np.asarray(a) - np.asarray(r)).max() / denom < 2e-5


def test_flash_causal_cross_length_uses_oracle():
    """causal with sq != sk is END-aligned in the reference (tril
    offset); the kernels are start-aligned, so the public op must
    route cross-length causal to the oracle."""
    from mxnet_tpu.ops.attention import sdpa_reference
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    rng = np.random.RandomState(1)
    import jax.numpy as jnp

    q = jnp.asarray(rng.randn(1, 2, 128, 64), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 256, 64), jnp.float32)
    out = flash_attention(q, k, k, causal=True)
    ref = sdpa_reference(q, k, k, causal=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
