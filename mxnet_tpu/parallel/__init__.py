"""Parallelism subsystems: mesh SPMD data-parallel, distributed runtime,
sequence parallelism (ref: §2.3 of SURVEY.md — kvstore comm, ps-lite,
DataParallelExecutorGroup; plus capability upgrades beyond the
reference: sharded SPMD training, ring attention)."""
from . import dist  # noqa: F401


def __getattr__(name):
    if name in ("mesh", "data_parallel", "ring_attention", "ulysses"):
        import importlib

        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(
        f"module 'mxnet_tpu.parallel' has no attribute {name!r}")
