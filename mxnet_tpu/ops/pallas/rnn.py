"""Pallas fused LSTM/GRU recurrence kernels for TPU.

Ref: src/operator/rnn.{cc,cu}, nn/cudnn/cudnn_rnn-inl.h — the cuDNN
fused RNN. The BASELINE north star names this explicitly ("LSTM cell
kernels → Pallas").

TPU-native split (the same one cuDNN uses): the input projection
``x @ Wi.T + bi + bh`` is a single big batched GEMM over all timesteps
— left to XLA, which tiles it perfectly onto the MXU. What the compiler
CANNOT fuse well is the sequential recurrence; that is the Pallas
kernel here:

- forward: grid over T; per step one (N,H)x(H,4H) MXU matmul + VPU
  gate math, hidden/cell state living in VMEM scratch across grid
  steps (Mosaic double-buffers the x_proj block DMAs automatically).
- backward: a second Pallas kernel running the grid in reverse
  (index_map ``T-1-t``), accumulating dWh in VMEM scratch and
  producing per-step dgates for the XLA-side input-GEMM VJP.

Forward saves post-activation gates + cell states (the cuDNN
"reserveSpace" trick) so backward needs no recompute.

Parity contract: `lstm_layer(x_proj, wh, h0, c0)` == the lax.scan
reference in ops/rnn.py for the same flat-parameter layout; tested in
interpret mode on CPU (tests/test_pallas_rnn.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _lstm_fwd_kernel(xp_ref, wht_ref, h0_ref, c0_ref,
                     ys_ref, hn_ref, cn_ref, gates_ref, cs_ref,
                     h_scr, c_scr):
    # gate-axis layout: xp (1,N,4,H), wht (4,H,H), gates (1,N,4,H).
    # The 4 gates live on their own (sublane-side) axis, so no op ever
    # slices or concatenates at a non-128 offset of the lane axis — the
    # kernel is Mosaic-tileable for ANY H (DeepAR's H=40 included).
    # Mosaic's tpu.matmul is strictly 2-D (no batched contraction — the
    # first chip session rejected the (N,H)x(4,H,H) dot_general), so the
    # gate matmuls are a static 4-way unroll of clean (N,H)x(H,H) MXU
    # dots; wht is pre-transposed on the host so each is h @ Wh[g].T.
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)
        c_scr[:] = c0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    c = c_scr[:]
    xp = xp_ref[0].astype(jnp.float32)        # (N, 4, H)
    gp = [xp[:, g, :] + jnp.dot(h, wht_ref[g].astype(jnp.float32),
                                preferred_element_type=jnp.float32)
          for g in range(4)]
    i = jax.nn.sigmoid(gp[0])
    f = jax.nn.sigmoid(gp[1])
    g = jnp.tanh(gp[2])
    o = jax.nn.sigmoid(gp[3])
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)

    h_scr[:] = h_new
    c_scr[:] = c_new
    ys_ref[0] = h_new.astype(ys_ref.dtype)
    cs_ref[0] = c_new.astype(cs_ref.dtype)
    for gi, v in enumerate((i, f, g, o)):
        gates_ref[0, :, gi, :] = v.astype(gates_ref.dtype)
    hn_ref[:] = h_new.astype(hn_ref.dtype)
    cn_ref[:] = c_new.astype(cn_ref.dtype)


def _lstm_forward(x_proj, wh, h0, c0):
    T, N, G4 = x_proj.shape
    H = wh.shape[1]
    xp4 = x_proj.reshape(T, N, 4, H)
    # pre-transpose per-gate so the kernel's dots need no in-kernel .T
    wh4 = wh.reshape(4, H, H).transpose(0, 2, 1)
    outs = pl.pallas_call(
        _lstm_fwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, 4, H), lambda t: (t, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((4, H, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((T, N, H), x_proj.dtype),    # ys
            jax.ShapeDtypeStruct((N, H), x_proj.dtype),       # h_n
            jax.ShapeDtypeStruct((N, H), x_proj.dtype),       # c_n
            jax.ShapeDtypeStruct((T, N, 4, H), jnp.float32),  # gates ifgo
            jax.ShapeDtypeStruct((T, N, H), jnp.float32),     # c states
        ),
        out_specs=(
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, 4, H), lambda t: (t, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((N, H), jnp.float32),
        ],
    )(xp4, wh4, h0, c0)
    return outs


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _lstm_bwd_kernel(dy_ref, gates_ref, cs_ref, cprev_ref, hprev_ref,
                     wh_ref, dhn_ref, dcn_ref,
                     dxp_ref, dwh_ref, dh0_ref, dc0_ref,
                     dh_scr, dc_scr, dwh_scr):
    # grid index runs 0..T-1 but index_maps feed step t = T-1-idx
    idx = pl.program_id(0)

    @pl.when(idx == 0)
    def _():
        dh_scr[:] = dhn_ref[:].astype(jnp.float32)
        dc_scr[:] = dcn_ref[:].astype(jnp.float32)
        dwh_scr[:] = jnp.zeros_like(dwh_scr)

    dh = dh_scr[:] + dy_ref[0].astype(jnp.float32)
    i = gates_ref[0, :, 0, :]                 # (N, H) post-activation
    f = gates_ref[0, :, 1, :]
    g = gates_ref[0, :, 2, :]
    o = gates_ref[0, :, 3, :]
    c_t = cs_ref[0]
    c_prev = cprev_ref[0]
    tc = jnp.tanh(c_t)

    do = dh * tc
    dc = dh * o * (1.0 - tc * tc) + dc_scr[:]
    # pre-activation gate grads, order i,f,g,o — kept as four (N,H)
    # arrays so every matmul below is a 2-D tpu.matmul (Mosaic has no
    # batched contraction; see the forward kernel note)
    dgp = (
        (dc * g) * i * (1.0 - i),
        (dc * c_prev) * f * (1.0 - f),
        (dc * i) * (1.0 - g * g),
        do * o * (1.0 - o),
    )

    hp = hprev_ref[0].astype(jnp.float32)
    dh_new = None
    for gi in range(4):
        # param grads: dWh[g] += dgp_g.T @ h_prev -> (H, H)
        dwh_scr[gi] += jnp.dot(dgp[gi].T, hp,
                               preferred_element_type=jnp.float32)
        # dh_prev = sum_g dgp_g @ wh[g]
        contrib = jnp.dot(dgp[gi], wh_ref[gi].astype(jnp.float32),
                          preferred_element_type=jnp.float32)
        dh_new = contrib if dh_new is None else dh_new + contrib
        dxp_ref[0, :, gi, :] = dgp[gi].astype(dxp_ref.dtype)
    dh_scr[:] = dh_new
    dc_scr[:] = dc * f

    dwh_ref[:] = dwh_scr[:].astype(dwh_ref.dtype)
    dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)
    dc0_ref[:] = dc_scr[:].astype(dc0_ref.dtype)


def _lstm_backward(wh, h0, c0, ys, gates, cs, dys, dhn, dcn):
    T, N = gates.shape[0], gates.shape[1]
    H = wh.shape[1]
    wh4 = wh.reshape(4, H, H)
    f32 = jnp.float32
    # h_prev / c_prev sequences (cuDNN reserve-space equivalents)
    h_prev = jnp.concatenate([h0[None].astype(f32), ys[:-1].astype(f32)], 0)
    c_prev = jnp.concatenate([c0[None].astype(f32), cs[:-1]], 0)

    rev3 = lambda t: (T - 1 - t, 0, 0)     # noqa: E731
    rev4 = lambda t: (T - 1 - t, 0, 0, 0)  # noqa: E731
    outs = pl.pallas_call(
        _lstm_bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, H), rev3, memory_space=pltpu.VMEM),  # dy
            pl.BlockSpec((1, N, 4, H), rev4,
                         memory_space=pltpu.VMEM),                   # gates
            pl.BlockSpec((1, N, H), rev3, memory_space=pltpu.VMEM),  # c_t
            pl.BlockSpec((1, N, H), rev3,
                         memory_space=pltpu.VMEM),                   # c_prev
            pl.BlockSpec((1, N, H), rev3,
                         memory_space=pltpu.VMEM),                   # h_prev
            pl.BlockSpec((4, H, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),                   # wh
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),                   # dh_n
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),                   # dc_n
        ],
        out_shape=(
            jax.ShapeDtypeStruct((T, N, 4, H), jnp.float32),  # dx_proj
            jax.ShapeDtypeStruct((4, H, H), jnp.float32),     # dwh
            jax.ShapeDtypeStruct((N, H), jnp.float32),        # dh0
            jax.ShapeDtypeStruct((N, H), jnp.float32),        # dc0
        ),
        out_specs=(
            pl.BlockSpec((1, N, 4, H), rev4, memory_space=pltpu.VMEM),
            pl.BlockSpec((4, H, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((4, H, H), jnp.float32),
        ],
    )(dys, gates, cs, c_prev, h_prev, wh4, dhn, dcn)
    return outs


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------

@jax.custom_vjp
def lstm_layer(x_proj, wh, h0, c0):
    """One LSTM layer/direction over time.

    x_proj: (T, N, 4H) input projection ``x @ Wi.T + bi + bh`` (both
    biases folded — they are additive constants in the pre-activation).
    wh: (4H, H); h0, c0: (N, H). Gate order i, f, g, o (the reference's
    canonical LSTM layout). Returns (ys (T,N,H), h_n, c_n).
    """
    ys, hn, cn, _, _ = _lstm_forward(x_proj, wh, h0, c0)
    return ys, hn, cn


def _lstm_fwd_rule(x_proj, wh, h0, c0):
    ys, hn, cn, gates, cs = _lstm_forward(x_proj, wh, h0, c0)
    return (ys, hn, cn), (wh, h0, c0, ys, gates, cs)


def _lstm_bwd_rule(res, cotangents):
    wh, h0, c0, ys, gates, cs = res
    dys, dhn, dcn = cotangents
    dys = jnp.zeros_like(ys) if _is_zero(dys) else dys
    dhn = jnp.zeros_like(h0) if _is_zero(dhn) else dhn
    dcn = jnp.zeros_like(c0) if _is_zero(dcn) else dcn
    dxp, dwh, dh0, dc0 = _lstm_backward(
        wh, h0, c0, ys, gates, cs,
        dys.astype(jnp.float32), dhn, dcn)
    T, N = dxp.shape[0], dxp.shape[1]
    H = wh.shape[1]
    # back to the packed (T,N,4H) / (4H,H) caller layout
    return (dxp.reshape(T, N, 4 * H).astype(ys.dtype),
            dwh.reshape(4 * H, H).astype(wh.dtype),
            dh0.astype(h0.dtype), dc0.astype(c0.dtype))


def _is_zero(x):
    return x is None or isinstance(
        x, jax.custom_derivatives.SymbolicZero)


lstm_layer.defvjp(_lstm_fwd_rule, _lstm_bwd_rule)


# ---------------------------------------------------------------------------
# GRU recurrence (same cuDNN-style split as the LSTM above: XLA does the
# time-batched input GEMM, the kernel does the sequential part).
# Cell (ops/rnn.py _step_fn('gru'), the cuDNN linear-before-reset form):
#   r = sigmoid(xp_r + h Wh_r^T + bh_r)
#   z = sigmoid(xp_z + h Wh_z^T + bh_z)
#   n = tanh(xp_n + r * (h Wh_n^T + bh_n))
#   h' = (1-z) n + z h
# Saves (r, z, n) and the n-gate recurrent linear term hn_lin for the
# backward (the reserve-space trick); bh rides INSIDE the kernel — its
# n-slot cannot be folded into x_proj because r multiplies it.
# ---------------------------------------------------------------------------


def _gru_fwd_kernel(xp_ref, wht_ref, bh_ref, h0_ref,
                    ys_ref, hn_ref, gates_ref, hnlin_ref, h_scr):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:].astype(jnp.float32)

    h = h_scr[:]
    xp = xp_ref[0].astype(jnp.float32)        # (N, 3, H)
    gh = [jnp.dot(h, wht_ref[g].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
          + bh_ref[g, 0, :].astype(jnp.float32)[None, :]
          for g in range(3)]
    r = jax.nn.sigmoid(xp[:, 0, :] + gh[0])
    z = jax.nn.sigmoid(xp[:, 1, :] + gh[1])
    n = jnp.tanh(xp[:, 2, :] + r * gh[2])
    h_new = (1.0 - z) * n + z * h

    h_scr[:] = h_new
    ys_ref[0] = h_new.astype(ys_ref.dtype)
    for gi, v in enumerate((r, z, n)):
        gates_ref[0, :, gi, :] = v
    hnlin_ref[0] = gh[2]
    hn_ref[:] = h_new.astype(hn_ref.dtype)


def _gru_forward(x_proj, wh, bh, h0):
    T, N, G3 = x_proj.shape
    H = wh.shape[1]
    xp3 = x_proj.reshape(T, N, 3, H)
    wh3 = wh.reshape(3, H, H).transpose(0, 2, 1)
    bh3 = bh.reshape(3, 1, H)
    return pl.pallas_call(
        _gru_fwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, 3, H), lambda t: (t, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, H, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 1, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((T, N, H), x_proj.dtype),    # ys
            jax.ShapeDtypeStruct((N, H), x_proj.dtype),       # h_n
            jax.ShapeDtypeStruct((T, N, 3, H), jnp.float32),  # r,z,n
            jax.ShapeDtypeStruct((T, N, H), jnp.float32),     # hn_lin
        ),
        out_specs=(
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, 3, H), lambda t: (t, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[pltpu.VMEM((N, H), jnp.float32)],
    )(xp3, wh3, bh3, h0)


def _gru_bwd_kernel(dy_ref, gates_ref, hnlin_ref, hprev_ref, wh_ref,
                    dhn_ref,
                    dxp_ref, dwh_ref, dbh_ref, dh0_ref,
                    dh_scr, dwh_scr, dbh_scr):
    idx = pl.program_id(0)

    @pl.when(idx == 0)
    def _():
        dh_scr[:] = dhn_ref[:].astype(jnp.float32)
        dwh_scr[:] = jnp.zeros_like(dwh_scr)
        dbh_scr[:] = jnp.zeros_like(dbh_scr)

    dh = dh_scr[:] + dy_ref[0].astype(jnp.float32)
    r = gates_ref[0, :, 0, :]
    z = gates_ref[0, :, 1, :]
    n = gates_ref[0, :, 2, :]
    hn_lin = hnlin_ref[0]
    hp = hprev_ref[0].astype(jnp.float32)

    dn = dh * (1.0 - z)
    dz = dh * (hp - n)
    dgn = dn * (1.0 - n * n)          # n-gate pre-activation grad
    dr = dgn * hn_lin
    dhnlin = dgn * r                  # grad into (h Wh_n^T + bh_n)
    dgr = dr * r * (1.0 - r)
    dgz = dz * z * (1.0 - z)

    dh_new = dh * z
    # per-gate recurrent VJPs: dh_prev += dgate @ Wh_g ; dWh_g += dgate.T @ h_prev
    for gi, dg in ((0, dgr), (1, dgz), (2, dhnlin)):
        dwh_scr[gi] += jnp.dot(dg.T, hp,
                               preferred_element_type=jnp.float32)
        dbh_scr[gi, 0, :] += jnp.sum(dg, axis=0)
        dh_new = dh_new + jnp.dot(dg, wh_ref[gi].astype(jnp.float32),
                                  preferred_element_type=jnp.float32)
        # x-projection grads: r and z slots take their pre-act grads;
        # the n slot takes dgn (xp_n enters the cell un-multiplied)
        dxp_ref[0, :, gi, :] = (dg if gi != 2 else dgn) \
            .astype(dxp_ref.dtype)
    dh_scr[:] = dh_new

    dwh_ref[:] = dwh_scr[:].astype(dwh_ref.dtype)
    dbh_ref[:] = dbh_scr[:].astype(dbh_ref.dtype)
    dh0_ref[:] = dh_scr[:].astype(dh0_ref.dtype)


def _gru_backward(wh, h0, ys, gates, hn_lin, dys, dhn):
    T, N = gates.shape[0], gates.shape[1]
    H = wh.shape[1]
    wh3 = wh.reshape(3, H, H)
    f32 = jnp.float32
    h_prev = jnp.concatenate([h0[None].astype(f32), ys[:-1].astype(f32)],
                             0)
    rev3 = lambda t: (T - 1 - t, 0, 0)     # noqa: E731
    rev4 = lambda t: (T - 1 - t, 0, 0, 0)  # noqa: E731
    return pl.pallas_call(
        _gru_bwd_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, 3, H), rev4, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, N, H), rev3, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, H, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=(
            jax.ShapeDtypeStruct((T, N, 3, H), jnp.float32),  # dx_proj
            jax.ShapeDtypeStruct((3, H, H), jnp.float32),     # dwh
            jax.ShapeDtypeStruct((3, 1, H), jnp.float32),     # dbh
            jax.ShapeDtypeStruct((N, H), jnp.float32),        # dh0
        ),
        out_specs=(
            pl.BlockSpec((1, N, 3, H), rev4, memory_space=pltpu.VMEM),
            pl.BlockSpec((3, H, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((3, 1, H), lambda t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((N, H), lambda t: (0, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((3, H, H), jnp.float32),
            pltpu.VMEM((3, 1, H), jnp.float32),
        ],
    )(dys, gates, hn_lin, h_prev, wh3, dhn)


@jax.custom_vjp
def gru_layer(x_proj, wh, bh, h0):
    """One GRU layer/direction over time.

    x_proj: (T, N, 3H) input projection ``x @ Wi.T + bi``; wh: (3H, H);
    bh: (3H,) recurrent bias (NOT foldable into x_proj — the reset
    gate multiplies its n-slot); h0: (N, H). Gate order r, z, n.
    Returns (ys (T,N,H), h_n)."""
    ys, hn, _, _ = _gru_forward(x_proj, wh, bh, h0)
    return ys, hn


def _gru_fwd_rule(x_proj, wh, bh, h0):
    ys, hn, gates, hn_lin = _gru_forward(x_proj, wh, bh, h0)
    return (ys, hn), (wh, h0, ys, gates, hn_lin)


def _gru_bwd_rule(res, cotangents):
    wh, h0, ys, gates, hn_lin = res
    dys, dhn = cotangents
    dys = jnp.zeros_like(ys) if _is_zero(dys) else dys
    dhn = jnp.zeros_like(h0) if _is_zero(dhn) else dhn
    dxp, dwh, dbh, dh0 = _gru_backward(
        wh, h0, ys, gates, hn_lin, dys.astype(jnp.float32), dhn)
    T, N = dxp.shape[0], dxp.shape[1]
    H = wh.shape[1]
    return (dxp.reshape(T, N, 3 * H).astype(ys.dtype),
            dwh.reshape(3 * H, H).astype(wh.dtype),
            dbh.reshape(3 * H).astype(wh.dtype),
            dh0.astype(h0.dtype))


gru_layer.defvjp(_gru_fwd_rule, _gru_bwd_rule)
