"""Base utilities: errors, registries, env-var config.

TPU-native equivalents of the reference's dmlc-core foundations
(ref: 3rdparty/dmlc-core — logging, Registry, GetEnv).  Instead of a C++
``dmlc::Registry`` we keep light Python registries; the operator
parameter-struct tier (``dmlc::Parameter``) maps to keyword arguments
validated at the op boundary.
"""
from __future__ import annotations

import os
import threading

__version__ = "0.1.0"

# ---------------------------------------------------------------------------
# Errors


class MXNetError(RuntimeError):
    """Error raised by the framework (ref: include/mxnet/base.h MXGetLastError)."""


def check_call(ok, msg=""):
    if not ok:
        raise MXNetError(msg)


# ---------------------------------------------------------------------------
# Env-var config tier (ref: docs/faq/env_var.md — MXNET_* read via dmlc::GetEnv).
# We accept both MXTPU_* and MXNET_* spellings, MXTPU_* winning.


def getenv(name: str, default=None, dtype=str):
    for prefix in ("MXTPU_", "MXNET_"):
        v = os.environ.get(prefix + name)
        if v is not None:
            if dtype is bool:
                return v not in ("0", "false", "False", "")
            return dtype(v)
    return default


def setenv(name: str, value):
    """Write a config knob under its canonical ``MXTPU_`` spelling (the
    one :func:`getenv` reads first, so it wins over any legacy
    ``MXNET_`` value already in the environment).  ``None`` clears both
    spellings.  The write side of the config tier lives here for the
    same reason the read side does: everything outside ``base.py``
    stays free of raw ``os.environ`` access (the MXA401 invariant)."""
    if value is None:
        for prefix in ("MXTPU_", "MXNET_"):
            os.environ.pop(prefix + name, None)
        return None
    if value is True or value is False:
        value = int(value)
    os.environ["MXTPU_" + name] = str(value)
    return value


# ---------------------------------------------------------------------------
# Generic string-keyed registry (ref: dmlc Registry pattern used by ops,
# iterators, optimizers, initializers, metrics).


class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries = {}
        self._lock = threading.Lock()

    def register(self, name=None, override=False):
        def _reg(obj):
            key = (name or getattr(obj, "__name__", None) or str(obj)).lower()
            with self._lock:
                if key in self._entries and not override:
                    raise MXNetError(
                        f"{self.kind} '{key}' already registered")
                self._entries[key] = obj
            return obj

        return _reg

    def get(self, name):
        key = str(name).lower()
        if key not in self._entries:
            raise MXNetError(
                f"unknown {self.kind} '{name}'; known: {sorted(self._entries)}")
        return self._entries[key]

    def __contains__(self, name):
        return str(name).lower() in self._entries

    def list(self):
        return sorted(self._entries)


# string-name helpers


def numeric_types():
    import numpy as _np

    return (int, float, _np.generic)
