"""Contrib / long-tail op family (ref: src/operator/contrib/ —
ctc_loss.cc, bounding_box.cc, roi_align.cc, amp_cast.cc, moments.cc,
optimizer_op.cc lamb phases). Numpy/brute-force oracles per SURVEY §4."""
import itertools

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def _brute_ctc(logp, target, blank=0):
    """Sum path probabilities over all alignments (tiny cases only)."""
    T, C = logp.shape

    def collapse(path):
        out, prev = [], None
        for p in path:
            if p != prev and p != blank:
                out.append(p)
            prev = p
        return tuple(out)

    tot = -np.inf
    for path in itertools.product(range(C), repeat=T):
        if collapse(path) == tuple(target):
            tot = np.logaddexp(tot, sum(logp[t, path[t]]
                                        for t in range(T)))
    return -tot


def test_ctc_loss_matches_brute_force():
    import jax

    T, N, C = 6, 2, 4
    rng = np.random.RandomState(0)
    data = rng.randn(T, N, C).astype(np.float32)
    label = np.array([[1, 2], [3, 0]], np.float32)  # second len-1 (0 pad)
    loss = nd.CTCLoss(nd.array(data), nd.array(label)).asnumpy()
    logp = np.asarray(jax.nn.log_softmax(data, axis=-1))
    assert np.allclose(loss[0], _brute_ctc(logp[:, 0], (1, 2)), atol=1e-4)
    assert np.allclose(loss[1], _brute_ctc(logp[:, 1], (3,)), atol=1e-4)


def test_ctc_loss_lengths_and_blank_last():
    import jax

    T, N, C = 5, 1, 3
    rng = np.random.RandomState(1)
    data = rng.randn(T, N, C).astype(np.float32)
    logp = np.asarray(jax.nn.log_softmax(data, axis=-1))
    # blank_label='last': blank id C-1, labels 0..C-2
    loss = nd.CTCLoss(nd.array(data), nd.array(np.array([[0, 1]], np.float32)),
                      blank_label="last").asnumpy()
    assert np.allclose(loss[0], _brute_ctc(logp[:, 0], (0, 1), blank=C - 1),
                       atol=1e-4)
    # explicit data length < T must shorten the recursion
    dl = nd.array(np.array([4], np.float32))
    loss4 = nd.CTCLoss(nd.array(data), nd.array(np.array([[1, 0]], np.float32)),
                       dl, use_data_lengths=True).asnumpy()
    assert np.allclose(loss4[0], _brute_ctc(logp[:4, 0], (1,)), atol=1e-4)


def test_ctc_loss_differentiable():
    x = nd.random.uniform(shape=(5, 2, 4))
    x.attach_grad()
    with autograd.record():
        loss = nd.CTCLoss(x, nd.array(np.array([[1, 2], [2, 0]],
                                               np.float32))).sum()
    loss.backward()
    g = x.grad.asnumpy()
    assert np.isfinite(g).all() and (np.abs(g) > 0).any()


def test_box_iou_and_nms():
    a = nd.array(np.array([[0, 0, 2, 2]], np.float32))
    b = nd.array(np.array([[1, 1, 3, 3]], np.float32))
    assert np.allclose(nd.contrib.box_iou(a, b).asnumpy(), 1.0 / 7.0)
    boxes = np.array([[0, 0.9, 0, 0, 10, 10],
                      [1, 0.8, 1, 1, 11, 11],
                      [0, 0.7, 20, 20, 30, 30],
                      [0, 0.05, 0, 0, 9, 9]], np.float32)
    out = nd.contrib.box_nms(nd.array(boxes), overlap_thresh=0.5,
                             valid_thresh=0.1,
                             force_suppress=True).asnumpy()
    # box1 overlaps box0 beyond thresh -> suppressed; box3 under
    # valid_thresh -> invalid; box2 disjoint -> kept.  Suppressed rows
    # are wiped to -1 across all columns (reference semantics)
    assert np.allclose(out[:, 1], [0.9, -1.0, 0.7, -1.0])
    assert np.allclose(out[1], -1.0) and np.allclose(out[3], -1.0)
    assert np.allclose(out[0], boxes[0])  # survivors pass through
    # per-class mode: different ids never suppress each other
    out2 = nd.contrib.box_nms(nd.array(boxes), overlap_thresh=0.5,
                              valid_thresh=0.1, id_index=0,
                              force_suppress=False).asnumpy()
    assert np.allclose(out2[:, 1], [0.9, 0.8, 0.7, -1.0])


def test_box_nms_out_format_conversion():
    # one valid center-format box: cx=5, cy=5, w=4, h=2 -> corners 3,4,7,6
    boxes = np.array([[0, 0.9, 5, 5, 4, 2]], np.float32)
    out = nd.contrib.box_nms(nd.array(boxes), in_format="center",
                             out_format="corner").asnumpy()
    assert np.allclose(out[0, 2:6], [3, 4, 7, 6])
    back = nd.contrib.box_nms(nd.array(out), in_format="corner",
                              out_format="center").asnumpy()
    assert np.allclose(back[0, 2:6], [5, 5, 4, 2])


def test_roi_align_position_sensitive():
    """PSROIAlign (r3: was NotImplementedError): output channel c at
    cell (iy, ix) pools input channel (c*ph + iy)*pw + ix with the
    plain ROIAlign bilinear grid."""
    rng = np.random.RandomState(9)
    D, ph, pw = 2, 2, 2
    img = rng.rand(1, D * ph * pw, 6, 6).astype(np.float32)
    rois = np.array([[0, 0.5, 0.5, 4.5, 4.5]], np.float32)
    got = nd.contrib.ROIAlign(nd.array(img), nd.array(rois),
                              pooled_size=(ph, pw),
                              position_sensitive=True).asnumpy()
    assert got.shape == (1, D, ph, pw)
    plain = nd.contrib.ROIAlign(nd.array(img), nd.array(rois),
                                pooled_size=(ph, pw)).asnumpy()
    for d in range(D):
        for iy in range(ph):
            for ix in range(pw):
                np.testing.assert_allclose(
                    got[0, d, iy, ix],
                    plain[0, (d * ph + iy) * pw + ix, iy, ix],
                    rtol=1e-5)
    # channel-count mismatch is loud
    with pytest.raises(Exception):
        nd.contrib.ROIAlign(nd.zeros((1, 5, 6, 6)), nd.array(rois),
                            pooled_size=(2, 2), position_sensitive=True)
    # grads flow through the gather
    from mxnet_tpu.test_utils import check_numeric_gradient

    check_numeric_gradient(
        lambda d: nd.contrib.ROIAlign(d, nd.array(rois),
                                      pooled_size=(ph, pw),
                                      position_sensitive=True), [img])


def test_sample_multinomial_get_prob_differentiable():
    mx.random.seed(5)
    p = nd.array(np.array([[0.3, 0.7]], np.float32))
    p.attach_grad()
    with autograd.record():
        s, logp = nd.sample_multinomial(p, get_prob=True)
        (logp.sum()).backward()
    g = p.grad.asnumpy()
    assert np.isfinite(g).all() and (np.abs(g) > 0).any()


def test_roi_align_values():
    img = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = nd.array(np.array([[0, 0, 0, 3, 3]], np.float32))
    out = nd.contrib.ROIAlign(img, rois, pooled_size=(2, 2),
                              spatial_scale=1.0).asnumpy()
    # bilinear average of each quadrant's sample taps
    assert np.allclose(out.ravel(), [3.75, 5.25, 9.75, 11.25])
    # gradient flows to the image
    img.attach_grad()
    with autograd.record():
        y = nd.contrib.ROIAlign(img, rois, pooled_size=(2, 2)).sum()
    y.backward()
    assert np.isfinite(img.grad.asnumpy()).all()
    assert img.grad.asnumpy().sum() > 0


def test_moments_matches_numpy():
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    m, v = nd.moments(nd.array(x), axes=(0,))
    assert np.allclose(m.asnumpy(), x.mean(0), atol=1e-6)
    assert np.allclose(v.asnumpy(), x.var(0), atol=1e-6)


def test_amp_ops():
    a = nd.array(np.ones((2, 2), np.float32))
    assert nd.amp_cast(a, dtype="float16").dtype == np.float16
    b16 = nd.amp_cast(a, dtype="float16")
    outs = nd.amp_multicast(b16, a, num_outputs=2)
    assert all(o.dtype == np.float32 for o in outs)  # widest wins
    outs = nd.amp_multicast(b16, a, num_outputs=2, cast_narrow=True)
    assert all(o.dtype == np.float16 for o in outs)
    assert nd.all_finite(a).asnumpy()[0] == 1.0
    assert nd.all_finite(nd.array(np.array([np.inf]))).asnumpy()[0] == 0.0
    assert nd.multi_all_finite(a, a, num_arrays=2).asnumpy()[0] == 1.0


def test_index_copy_add_allclose_quadratic():
    old = nd.zeros((4, 2))
    new = nd.array(np.ones((2, 2), np.float32))
    idx = nd.array(np.array([1, 3], np.float32))
    out = nd.contrib.index_copy(old, idx, new).asnumpy()
    assert np.allclose(out[[1, 3]], 1.0) and np.allclose(out[[0, 2]], 0.0)
    out2 = nd.contrib.index_add(nd.ones((4, 2)), idx, new).asnumpy()
    assert np.allclose(out2[[1, 3]], 2.0)
    assert nd.contrib.allclose(old, old).asnumpy()[0] == 1.0
    q = nd.contrib.quadratic(nd.array(np.array([2.0])), a=1.0, b=2.0,
                             c=3.0).asnumpy()
    assert np.allclose(q, 11.0)


def test_gradientmultiplier_reverses_gradient():
    x = nd.array(np.array([1.0, 2.0]))
    x.attach_grad()
    with autograd.record():
        y = nd.contrib.gradientmultiplier(x, scalar=-0.5).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [-0.5, -0.5])


def test_fft_ifft_reference_semantics():
    x = np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)
    f = nd.contrib.fft(nd.array(x))
    assert f.shape == (1, 8)  # interleaved re/im
    # reference contrib ifft is unnormalized: ifft(fft(x)) == d * x
    assert np.allclose(nd.contrib.ifft(f).asnumpy(), 4 * x, atol=1e-4)


def test_sample_multinomial_and_shuffle():
    mx.random.seed(7)
    p = nd.array(np.array([[0.0, 1.0, 0.0]], np.float32))
    assert nd.sample_multinomial(p).asnumpy()[0] == 1
    data = nd.array(np.arange(10, dtype=np.float32))
    mx.random.seed(3)
    s = nd.shuffle(data).asnumpy()
    assert sorted(s.tolist()) == list(range(10))


def test_softmax_cross_entropy_total():
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    y = np.array([0, 2, 1, 4], np.float32)
    out = nd.softmax_cross_entropy(nd.array(x), nd.array(y)).asnumpy()
    logp = x - np.log(np.exp(x).sum(1, keepdims=True))
    expect = -logp[np.arange(4), y.astype(int)].sum()
    assert np.allclose(out, expect, atol=1e-4)


def test_lamb_phases_descend():
    w = nd.array(np.ones((4,), np.float32))
    g = nd.array(np.full((4,), 0.5, np.float32))
    mean, var = nd.zeros((4,)), nd.zeros((4,))
    upd = nd.lamb_update_phase1(w, g, mean, var, t=1, wd=0.01)
    assert (np.abs(mean.asnumpy()) > 0).all()  # states updated in place
    r1 = nd.array(np.array([np.linalg.norm(w.asnumpy())], np.float32))
    r2 = nd.array(np.array([np.linalg.norm(upd.asnumpy())], np.float32))
    w2 = nd.lamb_update_phase2(w, upd, r1, r2, lr=0.1)
    assert (w2.asnumpy() < 1.0).all()


def test_arange_like_and_isfinite():
    x = nd.zeros((2, 3))
    out = nd.contrib.arange_like(x).asnumpy()
    assert np.allclose(out, np.arange(6).reshape(2, 3))
    out = nd.contrib.arange_like(x, axis=1).asnumpy()
    assert np.allclose(out, [0, 1, 2])
    assert np.allclose(
        nd.isfinite(nd.array(np.array([1.0, np.inf, np.nan]))).asnumpy(),
        [1.0, 0.0, 0.0])


def test_legacy_v1_aliases():
    x = nd.random.uniform(shape=(1, 3, 8, 8))
    w = nd.random.uniform(shape=(4, 3, 3, 3))
    b = nd.zeros((4,))
    y1 = nd.Convolution_v1(x, w, b, kernel=(3, 3), num_filter=4)
    y2 = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert np.allclose(y1.asnumpy(), y2.asnumpy())
    p = nd.Pooling_v1(x, kernel=(2, 2), pool_type="max", stride=(2, 2))
    assert p.shape == (1, 3, 4, 4)


def test_gluon_ctc_loss():
    from mxnet_tpu import gluon

    mx.random.seed(0)
    loss = gluon.loss.CTCLoss()                      # NTC, blank last
    pred = nd.random.uniform(shape=(2, 8, 5))
    label = nd.array(np.array([[0, 1, -1], [2, 2, 3]], np.float32))
    out = loss(pred, label)
    assert out.shape == (2,) and np.isfinite(out.asnumpy()).all()
    # TNC layout must agree with manually swapped NTC
    out_tnc = gluon.loss.CTCLoss(layout="TNC")(
        nd.swapaxes(pred, dim1=0, dim2=1), label)
    assert np.allclose(out.asnumpy(), out_tnc.asnumpy(), atol=1e-5)
    # explicit lengths path
    out_len = loss(pred, label,
                   nd.array(np.array([8, 6], np.float32)),
                   nd.array(np.array([2, 3], np.float32)))
    assert np.isfinite(out_len.asnumpy()).all()
    with pytest.raises(ValueError):
        gluon.loss.CTCLoss(layout="CTN")


def test_multibox_prior_layout():
    x = nd.zeros((1, 3, 2, 2))
    anchors = nd.contrib.MultiBoxPrior(x, sizes=(0.5, 0.25),
                                       ratios=(1.0, 2.0)).asnumpy()
    # S + R - 1 = 3 anchors per cell, 2x2 cells
    assert anchors.shape == (1, 12, 4)
    # reference order (multibox_prior.h): sizes at ratios[0] first,
    # then ratios[1:] at sizes[0].  Cell 0 center (0.25, 0.25).
    assert np.allclose(anchors[0, 0], [0.0, 0.0, 0.5, 0.5], atol=1e-6)
    assert np.allclose(anchors[0, 1],
                       [0.125, 0.125, 0.375, 0.375], atol=1e-6)
    w, h = 0.5 * np.sqrt(2), 0.5 / np.sqrt(2)
    assert np.allclose(anchors[0, 2],
                       [0.25 - w / 2, 0.25 - h / 2,
                        0.25 + w / 2, 0.25 + h / 2], atol=1e-6)
    # non-square feature map: widths carry the in_h/in_w correction so
    # the ratio-1 anchor is square in pixel space
    a2 = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 2, 4)),
                                  sizes=(0.5,)).asnumpy()
    w2 = a2[0, 0, 2] - a2[0, 0, 0]
    h2 = a2[0, 0, 3] - a2[0, 0, 1]
    assert np.allclose(w2, h2 * 2 / 4 * 1), (w2, h2)  # w = s*(H/W)
    # int scalars accepted like the reference's attr parsing
    a3 = nd.contrib.MultiBoxPrior(x, sizes=1, ratios=1)
    assert a3.shape == (1, 4, 4)


def test_multibox_target_hard_negative_mining():
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)),
                                       sizes=(0.4,), ratios=(1.0,))
    N = anchors.shape[1]
    label = nd.array(np.array([[[0, 0.1, 0.1, 0.45, 0.45]]], np.float32))
    # cls_pred: make a few unmatched anchors look confidently non-bg
    cpred = np.zeros((1, 3, N), np.float32)
    cpred[0, 1, :4] = 5.0          # anchors 0-3: hard negatives
    _, _, ct = nd.contrib.MultiBoxTarget(
        anchors, label, nd.array(cpred), negative_mining_ratio=2.0,
        negative_mining_thresh=0.5, ignore_label=-1.0)
    ct = ct.asnumpy()[0]
    n_pos = (ct > 0).sum()
    n_bg = (ct == 0).sum()
    n_ign = (ct == -1).sum()
    assert n_pos >= 1
    assert n_bg <= 2 * n_pos + 1   # ratio bound holds
    assert n_ign == N - n_pos - n_bg and n_ign > 0
    # the kept negatives are exactly the confidently-wrong anchors
    kept = np.where(ct == 0)[0]
    assert set(kept).issubset({0, 1, 2, 3})


def test_multibox_target_encode_decode_roundtrip():
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)),
                                       sizes=(0.4,), ratios=(1.0,))
    # one gt box; cls 2
    label = nd.array(np.array(
        [[[2, 0.1, 0.1, 0.4, 0.45],
          [-1, 0, 0, 0, 0]]], np.float32))
    cls_pred = nd.zeros((1, 4, anchors.shape[1]))
    bt, bm, ct = nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    bt, bm, ct = bt.asnumpy(), bm.asnumpy(), ct.asnumpy()
    assert (bm > 0).any(), "at least the bipartite match must fire"
    matched = np.where(ct[0] > 0)[0]
    assert (ct[0][matched] == 3).all()  # cls 2 -> target 3 (bg=0)
    # decode the encoded target for a matched anchor -> the gt box
    anc = anchors.asnumpy()[0]
    i = matched[0]
    t = bt[0].reshape(-1, 4)[i]
    aw, ah = anc[i, 2] - anc[i, 0], anc[i, 3] - anc[i, 1]
    acx, acy = (anc[i, 0] + anc[i, 2]) / 2, (anc[i, 1] + anc[i, 3]) / 2
    cx = t[0] * 0.1 * aw + acx
    cy = t[1] * 0.1 * ah + acy
    w = np.exp(t[2] * 0.2) * aw
    h = np.exp(t[3] * 0.2) * ah
    assert np.allclose([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                       [0.1, 0.1, 0.4, 0.45], atol=1e-5)


def test_multibox_detection_roundtrip():
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)),
                                       sizes=(0.4,), ratios=(1.0,))
    N = anchors.shape[1]
    # ground truth: the anchor at index 5, class 1
    anc = anchors.asnumpy()[0]
    cls_prob = np.full((1, 3, N), 0.01, np.float32)  # bg + 2 classes
    cls_prob[0, 2, 5] = 0.95                          # class 1 at anchor 5
    loc_pred = np.zeros((1, N * 4), np.float32)       # zero offsets
    out = nd.contrib.MultiBoxDetection(
        nd.array(cls_prob), nd.array(loc_pred), anchors).asnumpy()
    rows = out[0]
    live = rows[rows[:, 0] >= 0]
    assert len(live) >= 1
    best = live[np.argmax(live[:, 1])]
    assert best[0] == 1 and best[1] > 0.9
    assert np.allclose(best[2:6], anc[5], atol=1e-5)


def test_multibox_target_padding_gt_cannot_clobber():
    """A padding row whose all -1 IoU argmaxes to anchor 0 must not wipe
    a real gt's bipartite claim on anchor 0."""
    # one anchor only: the real gt and the padding row both argmax to it
    anchors = nd.array(np.array([[[0.0, 0.0, 1.0, 1.0]]], np.float32))
    label = nd.array(np.array(
        [[[1, 0.0, 0.0, 1.0, 1.0],
          [-1, 0, 0, 0, 0]]], np.float32))
    cls_pred = nd.zeros((1, 3, 1))
    _, bm, ct = nd.contrib.MultiBoxTarget(anchors, label, cls_pred)
    assert ct.asnumpy()[0, 0] == 2.0  # class 1 -> target 2
    assert (bm.asnumpy() > 0).all()


def test_box_decode_encode_roundtrip():
    """encode(anchors, refs) then decode must reproduce the refs
    (ref: contrib bounding_box.cc BoxEncode/BoxDecode)."""
    rng = np.random.RandomState(0)
    B, N = 2, 5
    base = np.zeros((1, N, 4), np.float32)
    base[..., 0] = rng.uniform(0, 0.5, (1, N))
    base[..., 1] = rng.uniform(0, 0.5, (1, N))
    base[..., 2] = base[..., 0] + rng.uniform(0.1, 0.4, (1, N))
    base[..., 3] = base[..., 1] + rng.uniform(0.1, 0.4, (1, N))
    anchors = np.tile(base, (B, 1, 1))  # decode broadcasts (1, N, 4)
    refs = anchors + rng.uniform(-0.03, 0.03, anchors.shape).astype(
        np.float32)
    samples = np.ones((B, N), np.float32)
    matches = np.tile(np.arange(N, dtype=np.float32), (B, 1))
    means = np.zeros(4, np.float32)
    stds = np.ones(4, np.float32)

    t, m = nd.contrib.box_encode(nd.array(samples), nd.array(matches),
                                 nd.array(anchors), nd.array(refs),
                                 nd.array(means), nd.array(stds))
    assert m.asnumpy().min() == 1.0  # all positive samples
    dec = nd.contrib.box_decode(t, nd.array(anchors[:1]))
    np.testing.assert_allclose(dec.asnumpy(), refs, atol=1e-4)
    # negative samples are masked out
    samples[0, 0] = 0.0
    t2, m2 = nd.contrib.box_encode(nd.array(samples), nd.array(matches),
                                   nd.array(anchors), nd.array(refs),
                                   nd.array(means), nd.array(stds))
    assert (t2.asnumpy()[0, 0] == 0).all() and m2.asnumpy()[0, 0, 0] == 0


def test_adaptive_avg_pooling2d():
    x = nd.array(np.arange(2 * 3 * 6 * 6, dtype=np.float32)
                 .reshape(2, 3, 6, 6))
    out = nd.contrib.AdaptiveAvgPooling2D(x, output_size=3)
    assert out.shape == (2, 3, 3, 3)
    # divisible case equals plain 2x2 average pooling
    ref = x.asnumpy().reshape(2, 3, 3, 2, 3, 2).mean(axis=(3, 5))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)
    # non-divisible output: global check via output_size=1
    g = nd.contrib.AdaptiveAvgPooling2D(x, output_size=1)
    np.testing.assert_allclose(g.asnumpy()[..., 0, 0],
                               x.asnumpy().mean(axis=(2, 3)), rtol=1e-6)
    odd = nd.contrib.AdaptiveAvgPooling2D(
        nd.array(np.ones((1, 1, 5, 7), np.float32)), output_size=(2, 3))
    assert odd.shape == (1, 1, 2, 3)
    np.testing.assert_allclose(odd.asnumpy(), 1.0)


def test_index_array():
    x = nd.zeros((2, 3))
    out = nd.contrib.index_array(x)
    assert out.shape == (2, 3, 2)
    np.testing.assert_array_equal(out.asnumpy()[1, 2], [1, 2])
    ax = nd.contrib.index_array(x, axes=(1,))
    assert ax.shape == (2, 3, 1)
    np.testing.assert_array_equal(ax.asnumpy()[..., 0],
                                  [[0, 1, 2], [0, 1, 2]])


def test_contrib_op_edge_kwargs():
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu import optimizer as opt

    # 1-elem output_size tuple = square (ref Shape ndim==1 semantics)
    x = nd.array(np.ones((1, 1, 6, 6), np.float32))
    assert nd.contrib.AdaptiveAvgPooling2D(
        x, output_size=(3,)).shape == (1, 1, 3, 3)
    # negative axes in index_array
    ia = nd.contrib.index_array(nd.zeros((2, 3)), axes=(-1,))
    np.testing.assert_array_equal(ia.asnumpy()[..., 0],
                                  [[0, 1, 2], [0, 1, 2]])
    # GroupAdaGrad rejects weight decay like the reference
    with pytest.raises(mx.MXNetError, match="weight decay"):
        opt.create("groupadagrad", wd=1e-4)


# ---------------------------------------------------------------------------
# round-3 long-tail residue (VERDICT r2 #6): DeformableConvolution,
# PSROIPooling, count_sketch


def _np_bilinear(img, y, x):
    """Zero-padded bilinear sample; img (C, H, W)."""
    C, H, W = img.shape
    out = np.zeros((C,), img.dtype)
    y0, x0 = int(np.floor(y)), int(np.floor(x))
    for yy, wy in ((y0, 1 - (y - y0)), (y0 + 1, y - y0)):
        for xx, wx in ((x0, 1 - (x - x0)), (x0 + 1, x - x0)):
            if 0 <= yy < H and 0 <= xx < W:
                out += img[:, yy, xx] * wy * wx
    return out


def _np_deform_conv(data, offset, weight, bias, kernel, stride, dilate,
                    pad, num_group, dg):
    N, C, H, W = data.shape
    O = weight.shape[0]
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    off = offset.reshape(N, dg, kh, kw, 2, Ho, Wo)
    Cg, Og = C // num_group, O // num_group
    out = np.zeros((N, O, Ho, Wo), np.float32)
    for n in range(N):
        for ho in range(Ho):
            for wo in range(Wo):
                # sampled column (C, kh, kw)
                col = np.zeros((C, kh, kw), np.float32)
                for i in range(kh):
                    for j in range(kw):
                        for g in range(dg):
                            y = (ho * sh - ph + i * dh
                                 + off[n, g, i, j, 0, ho, wo])
                            x = (wo * sw - pw + j * dw
                                 + off[n, g, i, j, 1, ho, wo])
                            cs = slice(g * (C // dg), (g + 1) * (C // dg))
                            col[cs, i, j] = _np_bilinear(
                                data[n, cs], y, x)
                for gr in range(num_group):
                    for o in range(Og):
                        out[n, gr * Og + o, ho, wo] = (
                            weight[gr * Og + o]
                            * col[gr * Cg:(gr + 1) * Cg]).sum()
    return out + bias.reshape(1, -1, 1, 1)


def test_deformable_convolution_numpy_oracle():
    rng = np.random.RandomState(7)
    N, C, H, W = 2, 4, 7, 8
    O, kh, kw = 6, 3, 3
    dg, ng = 2, 2
    data = rng.rand(N, C, H, W).astype(np.float32)
    offset = (rng.rand(N, 2 * dg * kh * kw, 7, 8).astype(np.float32)
              - 0.5) * 2
    weight = rng.rand(O, C // ng, kh, kw).astype(np.float32)
    bias = rng.rand(O).astype(np.float32)
    got = nd.DeformableConvolution(
        nd.array(data), nd.array(offset), nd.array(weight),
        nd.array(bias), kernel=(kh, kw), num_filter=O, pad=(1, 1),
        num_group=ng, num_deformable_group=dg).asnumpy()
    want = _np_deform_conv(data, offset, weight, bias, (kh, kw), (1, 1),
                           (1, 1), (1, 1), ng, dg)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    # zero offsets + zero pad + stride 2 degenerate to plain convolution
    data2 = rng.rand(1, 2, 9, 9).astype(np.float32)
    w2 = rng.rand(3, 2, 3, 3).astype(np.float32)
    off2 = np.zeros((1, 2 * 3 * 3, 4, 4), np.float32)
    got2 = nd.DeformableConvolution(
        nd.array(data2), nd.array(off2), nd.array(w2), None,
        kernel=(3, 3), num_filter=3, stride=(2, 2), no_bias=True).asnumpy()
    want2 = nd.Convolution(nd.array(data2), nd.array(w2), None,
                           kernel=(3, 3), num_filter=3, stride=(2, 2),
                           no_bias=True).asnumpy()
    np.testing.assert_allclose(got2, want2, rtol=1e-4, atol=1e-4)


def test_deformable_convolution_grad():
    from mxnet_tpu.test_utils import check_numeric_gradient

    rng = np.random.RandomState(3)
    data = rng.rand(1, 2, 5, 5).astype(np.float32)
    # keep sampled positions >=0.25 px away from integer pixel centers:
    # bilinear interpolation has gradient kinks there and the central
    # difference (eps=1e-3) would straddle them
    offset = ((rng.rand(1, 2 * 9, 5, 5) * 0.5 + 0.25)
              * rng.choice([-1.0, 1.0], (1, 2 * 9, 5, 5))
              ).astype(np.float32)
    weight = rng.rand(2, 2, 3, 3).astype(np.float32)
    bias = rng.rand(2).astype(np.float32)
    check_numeric_gradient(
        lambda d, o, w, b: nd.DeformableConvolution(
            d, o, w, b, kernel=(3, 3), num_filter=2, pad=(1, 1)),
        [data, offset, weight, bias])


def _np_psroipool(data, rois, scale, D, P, G):
    N, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, D, P, P), np.float32)
    f32 = np.float32
    for r in range(R):
        bidx = int(rois[r, 0])
        # float32 throughout: the op (like the reference kernel) works
        # in f32, and bin edges landing on integers flip floor/ceil by
        # a whole pixel if the oracle runs in float64
        sw_ = f32(np.round(rois[r, 1]) * f32(scale))
        sh_ = f32(np.round(rois[r, 2]) * f32(scale))
        ew = f32((np.round(rois[r, 3]) + f32(1.0)) * f32(scale))
        eh = f32((np.round(rois[r, 4]) + f32(1.0)) * f32(scale))
        rw = max(f32(ew - sw_), f32(0.1))
        rh = max(f32(eh - sh_), f32(0.1))
        bh, bw = f32(rh / P), f32(rw / P)
        for c in range(D):
            for phh in range(P):
                for pww in range(P):
                    hs = int(np.clip(np.floor(f32(phh * bh) + sh_), 0, H))
                    he = int(np.clip(
                        np.ceil(f32((phh + 1) * bh) + sh_), 0, H))
                    ws = int(np.clip(np.floor(f32(pww * bw) + sw_), 0, W))
                    we = int(np.clip(
                        np.ceil(f32((pww + 1) * bw) + sw_), 0, W))
                    gh = int(np.clip(np.floor(phh * G / P), 0, G - 1))
                    gw = int(np.clip(np.floor(pww * G / P), 0, G - 1))
                    ch = (c * G + gh) * G + gw
                    if he <= hs or we <= ws:
                        continue
                    out[r, c, phh, pww] = \
                        data[bidx, ch, hs:he, ws:we].mean()
    return out


def test_psroipooling_numpy_oracle():
    rng = np.random.RandomState(11)
    D, G, P = 3, 3, 3
    data = rng.rand(2, D * G * G, 14, 10).astype(np.float32)
    rois = np.array([[0, 1, 2, 7, 8],
                     [1, 0, 0, 9, 13],
                     [0, 4, 4, 4.6, 4.6],   # tiny roi -> 0.1 floor
                     [1, 6, 9, 20, 30]],    # clipped past the edge
                    np.float32)
    got = nd.PSROIPooling(nd.array(data), nd.array(rois),
                          spatial_scale=0.5, output_dim=D,
                          pooled_size=P).asnumpy()
    want = _np_psroipool(data, rois, 0.5, D, P, G)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_psroipooling_grad():
    from mxnet_tpu.test_utils import check_numeric_gradient

    rng = np.random.RandomState(5)
    data = rng.rand(1, 2 * 2 * 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 6, 6]], np.float32)
    check_numeric_gradient(
        lambda d: nd.PSROIPooling(d, nd.array(rois), spatial_scale=1.0,
                                  output_dim=2, pooled_size=2),
        [data])


def test_count_sketch_numpy_oracle_and_grad():
    from mxnet_tpu.test_utils import check_numeric_gradient

    rng = np.random.RandomState(13)
    n, d, K = 4, 16, 8
    data = rng.rand(n, d).astype(np.float32)
    h = rng.randint(0, K, (1, d)).astype(np.float32)
    s = (rng.randint(0, 2, (1, d)) * 2 - 1).astype(np.float32)
    got = nd.count_sketch(nd.array(data), nd.array(h), nd.array(s),
                          out_dim=K).asnumpy()
    want = np.zeros((n, K), np.float32)
    for j in range(d):
        want[:, int(h[0, j])] += s[0, j] * data[:, j]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # collision-heavy hash must accumulate, and grad must route back
    # through the scatter (reference backward: s * grad_out[:, h])
    check_numeric_gradient(
        lambda x: nd.count_sketch(x, nd.array(h), nd.array(s),
                                  out_dim=K), [data])
