// Native IO library: RecordIO + multithreaded image decode pipeline.
//
// Ref: 3rdparty/dmlc-core recordio (format: [magic u32][lrec u32][data]
// [pad4], magic 0xced7230a) and src/io/iter_image_recordio_2.cc (N decode
// threads -> batch queue -> prefetch).  This is the TPU build's native
// data-loader: workers pread records, parse IRHeader, decode JPEG via
// libjpeg, resize/crop/mirror/normalize into pinned batch buffers that
// Python hands to PjRt host-to-device transfer.
//
// Exposed as a flat C ABI (ref: the c_api boundary) consumed via ctypes.

#include <csetjmp>
#include <cstddef>
#include <cstdio>

#include <jpeglib.h>

#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <queue>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

// ---------------------------------------------------------------------------
// RecordIO

struct RecordWriter {
  FILE* f = nullptr;
};

struct RecordReader {
  FILE* f = nullptr;
  std::vector<char> buf;
};

// IRHeader (ref: mx.recordio.IRHeader): flag u32, label f32, id u64, id2 u64
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

// ---------------------------------------------------------------------------
// JPEG decode via libjpeg

// libjpeg's default error_exit calls exit(); corrupt records must decode
// as a failure return instead, so route fatal errors through longjmp (the
// canonical libjpeg.txt recovery pattern).
struct JpegErrorJmp {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

extern "C" void MxtpuJpegErrorExit(j_common_ptr cinfo) {
  JpegErrorJmp* e = reinterpret_cast<JpegErrorJmp*>(cinfo->err);
  longjmp(e->jb, 1);
}

extern "C" void MxtpuJpegSilence(j_common_ptr, int) {}

bool DecodeJpeg(const uint8_t* data, size_t len, std::vector<uint8_t>* out,
                int* w, int* h, int* channels, bool gray) {
  if (len < 2 || data[0] != 0xFF || data[1] != 0xD8) return false;
  jpeg_decompress_struct cinfo;
  JpegErrorJmp jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = MxtpuJpegErrorExit;
  jerr.pub.emit_message = MxtpuJpegSilence;  // no warning spam on stderr
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = gray ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  *channels = cinfo.output_components;
  out->resize(static_cast<size_t>(*w) * (*h) * (*channels));
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
        static_cast<size_t>(cinfo.output_scanline) * (*w) * (*channels);
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// nearest-neighbour resize HWC uint8 (inter_method 0)
void ResizeNearest(const uint8_t* src, int sw, int sh, int c,
                   uint8_t* dst, int dw, int dh) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    int yy = std::min(sh - 1, static_cast<int>((y + 0.5f) * sy));
    for (int x = 0; x < dw; ++x) {
      int xx = std::min(sw - 1, static_cast<int>((x + 0.5f) * sx));
      for (int ch = 0; ch < c; ++ch) {
        dst[(y * dw + x) * c + ch] = src[(yy * sw + xx) * c + ch];
      }
    }
  }
}

// bilinear resize HWC uint8
void ResizeBilinear(const uint8_t* src, int sw, int sh, int c,
                    uint8_t* dst, int dw, int dh) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    int y0 = std::max(0, static_cast<int>(fy));
    int y1 = std::min(sh - 1, y0 + 1);
    float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      int x0 = std::max(0, static_cast<int>(fx));
      int x1 = std::min(sw - 1, x0 + 1);
      float wx = fx - x0;
      for (int ch = 0; ch < c; ++ch) {
        float v00 = src[(y0 * sw + x0) * c + ch];
        float v01 = src[(y0 * sw + x1) * c + ch];
        float v10 = src[(y1 * sw + x0) * c + ch];
        float v11 = src[(y1 * sw + x1) * c + ch];
        float v = v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                  v10 * wy * (1 - wx) + v11 * wy * wx;
        dst[(y * dw + x) * c + ch] = static_cast<uint8_t>(v + 0.5f);
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Image pipeline: threaded decode + augment + batch assembly

struct PipelineConfig {
  int c, h, w;
  int batch_size;
  int num_threads;
  int shuffle, rand_crop, rand_mirror;
  int resize_short;  // <=0: disabled
  float mean[3], std_[3];
  uint64_t seed;
  // augmentation tier (ref: src/io/image_aug_default.cc):
  int random_resized_crop = 0;      // area/aspect-sampled crop
  float min_area = 1.f, max_area = 1.f;        // fraction of source
  float min_aspect = 1.f, max_aspect = 1.f;    // w/h ratio range
  float brightness = 0.f, contrast = 0.f, saturation = 0.f;
  float hue_deg = 0.f;              // max |hue shift|, OpenCV half-deg
  int inter_method = 1;             // 0 nearest, 1 bilinear, 9/10 random
};

void Resize(const uint8_t* src, int sw, int sh, int c, uint8_t* dst,
            int dw, int dh, int method) {
  if (method == 0) {
    ResizeNearest(src, sw, sh, c, dst, dw, dh);
  } else {
    ResizeBilinear(src, sw, sh, c, dst, dw, dh);
  }
}

struct Batch {
  std::vector<float> data;
  std::vector<float> labels;
  int count = 0;
};

struct ImagePipeline {
  FILE* f = nullptr;
  std::vector<uint64_t> offsets;
  std::vector<float> labels_at;  // parsed lazily; offsets drive reads
  PipelineConfig cfg;
  std::vector<size_t> order;
  std::atomic<size_t> cursor{0};
  size_t num_batches = 0;

  std::vector<std::thread> workers;
  // completed batches keyed by batch index: the consumer emits them in
  // sequence order regardless of which worker finished first (the
  // reference's batcher/prefetcher preserves record order; without
  // this, batch order silently depends on thread scheduling — a race
  // caught by the parity test under CPU load)
  std::map<size_t, Batch*> ready;
  size_t next_emit = 0;  // guarded by mu
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  size_t max_queue = 4;
  std::atomic<bool> stop{false};
  std::atomic<int> active_workers{0};
  uint64_t epoch_seed;

  ~ImagePipeline() { Shutdown(); }

  void Shutdown() {
    {
      // stop must flip under mu: a worker that just evaluated the
      // cv_space predicate false would otherwise sleep through this
      // notify and hang the join (lost wakeup)
      std::lock_guard<std::mutex> lk(mu);
      stop.store(true);
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) {
      if (t.joinable()) t.join();
    }
    workers.clear();
    std::lock_guard<std::mutex> lk(mu);
    for (auto& kv : ready) delete kv.second;
    ready.clear();
    if (f) {
      fclose(f);
      f = nullptr;
    }
  }

  bool ReadRecordAt(uint64_t off, std::vector<char>* buf) {
    // thread-safe independent reads via pread on the raw fd.
    // cflag continuation chunks (dmlc magic-escape splitting) are
    // reassembled with the removed magic word re-inserted.
    int fd = fileno(f);
    buf->clear();
    bool first = true;
    while (true) {
      uint32_t hdr[2];
      if (pread(fd, hdr, 8, off) != 8) return false;
      if (hdr[0] != kMagic) return false;
      uint32_t len = hdr[1] & kLenMask;
      uint32_t cflag = hdr[1] >> 29;
      if (first && cflag != 0 && cflag != 1) return false;
      if (!first) {
        if (cflag != 2 && cflag != 3) return false;
        uint32_t magic_word = kMagic;
        const char* m = reinterpret_cast<const char*>(&magic_word);
        buf->insert(buf->end(), m, m + 4);
      }
      size_t base = buf->size();
      buf->resize(base + len);
      if (pread(fd, buf->data() + base, len, off + 8) !=
          static_cast<ssize_t>(len)) {
        return false;
      }
      if (cflag == 0 || cflag == 3) return true;
      off += 8 + len + ((4 - len % 4) % 4);
      first = false;
    }
  }

  void DecodeOne(const std::vector<char>& rec, float* out, float* label,
                 std::mt19937* rng) {
    const char* p = rec.data();
    IRHeader h;
    // h.flag comes from the file: a truncated/corrupt record can carry a
    // flag whose label vector extends past the payload, so bound-check
    // before the label read and the skip arithmetic (size_t underflow).
    if (rec.size() < sizeof(h)) {
      *label = 0.f;
      std::fill(out, out + static_cast<size_t>(cfg.c) * cfg.h * cfg.w, 0.f);
      return;
    }
    std::memcpy(&h, p, sizeof(h));
    // flag > 0 means the label is a packed float vector of that many
    // elements preceding the image bytes (ref: mx.recordio.unpack strips
    // for flag > 0 — size-1 vectors included)
    size_t skip = sizeof(h) + (h.flag > 0 ? 4ull * h.flag : 0ull);
    if (skip > rec.size()) {
      *label = 0.f;
      std::fill(out, out + static_cast<size_t>(cfg.c) * cfg.h * cfg.w, 0.f);
      return;
    }
    float lab;
    if (h.flag > 0) {
      std::memcpy(&lab, p + sizeof(h), 4);  // first element of the vector
    } else {
      lab = h.label;
    }
    *label = lab;
    const uint8_t* img = reinterpret_cast<const uint8_t*>(p + skip);
    size_t img_len = rec.size() - skip;

    std::vector<uint8_t> pixels;
    int w = 0, hh = 0, ch = 0;
    if (!DecodeJpeg(img, img_len, &pixels, &w, &hh, &ch, cfg.c == 1)) {
      std::fill(out, out + static_cast<size_t>(cfg.c) * cfg.h * cfg.w, 0.f);
      return;
    }
    std::uniform_real_distribution<float> u01(0.f, 1.f);
    int inter = cfg.inter_method;
    if (inter == 9 || inter == 10) inter = ((*rng)() & 1) ? 1 : 0;

    std::vector<uint8_t> resized;
    int x0 = 0, y0 = 0;
    if (cfg.random_resized_crop) {
      // area/aspect-sampled crop, resized to the target (ref:
      // image_aug_default.cc max_random_area/max_aspect_ratio path)
      int cw = -1, chh = -1;
      for (int attempt = 0; attempt < 10 && cw < 0; ++attempt) {
        float area = (cfg.min_area +
                      u01(*rng) * (cfg.max_area - cfg.min_area)) *
                     static_cast<float>(w) * hh;
        float la = std::log(cfg.min_aspect), lb = std::log(cfg.max_aspect);
        float ar = std::exp(la + u01(*rng) * (lb - la));
        int tw = static_cast<int>(std::sqrt(area * ar) + 0.5f);
        int th = static_cast<int>(std::sqrt(area / ar) + 0.5f);
        if (tw > 0 && th > 0 && tw <= w && th <= hh) {
          cw = tw;
          chh = th;
        }
      }
      if (cw < 0) {  // fallback: largest centered square
        cw = chh = std::min(w, hh);
      }
      x0 = (w == cw) ? 0 : static_cast<int>((*rng)() % (w - cw + 1));
      y0 = (hh == chh) ? 0 : static_cast<int>((*rng)() % (hh - chh + 1));
      std::vector<uint8_t> crop(static_cast<size_t>(cw) * chh * ch);
      for (int y = 0; y < chh; ++y) {
        std::memcpy(crop.data() + static_cast<size_t>(y) * cw * ch,
                    pixels.data() +
                        (static_cast<size_t>(y0 + y) * w + x0) * ch,
                    static_cast<size_t>(cw) * ch);
      }
      resized.resize(static_cast<size_t>(cfg.w) * cfg.h * ch);
      Resize(crop.data(), cw, chh, ch, resized.data(), cfg.w, cfg.h,
             inter);
      pixels.swap(resized);
      w = cfg.w;
      hh = cfg.h;
      x0 = y0 = 0;
    } else {
      // resize shorter side
      if (cfg.resize_short > 0) {
        int shorter = std::min(w, hh);
        float scale = static_cast<float>(cfg.resize_short) / shorter;
        int nw = std::max(cfg.w, static_cast<int>(w * scale + 0.5f));
        int nh = std::max(cfg.h, static_cast<int>(hh * scale + 0.5f));
        resized.resize(static_cast<size_t>(nw) * nh * ch);
        Resize(pixels.data(), w, hh, ch, resized.data(), nw, nh, inter);
        pixels.swap(resized);
        w = nw;
        hh = nh;
      }
      if (w < cfg.w || hh < cfg.h) {
        int nw = std::max(w, cfg.w), nh = std::max(hh, cfg.h);
        resized.resize(static_cast<size_t>(nw) * nh * ch);
        Resize(pixels.data(), w, hh, ch, resized.data(), nw, nh, inter);
        pixels.swap(resized);
        w = nw;
        hh = nh;
      }
      if (cfg.rand_crop) {
        x0 = static_cast<int>((*rng)() % (w - cfg.w + 1));
        y0 = static_cast<int>((*rng)() % (hh - cfg.h + 1));
      } else {
        x0 = (w - cfg.w) / 2;
        y0 = (hh - cfg.h) / 2;
      }
    }
    bool mirror = cfg.rand_mirror && ((*rng)() & 1);

    // color jitter as ONE per-image 3x3 matrix + offset (brightness →
    // contrast → saturation → hue composed; saturation/hue preserve the
    // gray axis so only contrast contributes an offset).  Applied in
    // float during the normalize pass — no extra image-sized buffer.
    bool jitter = ch == 3 &&
                  (cfg.brightness > 0.f || cfg.contrast > 0.f ||
                   cfg.saturation > 0.f || cfg.hue_deg > 0.f);
    bool use_hue = cfg.hue_deg > 0.f;
    float M[3][3] = {{1, 0, 0}, {0, 1, 0}, {0, 0, 1}};
    float off = 0.f;
    if (jitter) {
      auto uj = [&](float j) {
        return 1.f + (2.f * u01(*rng) - 1.f) * j;
      };
      float ab = cfg.brightness > 0.f ? uj(cfg.brightness) : 1.f;
      float ac = cfg.contrast > 0.f ? uj(cfg.contrast) : 1.f;
      float as = cfg.saturation > 0.f ? uj(cfg.saturation) : 1.f;
      const float gw[3] = {0.299f, 0.587f, 0.114f};
      if (ac != 1.f) {
        double gsum = 0;
        for (int y = 0; y < cfg.h; ++y) {
          for (int x = 0; x < cfg.w; ++x) {
            const uint8_t* p = pixels.data() +
                ((static_cast<size_t>(y0 + y) * w) + x0 + x) * 3;
            gsum += gw[0] * p[0] + gw[1] * p[1] + gw[2] * p[2];
          }
        }
        float gray0 = static_cast<float>(
            gsum / (static_cast<double>(cfg.h) * cfg.w));
        off = (1.f - ac) * ab * gray0;
      }
      // S = as*I + (1-as) * 1 * gw^T   (rows identical in the 2nd term)
      float S[3][3];
      for (int r = 0; r < 3; ++r) {
        for (int col = 0; col < 3; ++col) {
          S[r][col] = (r == col ? as : 0.f) + (1.f - as) * gw[col];
        }
      }
      if (use_hue) {
        // hue rotation about the gray axis (YIQ approximation; the
        // reference's HSL conversion is per-pixel — same capability,
        // cheaper math).  hue_deg is in OpenCV half-degrees (max 180).
        // Skipped entirely at hue_deg=0: the YIQ constants don't
        // round-trip exactly and would bias channels at theta=0.
        float theta = (2.f * u01(*rng) - 1.f) * cfg.hue_deg / 180.f *
                      3.14159265f;
        float cs = std::cos(theta), sn = std::sin(theta);
        const float H[3][3] = {
            {0.299f + 0.701f * cs + 0.168f * sn,
             0.587f - 0.587f * cs + 0.330f * sn,
             0.114f - 0.114f * cs - 0.497f * sn},
            {0.299f - 0.299f * cs - 0.328f * sn,
             0.587f + 0.413f * cs + 0.035f * sn,
             0.114f - 0.114f * cs + 0.292f * sn},
            {0.299f - 0.300f * cs + 1.25f * sn,
             0.587f - 0.588f * cs - 1.05f * sn,
             0.114f + 0.886f * cs - 0.203f * sn}};
        // M = H * S * (ab*ac)
        for (int r = 0; r < 3; ++r) {
          for (int col = 0; col < 3; ++col) {
            M[r][col] = 0.f;
            for (int k = 0; k < 3; ++k) M[r][col] += H[r][k] * S[k][col];
            M[r][col] *= ab * ac;
          }
        }
      } else {
        for (int r = 0; r < 3; ++r) {
          for (int col = 0; col < 3; ++col) {
            M[r][col] = S[r][col] * ab * ac;
          }
        }
      }
    }

    // HWC crop -> CHW normalized (jitter matrix fused in)
    for (int cc = 0; cc < cfg.c; ++cc) {
      float m = cfg.mean[cc < 3 ? cc : 0];
      float s = cfg.std_[cc < 3 ? cc : 0];
      float* dst = out + static_cast<size_t>(cc) * cfg.h * cfg.w;
      for (int y = 0; y < cfg.h; ++y) {
        for (int x = 0; x < cfg.w; ++x) {
          int sx = mirror ? (cfg.w - 1 - x) : x;
          const uint8_t* p =
              pixels.data() +
              (static_cast<size_t>(y0 + y) * w + (x0 + sx)) * ch;
          float v;
          if (jitter) {
            v = M[cc][0] * p[0] + M[cc][1] * p[1] + M[cc][2] * p[2] + off;
            v = std::min(255.f, std::max(0.f, v));
          } else {
            v = static_cast<float>(p[ch == 1 ? 0 : cc]);
          }
          dst[y * cfg.w + x] = (v - m) / s;
        }
      }
    }
  }

  void WorkerLoop(int tid) {
    std::mt19937 rng(epoch_seed + 0x9e3779b9u * tid);
    const size_t bs = cfg.batch_size;
    while (!stop.load()) {
      size_t b = cursor.fetch_add(1);
      if (b >= num_batches) break;
      {
        // bounded lookahead: claim-order is sequential, so gating on
        // consumption progress bounds in-flight batches without the
        // full-queue deadlock an admission gate would have
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk,
                      [&] { return b < next_emit + max_queue || stop; });
        if (stop) break;
      }
      auto* batch = new Batch;
      batch->data.resize(bs * cfg.c * cfg.h * cfg.w);
      batch->labels.resize(bs);
      batch->count = static_cast<int>(bs);
      std::vector<char> rec;
      for (size_t i = 0; i < bs; ++i) {
        size_t idx = order[b * bs + i];
        if (!ReadRecordAt(offsets[idx], &rec)) {
          batch->labels[i] = -1.f;
          continue;
        }
        DecodeOne(rec, batch->data.data() +
                       i * static_cast<size_t>(cfg.c) * cfg.h * cfg.w,
                  &batch->labels[i], &rng);
      }
      std::unique_lock<std::mutex> lk(mu);
      if (stop) {
        delete batch;
        break;
      }
      ready[b] = batch;
      cv_ready.notify_all();
    }
    if (active_workers.fetch_sub(1) == 1) cv_ready.notify_all();
  }

  void Start() {
    stop.store(false);
    cursor.store(0);
    active_workers.store(cfg.num_threads);
    for (int t = 0; t < cfg.num_threads; ++t) {
      workers.emplace_back(&ImagePipeline::WorkerLoop, this, t);
    }
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI

extern "C" {

// ---- RecordIO writer ----
void* MXTPURecordIOWriterCreate(const char* path) {
  auto* w = new RecordWriter;
  w->f = fopen(path, "wb");
  if (!w->f) {
    delete w;
    return nullptr;
  }
  return w;
}

static bool WriteChunk(FILE* f, const char* data, uint64_t len,
                       uint32_t cflag) {
  if (len > kLenMask) return false;
  uint32_t hdr[2] = {kMagic,
                     (cflag << 29) | static_cast<uint32_t>(len)};
  if (fwrite(hdr, 1, 8, f) != 8) return false;
  if (len && fwrite(data, 1, len, f) != len) return false;
  static const char pad[4] = {0, 0, 0, 0};
  size_t p = (4 - len % 4) % 4;
  if (p && fwrite(pad, 1, p, f) != p) return false;
  return true;
}

int64_t MXTPURecordIOWrite(void* handle, const char* buf, uint64_t len) {
  auto* w = static_cast<RecordWriter*>(handle);
  int64_t pos = ftell(w->f);
  // dmlc magic-escape splitting, mirroring the python writer: split at
  // every 4-byte-aligned magic occurrence in the payload
  std::vector<uint64_t> splits;
  for (uint64_t i = 0; i + 4 <= len; i += 4) {
    uint32_t word;
    std::memcpy(&word, buf + i, 4);
    if (word == kMagic) splits.push_back(i);
  }
  if (splits.empty()) {
    if (len > kLenMask) return -1;
    if (!WriteChunk(w->f, buf, len, 0)) return -1;
    return pos;
  }
  // validate every chunk before writing anything
  uint64_t prev = 0;
  for (size_t i = 0; i <= splits.size(); ++i) {
    uint64_t end = (i < splits.size()) ? splits[i] : len;
    if (end - prev > kLenMask) return -1;
    prev = (i < splits.size()) ? splits[i] + 4 : end;
  }
  prev = 0;
  for (size_t i = 0; i <= splits.size(); ++i) {
    uint64_t end = (i < splits.size()) ? splits[i] : len;
    uint32_t flag = (i == 0) ? 1u : (i == splits.size() ? 3u : 2u);
    if (!WriteChunk(w->f, buf + prev, end - prev, flag)) return -1;
    prev = (i < splits.size()) ? splits[i] + 4 : end;
  }
  return pos;
}

void MXTPURecordIOWriterFree(void* handle) {
  auto* w = static_cast<RecordWriter*>(handle);
  if (w->f) fclose(w->f);
  delete w;
}

// ---- RecordIO reader ----
void* MXTPURecordIOReaderCreate(const char* path) {
  auto* r = new RecordReader;
  r->f = fopen(path, "rb");
  if (!r->f) {
    delete r;
    return nullptr;
  }
  return r;
}

// returns length, 0 on EOF, -1 on error; data pointer valid until next call
int64_t MXTPURecordIORead(void* handle, const char** out) {
  auto* r = static_cast<RecordReader*>(handle);
  r->buf.clear();
  bool first = true;
  while (true) {
    uint32_t hdr[2];
    if (fread(hdr, 1, 8, r->f) != 8) return first ? 0 : -1;
    if (hdr[0] != kMagic) return -1;
    uint32_t len = hdr[1] & kLenMask;
    uint32_t cflag = hdr[1] >> 29;
    if (first && cflag != 0 && cflag != 1) return -1;
    if (!first) {
      if (cflag != 2 && cflag != 3) return -1;
      // re-insert the magic word the writer removed at the split
      uint32_t magic_word = kMagic;
      const char* m = reinterpret_cast<const char*>(&magic_word);
      r->buf.insert(r->buf.end(), m, m + 4);
    }
    size_t base = r->buf.size();
    r->buf.resize(base + len);
    if (fread(r->buf.data() + base, 1, len, r->f) != len) return -1;
    size_t p = (4 - len % 4) % 4;
    if (p) fseek(r->f, static_cast<long>(p), SEEK_CUR);
    if (cflag == 0 || cflag == 3) {
      *out = r->buf.data();
      return static_cast<int64_t>(r->buf.size());
    }
    first = false;
  }
}

void MXTPURecordIOSeek(void* handle, uint64_t pos) {
  fseek(static_cast<RecordReader*>(handle)->f, static_cast<long>(pos),
        SEEK_SET);
}

int64_t MXTPURecordIOTell(void* handle) {
  return ftell(static_cast<RecordReader*>(handle)->f);
}

void MXTPURecordIOReaderFree(void* handle) {
  auto* r = static_cast<RecordReader*>(handle);
  if (r->f) fclose(r->f);
  delete r;
}

// ---- Image pipeline ----
// aug: 10 floats — {random_resized_crop, min_area, max_area, min_aspect,
// max_aspect, brightness, contrast, saturation, hue_deg, inter_method};
// may be null (no augmentation beyond crop/mirror).
void* MXTPUImagePipelineCreate(const char* rec_path,
                               const uint64_t* offsets, uint64_t n,
                               int c, int h, int w, int batch_size,
                               int num_threads, int shuffle, int rand_crop,
                               int rand_mirror, int resize_short,
                               const float* mean, const float* std_,
                               uint64_t seed, const float* aug) {
  auto* p = new ImagePipeline;
  p->f = fopen(rec_path, "rb");
  if (!p->f) {
    delete p;
    return nullptr;
  }
  p->offsets.assign(offsets, offsets + n);
  p->cfg = PipelineConfig{c, h, w, batch_size, num_threads, shuffle,
                          rand_crop, rand_mirror, resize_short,
                          {mean[0], mean[1], mean[2]},
                          {std_[0], std_[1], std_[2]}, seed};
  if (aug != nullptr) {
    p->cfg.random_resized_crop = aug[0] > 0.5f;
    p->cfg.min_area = aug[1];
    p->cfg.max_area = aug[2];
    p->cfg.min_aspect = aug[3];
    p->cfg.max_aspect = aug[4];
    p->cfg.brightness = aug[5];
    p->cfg.contrast = aug[6];
    p->cfg.saturation = aug[7];
    p->cfg.hue_deg = aug[8];
    p->cfg.inter_method = static_cast<int>(aug[9]);
  }
  p->epoch_seed = seed;
  return p;
}

// start (or restart) an epoch
void MXTPUImagePipelineReset(void* handle, uint64_t epoch) {
  auto* p = static_cast<ImagePipeline*>(handle);
  {
    std::lock_guard<std::mutex> lk(p->mu);  // see Shutdown: lost wakeup
    p->stop.store(true);
  }
  p->cv_space.notify_all();
  p->cv_ready.notify_all();
  for (auto& t : p->workers) {
    if (t.joinable()) t.join();
  }
  p->workers.clear();
  {
    std::lock_guard<std::mutex> lk(p->mu);
    for (auto& kv : p->ready) delete kv.second;
    p->ready.clear();
    p->next_emit = 0;
  }
  p->order.resize(p->offsets.size());
  for (size_t i = 0; i < p->order.size(); ++i) p->order[i] = i;
  p->epoch_seed = p->cfg.seed + epoch * 1000003ull;
  if (p->cfg.shuffle) {
    std::mt19937_64 rng(p->epoch_seed);
    std::shuffle(p->order.begin(), p->order.end(), rng);
  }
  p->num_batches = p->order.size() / p->cfg.batch_size;
  p->Start();
}

// copy next batch into out buffers; returns count (0 = epoch done)
int MXTPUImagePipelineNext(void* handle, float* out_data,
                           float* out_labels) {
  auto* p = static_cast<ImagePipeline*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->cv_ready.wait(lk, [&] {
    return p->ready.count(p->next_emit) ||
           p->active_workers.load() == 0 || p->stop.load();
  });
  auto it = p->ready.find(p->next_emit);
  if (it == p->ready.end()) return 0;
  Batch* b = it->second;
  p->ready.erase(it);
  ++p->next_emit;
  p->cv_space.notify_all();
  lk.unlock();
  std::memcpy(out_data, b->data.data(), b->data.size() * sizeof(float));
  std::memcpy(out_labels, b->labels.data(),
              b->labels.size() * sizeof(float));
  int count = b->count;
  delete b;
  return count;
}

uint64_t MXTPUImagePipelineNumBatches(void* handle) {
  auto* p = static_cast<ImagePipeline*>(handle);
  return p->offsets.size() / p->cfg.batch_size;
}

void MXTPUImagePipelineFree(void* handle) {
  delete static_cast<ImagePipeline*>(handle);
}

const char* MXTPUVersion() { return "mxtpu_io 0.1.0"; }

}  // extern "C"
