"""Fused trainer step (multi-tensor optimizer update + bucketed
allreduce + batched replica broadcast).

The contract under test: the fused path (on by default) is BIT-
compatible with the sequential path (`aggregate_num=1` /
MXNET_OPTIMIZER_AGGREGATION_SIZE=1), sparse/AMP configurations fall
through to the sequential code unchanged, and states snapshots move
freely between fused and sequential restarts.
"""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, profiler, _imperative
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import trainer as trainer_mod
from mxnet_tpu.gluon.parameter import Parameter

MIXED_SPECS = [((3, 4), "float32"), ((17,), "float32"),
               ((2, 3, 2), "float32"), ((5, 5), "float16"),
               ((1,), "float32"), ((4, 1), "float16"), ((6,), "float16")]


def make_params(specs, ctx=None, seed=0, **param_kwargs):
    rng = np.random.RandomState(seed)
    params = []
    for j, (shape, dtype) in enumerate(specs):
        p = Parameter(f"p{j}", shape=shape, dtype=dtype, **param_kwargs)
        p.initialize(ctx=ctx)
        p.set_data(nd.array(rng.randn(*shape).astype(dtype)))
        params.append(p)
    return params


def set_grads(params, seed=1):
    """Deterministic per-(param, replica) gradients: replicas get
    DIFFERENT grads so the allreduce actually has something to sum."""
    rng = np.random.RandomState(seed)
    for p in params:
        for c in p.list_ctx():
            g = rng.randn(*p.shape).astype(p.dtype)
            p._data[c]._grad = nd.array(g, ctx=c, dtype=p.dtype)


def run_steps(opt, opt_args, specs, n_steps, aggregate_num=None, ctx=None,
              batch_size=2, params=None, trainer=None, seed0=0):
    if params is None:
        params = make_params(specs, ctx=ctx)
    if trainer is None:
        kwargs = dict(opt_args)
        if aggregate_num is not None:
            kwargs["aggregate_num"] = aggregate_num
        trainer = gluon.Trainer(params, opt, kwargs)
    for step in range(n_steps):
        set_grads(params, seed=seed0 + step)
        trainer.step(batch_size)
    return params, trainer


def states_leaves(blob):
    out = []

    def walk(v):
        if v is None:
            return
        if isinstance(v, (tuple, list)):
            for x in v:
                walk(x)
        elif isinstance(v, dict):
            for x in v.values():
                walk(x)
        elif hasattr(v, "asnumpy"):
            out.append(v.asnumpy())
        elif isinstance(v, np.ndarray):
            out.append(v)

    walk(blob["states"])
    return out


@pytest.mark.parametrize("opt,opt_args", [
    ("sgd", {"learning_rate": 0.05, "wd": 0.01}),
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 0.01}),
    ("adam", {"learning_rate": 0.01, "wd": 0.01}),
])
def test_fused_bit_parity_mixed_dtypes_shapes(opt, opt_args):
    fused_p, fused_tr = run_steps(opt, opt_args, MIXED_SPECS, 4)
    seq_p, seq_tr = run_steps(opt, opt_args, MIXED_SPECS, 4,
                              aggregate_num=1)
    assert fused_tr._fusion_enabled() and not seq_tr._fusion_enabled()
    for a, b in zip(fused_p, seq_p):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())
    for a, b in zip(states_leaves(fused_tr.states_dict()),
                    states_leaves(seq_tr.states_dict())):
        np.testing.assert_array_equal(a, b)
    assert fused_tr.optimizer.num_update == seq_tr.optimizer.num_update


def test_fused_parity_with_clip_and_lr_schedule():
    from mxnet_tpu import lr_scheduler

    results = []
    for agg in (None, 1):
        sched = lr_scheduler.FactorScheduler(step=2, factor=0.5,
                                             base_lr=0.1)
        args = {"learning_rate": 0.1, "lr_scheduler": sched,
                "momentum": 0.9, "clip_gradient": 0.4, "wd": 0.001}
        if agg is not None:
            args["aggregate_num"] = agg
        p, _ = run_steps("sgd", args, MIXED_SPECS[:4], 6)
        results.append([q.data().asnumpy() for q in p])
    for a, b in zip(*results):
        np.testing.assert_array_equal(a, b)


def test_fused_multi_device_allreduce_and_grad_writeback():
    ctxs = [mx.xla(0), mx.xla(1)]
    specs = [((4, 3), "float32"), ((7,), "float32"), ((2, 2), "float32"),
             ((9,), "float32")]
    outcome = {}
    for agg in (None, 1):
        params, tr = run_steps("sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               specs, 3, aggregate_num=agg, ctx=ctxs,
                               batch_size=1)
        outcome[agg] = params
        if agg is None:
            assert tr._kvstore is not None
    # grads summed across replicas, written back into EVERY holder
    rng = np.random.RandomState(0 + 2)  # seed of the last step
    for p in outcome[None]:
        expected = sum(rng.randn(*p.shape).astype(p.dtype)
                       for _ in p.list_ctx())
        for c in p.list_ctx():
            got = p._data[c]._grad
            assert got is not None and got.context == c
            np.testing.assert_allclose(got.asnumpy(), expected,
                                       rtol=2e-6, atol=2e-6)
    for pa, pb in zip(outcome[None], outcome[1]):
        ref = pa.data(pa.list_ctx()[0]).asnumpy()
        for c in pa.list_ctx():
            # fused == sequential AND replicas identical
            np.testing.assert_array_equal(pa.data(c).asnumpy(),
                                          pb.data(c).asnumpy())
            np.testing.assert_array_equal(pa.data(c).asnumpy(), ref)


def test_bucket_size_cap_builds_multiple_buckets(monkeypatch):
    # ~100-byte buckets: every param bucket overflows, so the 4 fp32
    # params of >25 floats each land in separate buckets — parity must
    # survive the split
    monkeypatch.setenv("MXTPU_KVSTORE_BUCKET_MB", "0.0001")
    ctxs = [mx.xla(0), mx.xla(1)]
    specs = [((10, 4), "float32"), ((37,), "float32"), ((6, 5), "float32"),
             ((40,), "float32")]
    trainer_mod.reset_trainer_step_stats()
    fused, _ = run_steps("sgd", {"learning_rate": 0.1}, specs, 2,
                         ctx=ctxs, batch_size=1)
    assert trainer_mod.trainer_step_stats()["buckets_built"] >= 2 * 4
    monkeypatch.delenv("MXTPU_KVSTORE_BUCKET_MB")
    seq, _ = run_steps("sgd", {"learning_rate": 0.1}, specs, 2,
                       aggregate_num=1, ctx=ctxs, batch_size=1)
    for a, b in zip(fused, seq):
        for c in a.list_ctx():
            np.testing.assert_array_equal(a.data(c).asnumpy(),
                                          b.data(c).asnumpy())


def test_amp_overflow_skips_whole_fused_group():
    from mxnet_tpu.amp import LossScaler

    params = make_params(MIXED_SPECS[:3])
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})
    tr._amp_loss_scaler = LossScaler(init_scale=2.0 ** 8)
    tr._amp_original_scale = tr._scale
    before = [p.data().asnumpy().copy() for p in params]
    set_grads(params, seed=0)
    # poison ONE param's grad: the whole fused group must skip
    p0 = params[0]
    bad = np.full(p0.shape, np.inf, np.float32)
    p0._data[p0.list_ctx()[0]]._grad = nd.array(bad)
    scale_before = tr._amp_loss_scaler.loss_scale
    tr.step(1)
    for p, w in zip(params, before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)
    assert tr._amp_loss_scaler.loss_scale == scale_before / 2.0
    # clean grads: the update resumes (weights move)
    set_grads(params, seed=1)
    tr.step(1)
    assert not np.array_equal(params[1].data().asnumpy(), before[1])


def test_row_sparse_params_excluded_from_fusion():
    specs = [((8, 3), "float32"), ((5,), "float32"), ((4, 4), "float32")]
    outcome = {}
    for agg in (None, 1):
        params = make_params(specs)
        sp = Parameter("emb", shape=(12, 3), grad_stype="row_sparse")
        sp.initialize()
        sp.set_data(nd.array(np.random.RandomState(7).randn(12, 3)
                             .astype(np.float32)))
        params.append(sp)
        kwargs = {"learning_rate": 0.05, "momentum": 0.9}
        if agg is not None:
            kwargs["aggregate_num"] = agg
        tr = gluon.Trainer(params, "sgd", kwargs)
        trainer_mod.reset_trainer_step_stats()
        for step in range(3):
            set_grads(params, seed=step)
            tr.step(1)
        if agg is None:
            stats = trainer_mod.trainer_step_stats()
            # the row_sparse param never rides a fused group
            assert stats["params_fused"] == 3 * len(specs)
        outcome[agg] = params
    for a, b in zip(outcome[None], outcome[1]):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())


def test_states_dict_roundtrip_across_fused_sequential_restart():
    opt_args = {"learning_rate": 0.01, "wd": 0.01}
    # continuous fused run: 5 steps
    cont_p, cont_tr = run_steps("adam", opt_args, MIXED_SPECS[:4], 5)
    # fused 3 steps -> snapshot -> restart SEQUENTIAL for 2 more
    a_p, a_tr = run_steps("adam", opt_args, MIXED_SPECS[:4], 3)
    blob = a_tr.states_dict()
    b_p = make_params(MIXED_SPECS[:4])
    for src, dst in zip(a_p, b_p):
        dst.set_data(src.data())
    b_tr = gluon.Trainer(b_p, "adam", dict(opt_args, aggregate_num=1))
    b_tr.load_states_dict(blob)
    run_steps("adam", opt_args, None, 2, params=b_p, trainer=b_tr,
              seed0=3)
    for a, b in zip(cont_p, b_p):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())
    # and back: sequential snapshot resumed under the fused path
    blob2 = b_tr.states_dict()
    c_p = make_params(MIXED_SPECS[:4])
    for src, dst in zip(b_p, c_p):
        dst.set_data(src.data())
    c_tr = gluon.Trainer(c_p, "adam", dict(opt_args))
    c_tr.load_states_dict(blob2)
    run_steps("adam", opt_args, None, 2, params=c_p, trainer=c_tr,
              seed0=5)
    cont2_p, _ = run_steps("adam", opt_args, MIXED_SPECS[:4], 7)
    for a, b in zip(cont2_p, c_p):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())


def test_no_recompile_across_decaying_lr_schedule():
    from mxnet_tpu import lr_scheduler

    sched = lr_scheduler.FactorScheduler(step=3, factor=0.9, base_lr=0.1)
    params = make_params(MIXED_SPECS[:4])
    tr = gluon.Trainer(params, "adam",
                       {"learning_rate": 0.1, "lr_scheduler": sched})
    for step in range(3):  # warmup covers every group signature
        set_grads(params, seed=step)
        tr.step(1)
    nd.waitall()
    lr0 = tr.learning_rate
    c0 = _imperative.compiled_executable_count()
    for step in range(10):
        set_grads(params, seed=3 + step)
        tr.step(1)
    nd.waitall()
    assert _imperative.compiled_executable_count() == c0
    assert tr.learning_rate < lr0


def test_profiler_trainer_step_section_window_scoped():
    trainer_mod.reset_trainer_step_stats()
    run_steps("sgd", {"learning_rate": 0.1}, MIXED_SPECS[:3], 2)
    out = json.loads(profiler.dumps(reset=True))
    ts = out["trainerStep"]
    assert ts["steps"] == 2
    assert ts["params_fused"] == 2 * 3
    assert ts["dispatches_per_step"] > 0
    # reset=True scoped the window: a second dump starts from zero
    again = json.loads(profiler.dumps(reset=True))["trainerStep"]
    assert again["steps"] == 0 and again["params_fused"] == 0


def test_aggregation_env_knob_beats_ctor_arg(monkeypatch):
    from mxnet_tpu import optimizer as opt_mod

    monkeypatch.setenv("MXNET_OPTIMIZER_AGGREGATION_SIZE", "1")
    opt = opt_mod.create("sgd", aggregate_num=32)
    assert opt.aggregate_num == 1  # env wins (documented precedence)
    monkeypatch.delenv("MXNET_OPTIMIZER_AGGREGATION_SIZE")
    assert opt_mod.create("sgd", aggregate_num=7).aggregate_num == 7
    assert opt_mod.create("sgd").aggregate_num == 64
    # env=1 restores the sequential trainer path end to end
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION_SIZE", "1")
    trainer_mod.reset_trainer_step_stats()
    _, tr = run_steps("sgd", {"learning_rate": 0.1}, MIXED_SPECS[:3], 1)
    assert not tr._fusion_enabled()
    assert trainer_mod.trainer_step_stats()["params_fused"] == 0


def test_aggregate_num_caps_group_size():
    params = make_params([((4,), "float32")] * 10)
    tr = gluon.Trainer(params, "sgd",
                       {"learning_rate": 0.1, "aggregate_num": 4})
    trainer_mod.reset_trainer_step_stats()
    set_grads(params, seed=0)
    tr.step(1)
    stats = trainer_mod.trainer_step_stats()
    assert stats["params_fused"] == 10
    # 10 params in chunks of <=4 -> 3 fused dispatches
    assert stats["dispatches_per_step"] == 3


def test_donation_hold_gates_fused_donation(monkeypatch):
    """While an async checkpoint capture is draining (donation hold),
    the fused update must run its NON-donating executable so the held
    buffer references survive the d2h readback."""
    from mxnet_tpu import _imperative, engine
    from mxnet_tpu import optimizer as opt_mod

    recorded = []
    real = _imperative.get_jitted

    def spy(fn, kwargs, donate_argnums=None):
        recorded.append(donate_argnums)
        return real(fn, kwargs)  # never actually donate (CPU backend)

    monkeypatch.setattr(_imperative, "get_jitted", spy)
    monkeypatch.setattr(opt_mod, "_donate_ok", True)  # fake accelerator
    monkeypatch.setattr(opt_mod, "_nondonate_warmed", set())
    params = make_params(MIXED_SPECS[:2])
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})
    # the FIRST call per group signature warms the non-donating twin
    # (so a later checkpoint hold never compiles mid-step)...
    set_grads(params, seed=0)
    tr.step(1)
    assert recorded and all(d is None for d in recorded), recorded
    recorded.clear()
    # ...and every later call donates
    set_grads(params, seed=2)
    tr.step(1)
    assert (0, 2) in recorded, recorded
    recorded.clear()
    engine.acquire_donation_hold()
    try:
        assert engine.donation_held()
        set_grads(params, seed=1)
        tr.step(1)
        assert recorded and all(d is None for d in recorded), recorded
    finally:
        engine.release_donation_hold()
    assert not engine.donation_held()


def test_checkpoint_capture_holds_donation(tmp_path, monkeypatch):
    """CheckpointManager.save holds off donation from capture until the
    d2h readback completes, and releases it afterwards."""
    from mxnet_tpu import engine
    from mxnet_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    held = []
    real_readback = mgr._readback

    def spy_readback(state):
        held.append(engine.donation_held())
        return real_readback(state)

    monkeypatch.setattr(mgr, "_readback", spy_readback)
    params = make_params(MIXED_SPECS[:2])
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})
    set_grads(params, seed=0)
    tr.step(1)
    mgr.save(1, params={p.name: p.data() for p in params}, trainer=tr)
    mgr.wait_until_finished()
    assert held == [True]
    assert not engine.donation_held()


def test_fused_update_groups_by_device():
    """Model-parallel placement: params living on DIFFERENT devices must
    update correctly on the default fused path (grouped per device, not
    jammed into one jitted call that jax rejects)."""
    specs = [((4, 3), "float32"), ((6,), "float32"),
             ((2, 5), "float32"), ((3,), "float32")]
    outcome = {}
    for agg in (None, 1):
        params = []
        for j, (shape, dtype) in enumerate(specs):
            p = Parameter(f"p{j}", shape=shape, dtype=dtype)
            p.initialize(ctx=mx.xla(j % 2))  # alternate devices
            p.set_data(nd.array(np.random.RandomState(j).randn(*shape)
                                .astype(dtype), ctx=mx.xla(j % 2)))
            params.append(p)
        kwargs = {"learning_rate": 0.05, "momentum": 0.9}
        if agg is not None:
            kwargs["aggregate_num"] = agg
        tr = gluon.Trainer(params, "sgd", kwargs)
        trainer_mod.reset_trainer_step_stats()
        for step in range(3):
            set_grads(params, seed=step)
            tr.step(1)
        if agg is None:
            assert trainer_mod.trainer_step_stats()["params_fused"] == \
                3 * len(specs)
        outcome[agg] = params
    for a, b in zip(outcome[None], outcome[1]):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())


def test_pushpull_buckets_by_value_device():
    """Multi-key pushpull with VALUE slots on different devices (outs
    co-located) must not pack mixed-device buffers into one bucket."""
    store = mx.kv.create("local")
    rng = np.random.RandomState(0)
    vals_np = [(rng.randn(5).astype(np.float32),
                rng.randn(5).astype(np.float32)) for _ in range(4)]
    outs = []
    for k in range(4):
        store.init(k, nd.zeros((5,), ctx=mx.xla(0)))
        outs.append([nd.zeros((5,), ctx=mx.xla(0)),
                     nd.zeros((5,), ctx=mx.xla(0))])
    # keys alternate slot-device layout: (dev0,dev1) vs (dev1,dev0)
    values = [[nd.array(v0, ctx=mx.xla(k % 2)),
               nd.array(v1, ctx=mx.xla((k + 1) % 2))]
              for k, (v0, v1) in enumerate(vals_np)]
    stats = store.pushpull(list(range(4)), values, out=outs)
    assert stats is not None and stats["buckets"] >= 2
    for (v0, v1), o in zip(vals_np, outs):
        np.testing.assert_allclose(o[0].asnumpy(), v0 + v1, rtol=1e-6)
        np.testing.assert_allclose(o[1].asnumpy(), v0 + v1, rtol=1e-6)


def test_pushpull_single_replica_skips_packing():
    """One value slot + no distributed reduce = nothing to sum: the
    multi-key path must rebind like sequential push+pull, building no
    buckets and dispatching no pack/unpack kernels."""
    store = mx.kv.create("local")
    rng = np.random.RandomState(3)
    vals_np = [rng.randn(4).astype(np.float32) for _ in range(3)]
    outs = []
    for k, v in enumerate(vals_np):
        store.init(k, nd.zeros((4,), ctx=mx.xla(0)))
        outs.append([nd.zeros((4,), ctx=mx.xla(0))])
    values = [[nd.array(v, ctx=mx.xla(0))] for v in vals_np]
    stats = store.pushpull(list(range(3)), values, out=outs)
    assert stats == {"buckets": 0, "dispatches": 0}
    for v, o in zip(vals_np, outs):
        np.testing.assert_allclose(o[0].asnumpy(), v, rtol=1e-6)


def test_pushpull_preserves_per_key_store_context():
    """Keys bucketed together may have canonical store buffers on
    DIFFERENT devices: the fused writeback must land each on its own
    store context (like the sequential path), not the bucket anchor."""
    store = mx.kv.create("local")
    rng = np.random.RandomState(1)
    vals_np = [(rng.randn(6).astype(np.float32),
                rng.randn(6).astype(np.float32)) for _ in range(4)]
    outs = []
    for k in range(4):
        store.init(k, nd.zeros((6,), ctx=mx.xla(k % 2)))
        outs.append([nd.zeros((6,), ctx=mx.xla(0)),
                     nd.zeros((6,), ctx=mx.xla(0))])
    values = [[nd.array(v0, ctx=mx.xla(0)), nd.array(v1, ctx=mx.xla(0))]
              for v0, v1 in vals_np]
    stats = store.pushpull(list(range(4)), values, out=outs)
    assert stats is not None and stats["buckets"] >= 1
    for k, (v0, v1) in enumerate(vals_np):
        held = store._store[k]
        assert held.context == mx.xla(k % 2)
        assert next(iter(held._data.devices())) == \
            mx.xla(k % 2).jax_device()
        np.testing.assert_allclose(held.asnumpy(), v0 + v1, rtol=1e-6)
        np.testing.assert_allclose(outs[k][0].asnumpy(), v0 + v1,
                                   rtol=1e-6)


def test_fused_step_in_real_training_loop():
    """End-to-end: hybridized net + autograd grads, fused vs sequential
    trainers converge to bit-identical weights."""
    def run(agg):
        mx.random.seed(3)
        np.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, in_units=8, activation="relu"),
                nn.Dense(4, in_units=16))
        net.initialize(mx.init.Xavier())
        net.hybridize()
        kwargs = {"learning_rate": 0.1, "momentum": 0.9}
        if agg is not None:
            kwargs["aggregate_num"] = agg
        tr = gluon.Trainer(net.collect_params(), "sgd", kwargs)
        x = nd.array(np.random.RandomState(5).rand(16, 8)
                     .astype(np.float32))
        for _ in range(4):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            tr.step(16)
        # fresh blocks get fresh auto-prefixes: compare by position
        return [p.data().asnumpy()
                for p in net.collect_params().values()]
    fused, seq = run(None), run(1)
    assert len(fused) == len(seq) == 4
    for a, b in zip(fused, seq):
        np.testing.assert_array_equal(a, b)
