"""Fault-tolerant serving: a routed replica pool with health-based
eviction, hedged retries, and zero-downtime rolling reload.

A :class:`Router` fronts N server replicas (:class:`~.server.ModelServer`
or :class:`~.decode.DecodeServer`) so the serving tier survives exactly
the failures the training tier already does (PR 5/13):

- **least-loaded dispatch** — each request goes to the replica with the
  lowest live load score: (queued + in-flight requests) weighted by the
  replica's EWMA service time, the same queue/compute attribution the
  per-request telemetry spans record (measure-then-decide, arXiv
  2008.01040 applied to load balancing).
- **deadline budget propagation** — the replica sees the REMAINING
  milliseconds of the caller's deadline, not the original figure: a
  request that burned 300 of its 500 ms on a failed first dispatch
  reaches the retry replica with ``deadline_ms=200``, so the pool never
  computes an answer whose caller has already given up.
- **classified retries** — a dispatch failure runs through
  ``resilience.classify``: ``transient`` (and a replica shut down
  mid-eviction) re-dispatches on a DIFFERENT replica under the seeded
  :class:`~..resilience.retry.RetryPolicy`; ``overloaded`` spills to the
  next-least-loaded replica WITHOUT burning retry budget and rejects
  when every replica is full (shed, don't hammer); ``deadline`` fails
  the request immediately (the budget is gone — retrying cannot help);
  anything fatal is forwarded unchanged.
- **tail-latency hedging** — a request dispatched with less than
  ``hedge_ms`` of budget remaining is sent to the TWO least-loaded
  replicas; the first result wins and the loser is cancelled.
- **health-based eviction** — a background prober sends one tiny
  request per replica per ``health_sec``; ``evict_after`` consecutive
  failures (probe or traffic) trip the circuit breaker: the replica
  leaves rotation, its queued/in-flight work fails over to survivors,
  and a warm spare from the factory joins ONLY after its full
  BucketSpec AOT warmup — an eviction/replacement cycle causes zero
  in-traffic compiles on surviving replicas.
- **per-tenant quota** — ``submit(tenant=)`` bounds each tenant's
  outstanding requests in front of the pool's bounded queues, so one
  chatty client cannot starve the rest.
- **rolling reload** — ``rolling_reload()`` takes one replica at a
  time out of rotation, drains it, hot-swaps weights via the server's
  ``reload_weights()``, and rejoins it: a checkpoint rollout drops
  zero requests and recompiles nothing (each request is served
  entirely by pre- or post-reload weights, never a mix).

Chaos coverage rides two cataloged fault points — ``serve.replica.submit``
(per dispatch attempt) and ``serve.replica.health`` (per probe) — so
replica death, stalls, and flapping are injectable and bit-replayable
through the PR-5 :class:`~..resilience.faults.FaultPlan` machinery.

Knobs (docs/ENV_VARS.md): ``MXTPU_ROUTER_HEALTH_SEC``,
``MXTPU_ROUTER_EVICT_AFTER``, ``MXTPU_ROUTER_HEDGE_MS``,
``MXTPU_ROUTER_TENANT_QUOTA``.
"""
from __future__ import annotations

import itertools
import queue as _queue_mod
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout

import numpy as np

from .. import engine
from ..base import MXNetError, getenv
from ..log import get_logger
from ..resilience.retry import RetryPolicy
from ..resilience.supervisor import classify
from ..telemetry import tracer as _tracer
from .batcher import (DeadlineExceededError, ServerClosedError,
                      ServerOverloadedError)
from .stats import ServerStats

logger = get_logger("mxnet_tpu.serve.router")


class TenantQuotaExceededError(ServerOverloadedError):
    """The tenant's outstanding-request quota is exhausted — shed load
    for THIS tenant; other tenants are unaffected."""


class NoHealthyReplicaError(ServerOverloadedError):
    """Every replica is out of rotation or full — shed load upstream
    (classified ``overloaded``, same as a full single-server queue)."""


#: the Router counter set (rides the same ServerStats machinery the
#: servers use; exported as mxtpu_router_* by telemetry.metrics)
ROUTER_COUNTERS = ("submitted", "served", "failed", "cancelled",
                   "rejected_quota", "rejected_overload",
                   "expired_deadline", "dispatched", "retries", "hedges",
                   "hedge_wins", "evictions", "replacements", "probes",
                   "probe_failures", "reloads")

# replica rotation states
HEALTHY = "healthy"        # in rotation
RELOADING = "reloading"    # out of rotation for a rolling reload leg
EVICTED = "evicted"        # circuit breaker tripped; being replaced


# ---------------------------------------------------------------------------
# window-scoped module counters: the profiler's `router` section
# (provider: profiler._router_counters; exported to /metrics as
# mxtpu_router_* gauges by the section collector)

_sec_lock = threading.Lock()
_sec = {"dispatched": 0, "retries": 0, "hedges": 0, "hedge_wins": 0,
        "evictions": 0, "replacements": 0, "probes": 0,
        "probe_failures": 0, "reloads": 0}


def _sec_bump(**deltas):
    with _sec_lock:
        for k, n in deltas.items():
            _sec[k] += n


def router_stats():
    """Window snapshot of the pool-level routing counters (aggregated
    across every Router in the process)."""
    with _sec_lock:
        return dict(_sec)


def reset_router_stats():
    with _sec_lock:
        for k in _sec:
            _sec[k] = 0


# ---------------------------------------------------------------------------


class Replica:
    """One pool member: a server plus its rotation state, circuit-
    breaker counter, and live load attribution."""

    def __init__(self, rid, server):
        self.id = int(rid)
        self.server = server
        self.state = HEALTHY
        self.consecutive_failures = 0
        self.dispatched = 0
        self.served = 0
        self.failed = 0
        self.ewma_ms = 0.0          # per-request service time estimate
        self.outstanding = {}       # inner future -> _PoolRequest

    def score(self):
        """Live load: pending work weighted by expected service time.
        A replica that is both deep-queued and slow scores worst."""
        return (self.server.pending() + 1) * max(self.ewma_ms, 0.1)

    def info(self):
        return {"state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "dispatched": self.dispatched, "served": self.served,
                "failed": self.failed,
                "pending": self.server.pending(),
                "ewma_ms": round(self.ewma_ms, 3)}


class _PoolRequest:
    """Router-side request state: the caller-facing future, the
    absolute deadline the per-dispatch budgets derive from, and the
    resolve-exactly-once flag hedged/retried dispatches race on."""

    __slots__ = ("example", "kwargs", "tenant", "future", "deadline",
                 "deadline_ms", "submit_t", "attempts", "retries",
                 "lock", "resolved", "inners", "trace_id", "sink")

    def __init__(self, example, tenant, deadline_ms, kwargs):
        self.example = example
        self.kwargs = kwargs
        self.tenant = tenant
        self.future = Future()
        self.submit_t = time.monotonic()
        self.deadline_ms = deadline_ms
        self.deadline = (self.submit_t + deadline_ms / 1e3
                         if deadline_ms is not None else None)
        self.attempts = 0
        self.retries = 0
        self.lock = threading.Lock()
        self.resolved = False
        self.inners = []
        self.trace_id = None
        self.sink = None    # PooledStreamHandle for submit_stream()

    def remaining_ms(self, now=None):
        """The budget a dispatch RIGHT NOW would propagate (None when
        the caller gave no deadline)."""
        if self.deadline is None:
            return None
        return (self.deadline - (now or time.monotonic())) * 1e3


_POOL_STREAM_DONE = object()   # attach-queue sentinel: outer resolved


class PooledStreamHandle:
    """The :meth:`Router.submit_stream` handle: a decode token iterator
    that fans through the pool.

    Iteration yields token ids the moment they land on whichever
    replica CURRENTLY owns the request.  When a replica dies mid-stream
    the router's classified-retry path re-dispatches the request and
    the next attach resumes the walk, skipping the prefix already
    yielded — greedy decode is deterministic across same-weight
    replicas, so the re-generated prefix is identical and the caller
    sees one gapless, duplicate-free token sequence.  :attr:`future`
    resolves with the full sequence exactly like ``DecodeHandle``'s.

    Each pooled stream reads only its OWN per-request queue (in-process
    handles) or demux lane (remote replicas), so a slow consumer never
    head-of-line-blocks other requests' tokens.
    """

    def __init__(self, future):
        self.future = future
        self._attached = _queue_mod.Queue()   # inner handles, in
        # dispatch order; _POOL_STREAM_DONE once the outer resolved
        self._inner = None
        self._skip = 0
        self._yielded = 0
        self._tail = None   # leftovers recovered from future.result()

    # router-internal -------------------------------------------------------

    def _attach(self, inner, replica_id):
        self._attached.put(inner)

    def _finalize(self, fut):
        self._attached.put(_POOL_STREAM_DONE)

    # iterator --------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._tail is not None:
                if self._tail:
                    self._yielded += 1
                    return self._tail.pop(0)
                raise StopIteration
            if self._inner is None:
                nxt = self._attached.get()
                if nxt is _POOL_STREAM_DONE:
                    # repeat-consumable, like DecodeHandle's sentinel
                    self._attached.put(_POOL_STREAM_DONE)
                    # future.result() re-raises the terminal error
                    # (incl. CancelledError) when the request failed;
                    # on success any tokens the inner walks missed
                    # (hedge winner raced us, connection died between
                    # the result and the last frame) drain as the tail
                    seq = self.future.result(timeout=0)
                    self._tail = [int(t) for t in seq[self._yielded:]]
                    continue
                self._inner = iter(nxt)
                self._skip = self._yielded
                continue
            try:
                tok = next(self._inner)
            except StopIteration:
                # clean inner finish: the outer future resolves off its
                # done-callback; loop to the sentinel/tail path
                self._inner = None
                continue
            except BaseException:  # noqa: BLE001 — the router already
                # classified it: a retryable failure re-dispatches (a
                # new attach arrives), a terminal one resolves the
                # outer future (the sentinel arrives); either way the
                # loop blocks on the attach queue, never on a dead
                # stream
                self._inner = None
                continue
            if self._skip > 0:
                self._skip -= 1
                continue
            self._yielded += 1
            return tok

    def result(self, timeout=None):
        """The full generated token sequence (np.int32 array)."""
        return self.future.result(timeout)

    def cancel(self):
        self.future.cancel()


class Router:
    """A replica pool fronting N servers behind one ``submit()`` edge.

    Parameters
    ----------
    factory : callable, optional
        ``factory(replica_id) -> server`` building one UNSTARTED
        replica (its own block instance + spec).  Used for the initial
        pool (with ``n_replicas``) and for warm spares after an
        eviction; without a factory an evicted replica is not replaced.
    n_replicas : int, optional
        Initial pool size built from ``factory``.
    servers : sequence, optional
        Pre-built (unstarted) servers instead of / in addition to the
        factory-built pool.
    retry : RetryPolicy, optional
        Seeded policy bounding per-request re-dispatches (default:
        ``RetryPolicy(max_retries=2, base_delay=0.01, max_delay=0.25)``).
    evict_after : int
        Consecutive failures (traffic or probe) that trip the circuit
        breaker (``MXTPU_ROUTER_EVICT_AFTER``, default 3).
    health_sec : float
        Probe period; 0 disables probing
        (``MXTPU_ROUTER_HEALTH_SEC``, default 5).
    hedge_ms : float
        Hedge a dispatch whose remaining deadline budget is below this
        (``MXTPU_ROUTER_HEDGE_MS``, default 0 = off).
    tenant_quota : int
        Max outstanding requests per tenant; 0 disables
        (``MXTPU_ROUTER_TENANT_QUOTA``, default 0).
    probe_example / probe_kwargs :
        Health-probe payload; by default derived from the first
        replica's smallest bucket (``server.probe_example()``), with
        ``max_new_tokens=1`` added for decode replicas.
    """

    def __init__(self, factory=None, n_replicas=None, *, servers=None,
                 retry=None, evict_after=None, health_sec=None,
                 hedge_ms=None, tenant_quota=None, probe_example=None,
                 probe_kwargs=None):
        if factory is None and not servers:
            raise MXNetError(
                "Router needs replicas: pass factory= + n_replicas=, "
                "or servers=[...]")
        if factory is not None and n_replicas is None and not servers:
            raise MXNetError("factory= without n_replicas=: how many "
                             "replicas should the initial pool hold?")
        self._factory = factory
        self._retry = retry if retry is not None else RetryPolicy(
            max_retries=2, base_delay=0.01, max_delay=0.25)
        self._evict_after = int(getenv("ROUTER_EVICT_AFTER", 3, int)
                                if evict_after is None else evict_after)
        self._health_sec = float(getenv("ROUTER_HEALTH_SEC", 5.0, float)
                                 if health_sec is None else health_sec)
        self._hedge_ms = float(getenv("ROUTER_HEDGE_MS", 0.0, float)
                               if hedge_ms is None else hedge_ms)
        self._tenant_quota = int(getenv("ROUTER_TENANT_QUOTA", 0, int)
                                 if tenant_quota is None else tenant_quota)
        if self._evict_after < 1:
            raise MXNetError(
                f"evict_after must be >= 1, got {self._evict_after}")
        self._ids = itertools.count(0)   # per-router: replica ids (and
        # therefore fault-plan match={"replica": N} targeting) are
        # deterministic regardless of other routers in the process
        self._lock = threading.RLock()   # pool membership + states +
        # tenant counts; OUTERMOST — never acquired from code running
        # under a server/batcher/stats lock
        self._pool = []
        for srv in (servers or ()):
            self._pool.append(Replica(next(self._ids), srv))
        missing = int(n_replicas or 0) - len(self._pool)
        if missing > 0 and factory is None:
            raise MXNetError(
                f"n_replicas={n_replicas} but only {len(self._pool)} "
                "server(s) were given and there is no factory= to "
                "build the rest")
        for _ in range(max(missing, 0)):
            rid = next(self._ids)
            self._pool.append(Replica(rid, factory(rid)))
        self._stats = ServerStats(counters=ROUTER_COUNTERS)
        self._tenants = {}
        self._outstanding = set()
        self._started = False
        self._closing = False    # no NEW submits (drain or shutdown)
        self._aborting = False   # abrupt shutdown: stop re-dispatching
        self._health_stop = None
        self._health_thread = None
        self._metrics_collector = None
        self._probe_example = probe_example
        self._probe_kwargs = dict(probe_kwargs or {})
        self.last_recovery_ms = None    # evict -> warm spare admitted

    # -- lifecycle ----------------------------------------------------------

    def start(self):
        """Start (and AOT-warm) every replica, then the health prober.
        Each replica's full bucket grid compiles during ITS start(), so
        steady pool traffic — including traffic during a later
        eviction/replacement cycle — never compiles."""
        if self._started:
            raise MXNetError("Router already started")
        self._closing = False
        self._aborting = False
        for rep in self._pool:
            rep.server.start()
        if self._probe_example is None and self._pool:
            self._probe_example = self._pool[0].server.probe_example()
        if not self._probe_kwargs and self._pool and \
                hasattr(self._pool[0].server, "generate"):
            # decode replicas: one token proves the whole loop is live
            self._probe_kwargs = {"max_new_tokens": 1}
        self._started = True
        if self._metrics_collector is None:
            from ..telemetry import metrics as _metrics

            self._metrics_collector = _metrics.register_router(self)
        if self._health_sec > 0:
            self._health_stop = threading.Event()
            self._health_thread = threading.Thread(
                target=self._health_loop, args=(self._health_stop,),
                name="mxtpu-router-health", daemon=True)
            self._health_thread.start()
        return self

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc == (None, None, None))
        return False

    def _stop_health(self):
        if self._health_stop is not None:
            self._health_stop.set()
            self._health_thread.join(timeout=2 * max(self._health_sec, 1))
            self._health_stop = self._health_thread = None

    def drain(self, timeout=None):
        """Stop admissions, wait for every outstanding request to
        resolve (re-dispatches included), then drain each replica —
        ``timeout`` bounds the WHOLE drain (the replica drains get the
        remaining budget, not the original figure again)."""
        self._closing = True
        self._stop_health()
        deadline = (time.monotonic() + timeout) if timeout else None
        while self._outstanding:
            if deadline is not None and time.monotonic() > deadline:
                raise MXNetError(
                    f"router drain timed out with "
                    f"{len(self._outstanding)} request(s) outstanding")
            time.sleep(0.005)
        with self._lock:
            reps = [r for r in self._pool if r.state != EVICTED]
        for rep in reps:
            rep.server.drain(
                max(deadline - time.monotonic(), 0.001)
                if deadline is not None else None)
        self._started = False

    def shutdown(self, drain=True, timeout=None):
        if not self._started:
            return
        if drain:
            self.drain(timeout)
            return
        self._closing = True
        self._aborting = True
        self._stop_health()
        with self._lock:
            reps = [r for r in self._pool if r.state != EVICTED]
        for rep in reps:
            try:
                rep.server.shutdown(drain=False, timeout=timeout or 2.0)
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                logger.warning("replica %d shutdown failed: %s",
                               rep.id, e)
        # anything still unresolved (e.g. callbacks raced the close)
        for rreq in list(self._outstanding):
            self._resolve_exc(rreq, ServerClosedError(
                "router shut down"), "failed", outcome="cancelled")
        self._started = False

    # -- request path -------------------------------------------------------

    def submit(self, example, deadline_ms=None, tenant=None, **kwargs):
        """Admit one request into the pool; returns a Future.

        Raises :class:`TenantQuotaExceededError` when ``tenant``'s
        outstanding quota is exhausted (admission control in FRONT of
        the replicas' bounded queues).  Every dispatch-level failure —
        replica full, replica dead, budget exhausted — resolves the
        FUTURE with a classified error instead; an admitted request is
        never silently lost.  Extra kwargs (e.g. ``max_new_tokens`` for
        decode pools) pass through to the replica's ``submit()``.
        """
        return self._admit(example, deadline_ms, tenant, kwargs).future

    def submit_stream(self, example, deadline_ms=None, tenant=None,
                      **kwargs):
        """Pooled streaming decode: like :meth:`submit` against a
        decode-replica pool, but returns a :class:`PooledStreamHandle`
        whose iterator yields tokens as they land — multiplexed
        per-request, surviving mid-stream replica loss via the same
        classified re-dispatch path (the re-attached stream skips the
        already-yielded prefix).  Admission control (quota, closing)
        is identical to ``submit``."""
        rreq = self._admit(example, deadline_ms, tenant, kwargs,
                           stream=True)
        return rreq.sink

    def _admit(self, example, deadline_ms, tenant, kwargs,
               stream=False):
        if not self._started or self._closing:
            raise ServerClosedError(
                "Router is not accepting requests (not started, "
                "draining, or shut down)")
        if self._tenant_quota > 0 and tenant is not None:
            with self._lock:
                n = self._tenants.get(tenant, 0)
                if n >= self._tenant_quota:
                    self._stats.incr("rejected_quota")
                    raise TenantQuotaExceededError(
                        f"tenant {tenant!r} has {n} outstanding "
                        f"request(s), at its quota of "
                        f"{self._tenant_quota}; retry after one "
                        "resolves or raise MXTPU_ROUTER_TENANT_QUOTA")
                self._tenants[tenant] = n + 1
        rreq = _PoolRequest(example, tenant, deadline_ms, kwargs)
        if stream:
            rreq.sink = PooledStreamHandle(rreq.future)
            rreq.future.add_done_callback(rreq.sink._finalize)
        rreq.trace_id = _tracer.request_begin(
            "serve.router.request", cat="serve",
            deadline_ms=deadline_ms if deadline_ms is not None else -1,
            tenant=str(tenant) if tenant is not None else "")
        self._stats.incr("submitted")
        self._outstanding.add(rreq)
        rreq.future.add_done_callback(
            lambda f, r=rreq: self._on_outer_done(r, f))
        self._dispatch(rreq, exclude=frozenset())
        return rreq

    def predict(self, example, deadline_ms=None, timeout=None,
                tenant=None, **kwargs):
        """Synchronous wrapper; like ``ModelServer.predict`` the
        caller-side wait derives its default bound from the deadline
        and an expiry cancels the pooled request."""
        from .server import PREDICT_GRACE_S

        fut = self.submit(example, deadline_ms=deadline_ms,
                          tenant=tenant, **kwargs)
        if timeout is None and deadline_ms is not None:
            timeout = deadline_ms / 1e3 + PREDICT_GRACE_S
        try:
            return fut.result(timeout)
        except _FutureTimeout:
            fut.cancel()
            raise

    # -- dispatch -----------------------------------------------------------

    def _pick(self, skip):
        """Least-loaded healthy replica not in ``skip`` (ties go to the
        least-dispatched, so an idle pool round-robins)."""
        with self._lock:
            cands = [r for r in self._pool
                     if r.state == HEALTHY and r.id not in skip]
        if not cands:
            return None
        # scores read the servers' live queue gauges OUTSIDE the pool
        # lock (one-directional router->batcher lock order)
        return min(cands, key=lambda r: (r.score(), r.dispatched, r.id))

    def _dispatch(self, rreq, exclude):
        """Place ``rreq`` on a replica; spills across replicas on
        overload and resolves the request with a classified error when
        no placement is possible."""
        skip = set(exclude)
        while True:
            if rreq.resolved:
                return
            if self._aborting:
                # abrupt shutdown only — a graceful drain() keeps
                # re-dispatching so every outstanding request resolves
                self._resolve_exc(rreq, ServerClosedError(
                    "router shut down while the request was being "
                    "re-dispatched"), "failed", outcome="cancelled")
                return
            remaining = rreq.remaining_ms()
            if remaining is not None and remaining <= 0:
                self._resolve_exc(rreq, DeadlineExceededError(
                    f"deadline budget exhausted after {rreq.attempts} "
                    f"dispatch attempt(s) ({rreq.retries} retries) — "
                    f"original deadline_ms={rreq.deadline_ms}"),
                    "expired_deadline", outcome="expired")
                return
            replica = self._pick(skip)
            if replica is None:
                self._resolve_exc(rreq, NoHealthyReplicaError(
                    f"no healthy replica can take the request "
                    f"(pool={len(self._pool)}, tried "
                    f"{sorted(skip) if skip else 'none'}); shed load "
                    "upstream or grow the pool"),
                    "rejected_overload", outcome="rejected")
                return
            try:
                self._dispatch_to(rreq, replica, remaining)
            except ServerOverloadedError:
                # this replica's queue is full: spill to the next
                # least-loaded one — admission pressure, not sickness,
                # so no health penalty and no retry-budget burn
                skip.add(replica.id)
                continue
            except Exception as e:  # noqa: BLE001 — classified below
                kind = classify(e)
                if self._retryable(e, kind):
                    self._note_failure(replica)
                    if self._claim_retry(rreq):
                        self._redispatch_later(rreq, {replica.id})
                    else:
                        self._resolve_exc(rreq, MXNetError(
                            f"request failed on {rreq.attempts} "
                            f"replica(s), retry budget exhausted "
                            f"(max_retries="
                            f"{self._retry.max_retries}): {e}"),
                            "failed", outcome="failed")
                    return
                self._resolve_exc(rreq, e, "failed", outcome="failed")
                return
            # hedging: near-deadline requests get a second runner
            if (self._hedge_ms > 0 and remaining is not None
                    and remaining <= self._hedge_ms
                    and not rreq.retries):
                second = self._pick(skip | {replica.id})
                if second is not None:
                    try:
                        self._dispatch_to(rreq, second,
                                          rreq.remaining_ms(),
                                          hedge=True)
                        self._stats.incr("hedges")
                        _sec_bump(hedges=1)
                    except Exception:  # noqa: BLE001 — a failed hedge
                        # never hurts the primary dispatch
                        pass
            return

    def _dispatch_to(self, rreq, replica, remaining_ms, hedge=False):
        rreq.attempts += 1
        attempt = rreq.attempts
        engine.fault_point("serve.replica.submit", replica=replica.id,
                           attempt=attempt)
        t0 = time.monotonic()
        inner = replica.server.submit(rreq.example,
                                      deadline_ms=remaining_ms,
                                      **rreq.kwargs)
        fut = getattr(inner, "future", inner)
        if rreq.sink is not None and inner is not fut:
            # streaming dispatch: hand the (decode) handle to the
            # pooled stream — tokens start flowing before the future
            rreq.sink._attach(inner, replica.id)
        with self._lock:
            replica.outstanding[fut] = rreq
            replica.dispatched += 1
        with rreq.lock:
            rreq.inners.append(fut)
        self._stats.incr("dispatched")
        _sec_bump(dispatched=1)
        _tracer.request_instant(
            "serve.router.dispatch", rreq.trace_id, cat="serve",
            replica=replica.id, attempt=attempt, hedge=hedge,
            remaining_ms=round(remaining_ms, 3)
            if remaining_ms is not None else -1)
        fut.add_done_callback(
            lambda f: self._on_inner_done(rreq, replica, f, t0, hedge))

    @staticmethod
    def _retryable(exc, kind):
        # transient = the classifier's call; `network` (a dropped RPC
        # connection to a cross-process replica) re-dispatches for the
        # same reason, and a replica closing under a concurrent
        # eviction is equally re-dispatchable.  `overloaded` and
        # `deadline` are deliberately NOT here: overload spills or
        # sheds (no backoff-hammering an overloaded pool), an exhausted
        # budget cannot be retried into existence.
        return (kind in ("transient", "network")
                or isinstance(exc, ServerClosedError))

    def _claim_retry(self, rreq):
        with rreq.lock:
            if rreq.resolved:
                return False
            rreq.retries += 1
            n = rreq.retries
        ok = self._retry.should_retry(n)
        if ok:
            # booked only when the re-dispatch will actually happen —
            # the claim that EXHAUSTS the budget is not a retry
            self._stats.incr("retries")
            _sec_bump(retries=1)
        return ok

    def _redispatch_later(self, rreq, exclude):
        delay = self._retry.delay_for(rreq.retries)
        if delay < 1e-3:
            self._dispatch(rreq, exclude)
            return
        t = threading.Timer(delay, self._dispatch, args=(rreq, exclude))
        t.daemon = True
        t.start()

    # -- inner-future resolution --------------------------------------------

    def _on_inner_done(self, rreq, replica, fut, t0, hedge):
        with self._lock:
            replica.outstanding.pop(fut, None)
        if fut.cancelled():
            return   # hedge loser / eviction failover — already handled
        exc = fut.exception()
        if exc is None:
            self._note_success(replica, (time.monotonic() - t0) * 1e3)
            self._resolve_result(rreq, fut.result(), replica, hedge)
            return
        kind = classify(exc)
        if kind == "deadline":
            # the propagated budget expired at the replica == the
            # caller's budget is gone; no replica can still help
            self._resolve_exc(rreq, exc, "expired_deadline",
                              outcome="expired")
        elif self._retryable(exc, kind):
            self._note_failure(replica)
            if self._aborting:
                self._resolve_exc(rreq, ServerClosedError(
                    "router shut down while the request was queued on "
                    f"replica {replica.id}"), "failed",
                    outcome="cancelled")
            elif self._claim_retry(rreq):
                self._redispatch_later(rreq, {replica.id})
            else:
                self._resolve_exc(rreq, MXNetError(
                    f"request failed on {rreq.attempts} replica(s), "
                    f"retry budget exhausted (max_retries="
                    f"{self._retry.max_retries}): {exc}"),
                    "failed", outcome="failed")
        else:
            # fatal (model bug, bad request): every replica would fail
            # identically — forward unchanged, no health penalty
            self._resolve_exc(rreq, exc, "failed", outcome="failed")

    def _claim_resolution(self, rreq):
        with rreq.lock:
            if rreq.resolved:
                return False
            rreq.resolved = True
            return True

    def _cancel_losers(self, rreq, winner=None):
        with rreq.lock:
            inners = list(rreq.inners)
        for f in inners:
            if f is not winner and not f.done():
                f.cancel()

    def _resolve_result(self, rreq, result, replica, hedge):
        if not self._claim_resolution(rreq):
            return
        self._cancel_losers(rreq, winner=None)
        delivered = rreq.future.set_running_or_notify_cancel()
        if delivered:
            rreq.future.set_result(result)
            self._stats.incr("served")
            self._stats.record_latency(
                (time.monotonic() - rreq.submit_t) * 1e3)
            if hedge:
                self._stats.incr("hedge_wins")
                _sec_bump(hedge_wins=1)
        else:
            # the caller cancelled between our claim and the delivery:
            # book it here — _on_outer_done lost the claim race
            self._stats.incr("cancelled")
        _tracer.request_end(
            "serve.router.request", rreq.trace_id, cat="serve",
            outcome="served" if delivered else "cancelled",
            replica=replica.id, attempts=rreq.attempts,
            retries=rreq.retries, hedged=hedge)

    def _resolve_exc(self, rreq, exc, counter, outcome):
        if not self._claim_resolution(rreq):
            return
        self._cancel_losers(rreq)
        if rreq.future.set_running_or_notify_cancel():
            rreq.future.set_exception(exc)
            self._stats.incr(counter)
        else:
            self._stats.incr("cancelled")
        _tracer.request_end(
            "serve.router.request", rreq.trace_id, cat="serve",
            outcome=outcome, attempts=rreq.attempts,
            retries=rreq.retries, error=str(exc)[:160])

    def _on_outer_done(self, rreq, fut):
        self._outstanding.discard(rreq)
        if rreq.tenant is not None and self._tenant_quota > 0:
            with self._lock:
                n = self._tenants.get(rreq.tenant, 0)
                if n <= 1:
                    self._tenants.pop(rreq.tenant, None)
                else:
                    self._tenants[rreq.tenant] = n - 1
        if fut.cancelled():
            # the CALLER gave up (predict timeout / explicit cancel):
            # stop the replicas computing a dead answer
            claimed = self._claim_resolution(rreq)
            self._cancel_losers(rreq)
            if claimed:
                self._stats.incr("cancelled")
                _tracer.request_end("serve.router.request",
                                    rreq.trace_id, cat="serve",
                                    outcome="cancelled",
                                    attempts=rreq.attempts,
                                    retries=rreq.retries)

    # -- health + eviction --------------------------------------------------

    def _note_success(self, replica, ms):
        with self._lock:
            replica.consecutive_failures = 0
            replica.served += 1
            if ms is not None:
                replica.ewma_ms = (0.8 * replica.ewma_ms + 0.2 * ms
                                   if replica.ewma_ms else ms)

    def _note_failure(self, replica):
        with self._lock:
            replica.consecutive_failures += 1
            replica.failed += 1
            trip = (replica.state == HEALTHY
                    and replica.consecutive_failures >= self._evict_after)
        if trip:
            self.evict(replica)

    def evict(self, replica):
        """Trip the circuit breaker: remove the replica from rotation,
        fail its queued/in-flight work over to survivors, and (with a
        factory) warm a spare that joins only after its full AOT
        warmup.  Idempotent per replica."""
        with self._lock:
            if replica.state == EVICTED:
                return
            replica.state = EVICTED
        self._stats.incr("evictions")
        _sec_bump(evictions=1)
        _tracer.instant("serve.router.evict", cat="serve",
                        replica=replica.id,
                        consecutive_failures=replica.consecutive_failures)
        logger.warning(
            "evicting replica %d after %d consecutive failure(s); "
            "queued work fails over to survivors%s", replica.id,
            replica.consecutive_failures,
            "" if self._factory is None
            else "; warming a replacement")
        # the replacement cycle runs off-thread: evict() may be called
        # from the sick replica's own worker thread (a future callback),
        # and shutting that server down joins the very thread
        threading.Thread(target=self._replace,
                         args=(replica, time.monotonic()),
                         name=f"mxtpu-router-replace-{replica.id}",
                         daemon=True).start()

    def _replace(self, old, t0):
        try:
            old.server.shutdown(drain=False, timeout=2.0)
        except Exception as e:  # noqa: BLE001 — a wedged server must
            # not block the replacement
            logger.warning("evicted replica %d shutdown failed: %s",
                           old.id, e)
        # failover: shutdown failed the QUEUED requests (their callbacks
        # re-dispatch); anything still outstanding is wedged in-flight —
        # claim and re-dispatch it here, racing the (possibly never
        # arriving) late completion via the resolve-once flag
        with self._lock:
            stuck = list(old.outstanding.items())
        for fut, rreq in stuck:
            fut.cancel()
            with self._lock:
                old.outstanding.pop(fut, None)
            if rreq.resolved:
                continue
            if self._claim_retry(rreq):
                self._redispatch_later(rreq, {old.id})
            else:
                self._resolve_exc(rreq, MXNetError(
                    f"replica {old.id} was evicted with the request "
                    f"in flight and the retry budget is exhausted "
                    f"(max_retries={self._retry.max_retries})"),
                    "failed", outcome="failed")
        if self._factory is None or self._closing:
            return
        rid = next(self._ids)
        try:
            srv = self._factory(rid)
            srv.start()   # FULL BucketSpec AOT warmup before admission
        except Exception as e:  # noqa: BLE001 — pool keeps serving at
            # reduced size; the operator sees it in healthy/pool_size
            logger.error("replacement replica %d failed to start: %s",
                         rid, e)
            return
        rep = Replica(rid, srv)
        with self._lock:
            if self._closing:
                admit = False
            else:
                self._pool.append(rep)
                admit = True
        if not admit:
            srv.shutdown(drain=False, timeout=2.0)
            return
        self.last_recovery_ms = round((time.monotonic() - t0) * 1e3, 3)
        self._stats.incr("replacements")
        _sec_bump(replacements=1)
        _tracer.instant("serve.router.admit", cat="serve", replica=rid,
                        recovery_ms=self.last_recovery_ms)
        logger.warning("replacement replica %d warmed and admitted "
                       "(%.0f ms after eviction)", rid,
                       self.last_recovery_ms)

    def _health_loop(self, stop):
        while not stop.wait(self._health_sec):
            with self._lock:
                reps = [r for r in self._pool if r.state == HEALTHY]
            for rep in reps:
                if stop.is_set() or self._closing:
                    return
                self._probe(rep)

    def _probe(self, replica):
        """One end-to-end health probe: a real (tiny) request through
        the replica's full submit->batch->compute->resolve path, so a
        wedged batcher or a dead device fails it, not just a dead
        process."""
        self._stats.incr("probes")
        _sec_bump(probes=1)
        budget_ms = max(self._health_sec, 0.25) * 1e3
        try:
            engine.fault_point("serve.replica.health", replica=replica.id)
            inner = replica.server.submit(self._probe_example,
                                          deadline_ms=budget_ms,
                                          **self._probe_kwargs)
            fut = getattr(inner, "future", inner)
            fut.result(timeout=budget_ms / 1e3)
            self._note_success(replica, None)
        except Exception as e:  # noqa: BLE001 — every probe failure is
            # a health datapoint, whatever its type
            self._stats.incr("probe_failures")
            _sec_bump(probe_failures=1)
            logger.warning("health probe failed on replica %d: %s",
                           replica.id, e)
            self._note_failure(replica)

    # -- pool scaling (the control plane's actuation primitives) ------------

    def admit(self, server=None):
        """Warm-admit ONE new replica into rotation — the scale-UP
        actuation path.  The replica is built from the factory when
        ``server`` is not given, and its full AOT-warming ``start()``
        runs BEFORE it joins the pool, so scaling up never serves a
        cold compile in traffic (same admission contract as the
        eviction path's warm spare).  Returns the new :class:`Replica`.
        """
        if not self._started:
            raise MXNetError("admit() needs a started Router")
        rid = next(self._ids)
        if server is None:
            if self._factory is None:
                raise MXNetError(
                    "admit() without server= needs a factory")
            server = self._factory(rid)
        server.start()
        rep = Replica(rid, server)
        with self._lock:
            ok = not self._closing
            if ok:
                self._pool.append(rep)
        if not ok:
            server.shutdown(drain=False, timeout=2.0)
            raise ServerClosedError(
                "router is draining/shut down; the admitted replica "
                "was discarded")
        _tracer.instant("serve.router.admit", cat="serve", replica=rid)
        logger.info("replica %d warmed and admitted (pool grows to %d)",
                    rid, len(self._pool))
        return rep

    def retire(self, replica=None, timeout=60.0):
        """Gracefully remove ONE replica from the pool — the scale-DOWN
        actuation path, riding the ``rolling_reload`` drain machinery:
        the replica (least-loaded healthy one by default) leaves
        rotation, its queued and in-flight work drains to completion,
        then it shuts down and drops from the pool.  Zero requests
        dropped; refuses to retire the last healthy replica.  Returns
        the retired replica's id."""
        with self._lock:
            cands = [r for r in self._pool if r.state == HEALTHY]
            if replica is not None:
                cands = [r for r in cands if r is replica
                         or r.id == replica]
        if not cands:
            raise MXNetError("retire(): no matching healthy replica")
        # score() reads the servers' live queue gauges OUTSIDE the pool
        # lock (one-directional router->batcher lock order, like _pick)
        rep = min(cands, key=lambda r: (r.score(), -r.id))
        with self._lock:
            healthy = sum(1 for r in self._pool if r.state == HEALTHY)
            if healthy <= 1:
                raise MXNetError(
                    "refusing to retire the last healthy replica — "
                    "shut the router down instead")
            if rep.state != HEALTHY:
                raise MXNetError(
                    f"replica {rep.id} left rotation while being "
                    "selected for retirement; retry")
            rep.state = RELOADING   # out of _pick, like a reload leg
        deadline = time.monotonic() + timeout
        try:
            while rep.server.pending() > 0 or rep.outstanding:
                if time.monotonic() > deadline:
                    raise MXNetError(
                        f"retire: replica {rep.id} did not drain "
                        f"within {timeout}s "
                        f"({rep.server.pending()} pending)")
                time.sleep(0.005)
        except Exception:
            with self._lock:   # put it back in rotation on failure
                if rep.state == RELOADING:
                    rep.state = HEALTHY
            raise
        try:
            rep.server.shutdown(
                drain=True,
                timeout=max(deadline - time.monotonic(), 1.0))
        except Exception as e:  # noqa: BLE001 — it is out of rotation
            # and drained; a noisy teardown must not undo the retire
            logger.warning("retired replica %d shutdown failed: %s",
                           rep.id, e)
        with self._lock:
            if rep in self._pool:
                self._pool.remove(rep)
        logger.info("replica %d drained and retired (pool shrinks "
                    "to %d)", rep.id, len(self._pool))
        return rep.id

    # -- rolling reload -----------------------------------------------------

    def rolling_reload(self, step=None, timeout=60.0):
        """Hot weight rollout with zero dropped requests: one replica
        at a time leaves rotation, drains its already-dispatched work,
        ``reload_weights(step)``s, and rejoins — the rest of the pool
        keeps serving throughout, and every request is served entirely
        by pre- or post-reload weights (a request never sees a mix:
        it runs on exactly one replica, whose reload is serialized
        against batch execution).  A single-replica pool reloads in
        place (the server's exec lock already guarantees no drops).
        Returns the per-replica reload metadata."""
        out = []
        with self._lock:
            targets = [r for r in self._pool if r.state == HEALTHY]
        for rep in targets:
            with self._lock:
                if rep.state != HEALTHY:
                    continue   # evicted while we were reloading others
                others = any(r is not rep and r.state == HEALTHY
                             for r in self._pool)
                if others:
                    rep.state = RELOADING
            try:
                if others:
                    deadline = time.monotonic() + timeout
                    while rep.server.pending() > 0 or rep.outstanding:
                        if time.monotonic() > deadline:
                            raise MXNetError(
                                f"rolling reload: replica {rep.id} did "
                                f"not drain within {timeout}s "
                                f"({rep.server.pending()} pending)")
                        time.sleep(0.005)
                meta = rep.server.reload_weights(step)
            finally:
                with self._lock:
                    if rep.state == RELOADING:
                        rep.state = HEALTHY
            self._stats.incr("reloads")
            _sec_bump(reloads=1)
            _tracer.instant("serve.router.reload", cat="serve",
                            replica=rep.id, step=meta.get("step", -1))
            out.append(dict(meta, replica=rep.id))
        return out

    # -- observability ------------------------------------------------------

    def stats(self, reset=False):
        """Pool snapshot: routing counters, router-level latency
        percentiles, per-replica health/attribution, and the
        ``requests_lost`` audit (submitted minus every accounted
        outcome minus still-outstanding — 0 unless a request fell
        through an unhandled hole; exact when quiescent, like
        ``ModelServer.stats``).  ``reset=True`` window-scopes the
        counters exactly like the servers' ``stats(reset=True)``."""
        with self._lock:
            replicas = {r.id: r.info() for r in self._pool}
            healthy = sum(1 for r in self._pool if r.state == HEALTHY)
            pool_size = sum(1 for r in self._pool if r.state != EVICTED)
            pending = sum(r.server.pending() for r in self._pool
                          if r.state != EVICTED)
        outstanding = len(self._outstanding)
        snap = self._stats.snapshot(queue_depth=pending,
                                    in_flight=outstanding, reset=reset)
        snap["requests_lost"] = (
            snap["submitted"] - snap["served"] - snap["failed"]
            - snap["rejected_overload"] - snap["expired_deadline"]
            - snap["cancelled"] - outstanding)
        snap["pool_size"] = pool_size
        snap["healthy"] = healthy
        snap["last_recovery_ms"] = self.last_recovery_ms
        snap["replicas"] = replicas
        return snap

    @property
    def replicas(self):
        """Current pool members (evicted ones drop out)."""
        with self._lock:
            return [r for r in self._pool if r.state != EVICTED]


#: the pool-management reading of the same object (docs/serving.md)
ReplicaPool = Router
