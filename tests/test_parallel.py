"""SPMD parallelism tests on the virtual 8-device CPU mesh
(ref: tests/python/gpu/test_kvstore_gpu.py + nightly dist tests — the
modern analogue per SURVEY §4)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import data_parallel, mesh as mesh_mod


def test_make_mesh():
    m = mesh_mod.make_mesh()
    assert m.shape["dp"] == 8
    m2 = mesh_mod.make_mesh({"dp": 4, "tp": 2})
    assert m2.shape == {"dp": 4, "tp": 2}


def test_trainer_two_level_dcn_mesh_matches_flat_dp():
    """A {'dcn': 2, 'dp': 4} two-level mesh (the pod shape: DCN outer,
    ICI inner) must reproduce the flat {'dp': 8} losses step for step —
    the single-process half of VERDICT r3 #5 (the 2-process form runs
    in tests/test_dist_nightly.py::test_dist_hierarchical_dcn_x_ici)."""
    rng = np.random.RandomState(0)
    X = rng.rand(16, 20).astype(np.float32)
    Y = rng.randint(0, 10, 16).astype(np.float32)

    def run(mesh_shape):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
        net.initialize(mx.init.Xavier())
        tr = data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1},
            mesh=mesh_mod.make_mesh(mesh_shape))
        return [float(tr.step(X, Y).asnumpy()) for _ in range(4)]

    flat = run({"dp": 8})
    hier = run({"dcn": 2, "dp": 4})
    assert np.allclose(flat, hier, atol=1e-5), (flat, hier)
    assert flat[-1] < flat[0]  # actually training


def test_spmd_trainer_converges():
    np.random.seed(3)
    mx.random.seed(3)
    n, d = 512, 16
    X = np.random.rand(n, d).astype(np.float32)
    w_true = np.random.rand(d, 1).astype(np.float32)
    Y = (X @ w_true > w_true.sum() / 2).astype(np.float32).ravel()

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    trainer = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.01})

    losses = []
    bs = 64
    for epoch in range(30):
        for i in range(0, n, bs):
            loss = trainer.step(X[i:i + bs], Y[i:i + bs])
        losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]

    # sync back and check eager predictions agree with training
    trainer.sync_to_block()
    pred = net(nd.array(X)).asnumpy().argmax(1)
    assert (pred == Y).mean() > 0.9


def test_spmd_matches_single_device_math():
    """DP over 8 devices must equal single-device SGD step (allreduce
    correctness — the dist_sync_kvstore.py N-worker assertion)."""
    np.random.seed(0)
    X = np.random.rand(16, 4).astype(np.float32)
    Y = np.random.randint(0, 2, 16).astype(np.float32)

    def make_net(seed):
        np.random.seed(seed)
        net = nn.Dense(2, in_units=4)
        net.initialize(mx.init.Xavier())
        return net

    net_a = make_net(7)
    w0 = net_a.weight.data().asnumpy().copy()
    b0 = net_a.bias.data().asnumpy().copy()

    tr = data_parallel.DataParallelTrainer(
        net_a, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.5})
    tr.step(X, Y)
    tr.sync_to_block()
    w_spmd = net_a.weight.data().asnumpy()

    # reference: eager single-device on same initial weights
    net_b = nn.Dense(2, in_units=4)
    net_b.initialize()
    net_b.weight.set_data(nd.array(w0))
    net_b.bias.set_data(nd.array(b0))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer_b = gluon.Trainer(net_b.collect_params(), "sgd",
                              {"learning_rate": 0.5})
    with autograd.record():
        loss = loss_fn(net_b(nd.array(X)), nd.array(Y))
        # DataParallelTrainer optimizes mean loss; Trainer.step(bs)
        # rescales sum-of-grads by 1/bs — same thing for mean loss with
        # batch_size = number of rows when loss already averages:
        total = loss.mean()
    total.backward()
    trainer_b.step(1)
    w_eager = net_b.weight.data().asnumpy()
    assert np.allclose(w_spmd, w_eager, atol=1e-4), (w_spmd, w_eager)


def test_spmd_batchnorm_stats_update():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    tr = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1})
    X = np.random.rand(32, 4).astype(np.float32) + 3.0
    Y = np.random.randint(0, 2, 32).astype(np.float32)
    for _ in range(3):
        tr.step(X, Y)
    tr.sync_to_block()
    bn = net[1]
    assert not np.allclose(bn.running_mean.data().asnumpy(), 0.0), \
        "BN moving stats must update through the compiled SPMD step"


def test_spmd_tp_sharded_params():
    m = mesh_mod.make_mesh({"dp": 4, "tp": 2})
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(2))
    net.initialize()
    tr = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=m, shard_params=True)
    X = np.random.rand(16, 8).astype(np.float32)
    Y = np.random.randint(0, 2, 16).astype(np.float32)
    l0 = float(tr.step(X, Y).asscalar())
    l1 = float(tr.step(X, Y).asscalar())
    assert np.isfinite(l0) and np.isfinite(l1)
    # the big Dense weight must actually be sharded over tp
    big = [r for r in tr._params if r.shape == (64, 8)][0]
    assert len(big.sharding.device_set) >= 2


def test_spmd_zero_sharded_opt_states():
    """shard_opt_states=True: Adam m/v live dp-sharded (ZeRO-1) and the
    loss trajectory matches the replicated-state trainer exactly."""
    def run(shard):
        np.random.seed(5)
        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(64, activation="relu"), nn.Dense(2))
        net.initialize(mx.init.Xavier())
        trainer = data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.05}, shard_opt_states=shard)
        X = np.random.RandomState(0).rand(64, 16).astype(np.float32)
        Y = (X.sum(1) > 8).astype(np.float32)
        losses = [float(trainer.step(X, Y).asscalar()) for _ in range(8)]
        return trainer, losses

    t_sharded, l_sharded = run(True)
    t_repl, l_repl = run(False)
    np.testing.assert_allclose(l_sharded, l_repl, rtol=1e-4)

    # the big states must actually be partitioned over dp
    dp = t_sharded.mesh.shape["dp"]
    assert dp > 1
    found_sharded = False
    for st in t_sharded._states:
        if st is None:
            continue
        m, v = st
        if any(d % dp == 0 and d >= dp for d in m.shape):
            assert "dp" in tuple(m.sharding.spec), m.sharding
            nshards = len({s.device for s in m.addressable_shards})
            assert nshards == dp
            found_sharded = True
    assert found_sharded
    for st in t_repl._states:
        if st is not None:
            assert tuple(st[0].sharding.spec) in ((), (None,), (None, None))


def test_spmd_checkpoint_resume(tmp_path):
    """Kill-and-resume: save sharded params+opt state mid-training,
    rebuild a fresh trainer, load, and reproduce the exact loss
    trajectory of uninterrupted training (VERDICT §Next 6)."""
    X = np.random.RandomState(7).rand(64, 16).astype(np.float32)
    Y = (X.sum(1) > 8).astype(np.float32)

    def fresh():
        from mxnet_tpu.gluon.block import _BlockScope

        # a resumed PROCESS restarts auto-prefix counters at zero; do the
        # same here so checkpoint param names line up across instances
        _BlockScope._counters.clear()
        np.random.seed(9)
        mx.random.seed(9)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
        net.initialize(mx.init.Xavier())
        return data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.05}, shard_opt_states=True)

    # uninterrupted run: 8 steps
    t0 = fresh()
    ref = [float(t0.step(X, Y).asscalar()) for _ in range(8)]

    # interrupted run: 5 steps, checkpoint, "crash", resume, 3 steps
    t1 = fresh()
    part1 = [float(t1.step(X, Y).asscalar()) for _ in range(5)]
    prefix = str(tmp_path / "ckpt")
    t1.save_states(prefix)
    del t1

    t2 = fresh()           # new process stand-in: fresh params
    t2.build(X)
    t2.load_states(prefix)
    assert t2._t == 5
    part2 = [float(t2.step(X, Y).asscalar()) for _ in range(3)]
    np.testing.assert_allclose(part1 + part2, ref, rtol=1e-5)

    # opt-state sharding survives the round trip
    for st in t2._states:
        if st is not None and any(d % 8 == 0 and d >= 8
                                  for d in st[0].shape):
            assert "dp" in tuple(st[0].sharding.spec)

    # mesh-mismatch guard
    import jax as _jax
    from mxnet_tpu.parallel import mesh as mesh_mod

    small = mesh_mod.make_mesh({"dp": 2}, devices=_jax.devices()[:2])
    t3 = data_parallel.DataParallelTrainer(
        fresh().block, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
        {"learning_rate": 0.05}, mesh=small)
    t3.build(X)
    with pytest.raises(mx.MXNetError):
        t3.load_states(prefix)


def test_gradient_compression_2bit():
    """2-bit threshold quantization with error feedback
    (ref: tests/nightly/dist_sync_kvstore.py --gc-type 2bit)."""
    import numpy as np

    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", nd.zeros((4,)))
    g = nd.array(np.array([0.3, 0.7, -0.9, 0.0], np.float32))
    kv.push("w", [g])
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [0, 0.5, -0.5, 0])
    # error feedback: accumulated residual pushes 0.3+0.3 over threshold
    kv.push("w", [g])
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.5, -0.5, 0])
    # per-slot residuals are independent
    assert len(kv._compression._residuals) == 1
    assert kv._compression.get_params()["threshold"] == 0.5


def test_gradient_compression_validation():
    kv = mx.kv.create("device")
    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "1bit"})
    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "2bit", "threshold": -1})
    kv.set_gradient_compression({"type": "none"})
    assert kv._compression is None


def test_spmd_remat_matches_exact():
    """remat=True must change only the memory/FLOP schedule, not the
    math: identical loss trajectory and final params vs remat=False."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import data_parallel, mesh as mesh_mod

    def build(remat):
        mx.random.seed(11)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(4))
        net.initialize(mx.init.Xavier())
        mesh = mesh_mod.make_mesh({"dp": 2}, devices=jax.devices()[:2])
        return net, data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=mesh, remat=remat)

    rng = np.random.RandomState(0)
    x = rng.rand(16, 10).astype(np.float32)
    y = rng.randint(0, 4, 16).astype(np.float32)
    losses = {}
    params = {}
    for remat in (False, True):
        _, tr = build(remat)
        losses[remat] = [float(tr.step(x, y).asscalar()) for _ in range(5)]
        params[remat] = [np.asarray(p) for p in tr._params]
    assert np.allclose(losses[False], losses[True], atol=1e-6), losses
    for a, b in zip(params[False], params[True]):
        assert np.allclose(a, b, atol=1e-6)
    assert losses[True][-1] < losses[True][0]


def test_step_many_matches_stepwise():
    """step_many(K) is ONE XLA computation (lax.scan bulk execution,
    ref: MXNET_EXEC_BULK_EXEC_TRAIN) and must reproduce K individual
    step() calls exactly — same PRNG key sequence, same optimizer-state
    trajectory."""

    def build():
        mx.random.seed(11)
        np.random.seed(11)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
        net.initialize(mx.init.Xavier())
        return data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.01})

    rng = np.random.RandomState(5)
    K, bs, d = 4, 16, 8
    xs = rng.rand(K, bs, d).astype(np.float32)
    ys = rng.randint(0, 3, (K, bs)).astype(np.float32)

    tr_a = build()
    losses_a = [float(tr_a.step(xs[i], ys[i]).asscalar())
                for i in range(K)]

    # stacked mode: one minibatch per scanned step
    tr_b = build()
    losses_b = tr_b.step_many(xs, ys).asnumpy()
    assert np.allclose(losses_a, losses_b, atol=1e-6), (losses_a, losses_b)
    for a, b in zip(tr_a._params, tr_b._params):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert tr_a._t == tr_b._t == K

    # repeat mode: same batch K times == K step() calls on that batch
    tr_c = build()
    losses_c1 = [float(tr_c.step(xs[0], ys[0]).asscalar())
                 for i in range(K)]
    tr_d = build()
    losses_c2 = tr_d.step_many(xs[0], ys[0], n_steps=K).asnumpy()
    assert np.allclose(losses_c1, losses_c2, atol=1e-6)

    # interleaving with step() continues the same trajectory
    more_a = float(tr_a.step(xs[0], ys[0]).asscalar())
    more_b = float(tr_b.step(xs[0], ys[0]).asscalar())
    assert np.allclose(more_a, more_b, atol=1e-6)


def test_async_sharded_checkpoint(tmp_path):
    """async_save=True: the snapshot is immune to later donated steps
    (device buffers are invalidated) and the write completes on the
    host pool; the restored trajectory matches the synchronous save."""
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.block import _BlockScope
    from mxnet_tpu.parallel import data_parallel

    rng = np.random.RandomState(0)
    X = rng.rand(16, 6).astype(np.float32)
    Y = (X.sum(axis=1) > 3).astype(np.float32)

    def fresh():
        _BlockScope._counters.clear()
        np.random.seed(4)
        mx.random.seed(4)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
        net.initialize(mx.init.Xavier())
        return data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            {"learning_rate": 0.05})

    t1 = fresh()
    for _ in range(3):
        t1.step(X, Y)
    fut = t1.save_states(str(tmp_path / "async"), async_save=True)
    # keep training WHILE the write is in flight: donation must not
    # corrupt the snapshot
    after = [float(t1.step(X, Y).asscalar()) for _ in range(3)]
    fut.result()

    t2 = fresh()
    t2.build(X)
    t2.load_states(str(tmp_path / "async"))
    assert t2._t == 3
    resumed = [float(t2.step(X, Y).asscalar()) for _ in range(3)]
    np.testing.assert_allclose(resumed, after, rtol=1e-5)


def test_param_spec_fn_matched_nothing_raises():
    """An explicitly-passed param_spec_fn that places nothing is a
    misconfiguration (e.g. custom block prefix): loud error, not
    silent replication."""
    import pytest as _pytest

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import data_parallel, mesh as mesh_mod

    net = nn.Dense(4, in_units=4)
    net.initialize(mx.init.Xavier())
    mesh = mesh_mod.make_mesh({"dp": 2}, devices=__import__("jax")
                              .devices()[:2])
    tr = data_parallel.DataParallelTrainer(
        net, gluon.loss.L2Loss(), "sgd", {"learning_rate": 0.1},
        mesh=mesh, param_spec_fn=lambda name, shape: None)
    with _pytest.raises(mx.MXNetError, match="matched no parameters"):
        tr.step(np.ones((4, 4), np.float32), np.ones((4, 4), np.float32))


def test_zero_opt_states_stay_dp_sharded_with_tp_params():
    """shard_params=True (tp) + shard_opt_states=True (ZeRO): optimizer
    state keeps the dp placement — only param_spec_fn-placed params
    carry their own sharding into the state (review r3 find: the
    custom-spec override must not disable ZeRO for tp params)."""
    import jax

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import data_parallel, mesh as mesh_mod

    net = nn.Dense(32, in_units=64, use_bias=False)
    net.initialize(mx.init.Xavier())
    mesh = mesh_mod.make_mesh({"dp": 2, "tp": 2},
                              devices=jax.devices()[:4])
    tr = data_parallel.DataParallelTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 1e-3},
        mesh=mesh, shard_params=True, shard_opt_states=True)
    x = np.random.RandomState(0).rand(8, 64).astype(np.float32)
    tr.step(x, np.zeros((8, 32), np.float32))
    (m, v), = [s for s in tr._states if s is not None]
    mspec = str(m.sharding.spec)
    assert "dp" in mspec and "tp" not in mspec, mspec


def test_accum_steps_matches_full_batch():
    """Gradient accumulation (ref: grad_req='add' + Trainer.step on the
    accumulated batch): accum_steps=K scanning K micro-batches inside
    the compiled step must reproduce the full-batch trajectory exactly
    (equal micro sizes: mean-of-means == full mean)."""
    rng = np.random.RandomState(3)
    X = rng.rand(32, 12).astype(np.float32)
    Y = rng.randint(0, 4, 32).astype(np.float32)

    def run(accum):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize(mx.init.Xavier())
        tr = data_parallel.DataParallelTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1, "momentum": 0.9},
            accum_steps=accum)
        losses = [float(tr.step(X, Y).asnumpy()) for _ in range(4)]
        flat = np.concatenate([p.data().asnumpy().ravel()
                               for p in net.collect_params().values()])
        return losses, flat

    l1, p1 = run(1)
    l2, p2 = run(2)
    l4, p4 = run(4)
    assert np.allclose(l1, l2, atol=1e-5), (l1, l2)
    assert np.allclose(l1, l4, atol=1e-5), (l1, l4)
    assert np.allclose(p1, p2, atol=1e-5)
    assert np.allclose(p1, p4, atol=1e-5)
    assert l1[-1] < l1[0]


def test_accum_steps_indivisible_batch_raises():
    net = nn.Dense(4)
    net.initialize(mx.init.Xavier())
    tr = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, accum_steps=3)
    X = np.random.rand(8, 6).astype(np.float32)
    Y = np.zeros((8,), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        tr.step(X, Y)
