"""Convolution & pooling Gluon layers
(ref: python/mxnet/gluon/nn/conv_layers.py)."""
from __future__ import annotations

from ..block import HybridBlock


def _pair(x, n):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,) * n


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", op_name="Convolution",
                 adj=None, **kwargs):
        super().__init__(**kwargs)
        self._channels = channels
        self._in_channels = in_channels
        nd_ = len(kernel_size)
        self._op_name = op_name
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._activation = activation
        self._channel_last = bool(layout) and layout.endswith("C")
        cin = in_channels // groups if in_channels else 0
        if op_name == "Convolution":
            # channel-last layouts store weights OHWI (ref: convolution.cc
            # NHWC layout param; TPU-preferred — see ops/nn._conv_layouts)
            wshape = (channels,) + kernel_size + (cin,) \
                if self._channel_last else (channels, cin) + kernel_size
        else:  # Deconvolution: (in, out/groups, *k)
            wshape = (in_channels, channels // groups) + kernel_size
        self.weight = self.params.get(
            "weight", shape=wshape, init=weight_initializer,
            allow_deferred_init=True)
        if self._channel_last and cin:
            self._set_fan_hint(cin)
        self.bias = self.params.get(
            "bias", shape=(channels,), init=bias_initializer,
            allow_deferred_init=True) if use_bias else None

    def _set_fan_hint(self, c_in):
        """Exact fans for fan-based initializers: OHWI shapes are
        ambiguous (see initializer.InitDesc)."""
        import numpy as _np

        k = int(_np.prod(self._kwargs["kernel"]))
        self.weight._init_attrs = {
            "__init_fan__": (c_in * k, self._channels * k)}

    def infer_shape(self, x, *args):
        g = self._kwargs["num_group"]
        k = self._kwargs["kernel"]
        if self._op_name == "Convolution":
            if self._channel_last:
                c_in = x.shape[-1]
                self.weight.shape = (self._channels,) + tuple(k) \
                    + (c_in // g,)
                self._set_fan_hint(c_in // g)
            else:
                c_in = x.shape[1]
                self.weight.shape = (self._channels, c_in // g) + tuple(k)
        else:
            c_in = x.shape[1]
            self.weight.shape = (c_in, self._channels // g) + tuple(k)

    def hybrid_forward(self, F, x, weight, bias=None):
        op = getattr(F, self._op_name)
        out = op(x, weight, bias, **self._kwargs)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups,
                         layout, op_name="Deconvolution",
                         adj=_pair(output_padding, 2), **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups,
                         layout, op_name="Deconvolution",
                         adj=_pair(output_padding, 1), **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups,
                         layout, op_name="Deconvolution",
                         adj=_pair(output_padding, 3), **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, count_include_pad=None, layout=None, **kwargs):
        super().__init__(**kwargs)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "pool_type": pool_type, "global_pool": global_pool,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides, 1) if strides else None,
                         _pair(padding, 1), ceil_mode, False, "max", layout=layout, **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides, 2) if strides else None,
                         _pair(padding, 2), ceil_mode, False, "max", layout=layout, **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides, 3) if strides else None,
                         _pair(padding, 3), ceil_mode, False, "max", layout=layout, **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 1),
                         _pair(strides, 1) if strides else None,
                         _pair(padding, 1), ceil_mode, False, "avg",
                         count_include_pad, layout=layout, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 2),
                         _pair(strides, 2) if strides else None,
                         _pair(padding, 2), ceil_mode, False, "avg",
                         count_include_pad, layout=layout, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 **kwargs):
        super().__init__(_pair(pool_size, 3),
                         _pair(strides, 3) if strides else None,
                         _pair(padding, 3), ceil_mode, False, "avg",
                         count_include_pad, layout=layout, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "max", layout=layout, **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "max", layout=layout, **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "max", layout=layout,
                         **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), False, True, "avg", layout=layout, **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), False, True, "avg", layout=layout, **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), False, True, "avg", layout=layout,
                         **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        p = padding if isinstance(padding, (tuple, list)) else (padding,) * 4
        self._pad = (0, 0, 0, 0) + tuple(p)

    def hybrid_forward(self, F, x):
        return F.pad(x, mode="reflect", pad_width=self._pad)
