"""mxnet_tpu.serve — dynamic-batching inference serving.

Covers the subsystem's contract: concurrent submits coalesce into few
padded bucket batches whose per-request results are bit-close to the
unbatched forward; the bucket grid is the ENTIRE compile surface (a
warmed server serves a mixed-shape stream with zero new compilations —
the ISSUE acceptance demonstration); deadlines expire in the queue, a
full queue fails fast, drain leaves zero in-flight work, and hot reload
swaps checkpoint weights without dropping requests.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _imperative, checkpoint, serve
from mxnet_tpu.gluon import nn
from mxnet_tpu.serve.batcher import Batcher, _Request

FEAT = 6


def _make_net(seed=3, out_units=5):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, flatten=False, in_units=FEAT, activation="relu"),
            nn.Dense(out_units, flatten=False, in_units=8))
    net.initialize(mx.init.Xavier())
    return net


def _spec(batches=(1, 2, 4), lengths=(4, 8)):
    return serve.BucketSpec(batch_sizes=batches,
                            example_shape=(None, FEAT), lengths=lengths)


def _requests(n, rng, lengths=(2, 3, 4, 7, 8)):
    return [rng.rand(int(rng.choice(lengths)), FEAT).astype(np.float32)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# BucketSpec


def test_bucket_spec_geometry_and_validation():
    spec = _spec()
    assert spec.max_batch == 4
    assert len(spec.bucket_shapes()) == 6  # 3 batches x 2 lengths
    assert spec.pick(3, 5) == (4, 8)
    assert spec.pick(1, 1) == (1, 4)
    assert spec.pick(99, 8) == (4, 8)  # capped at max_batch
    assert spec.validate(np.zeros((3, FEAT))) == 3
    with pytest.raises(serve.BucketOverflowError):
        spec.validate(np.zeros((9, FEAT)))  # longer than every bucket
    with pytest.raises(mx.MXNetError):
        spec.validate(np.zeros((3, FEAT + 1)))  # fixed axis mismatch
    with pytest.raises(mx.MXNetError):
        spec.validate(np.zeros((3,)))  # rank mismatch


def test_bucket_pad_batch_layout():
    spec = _spec()
    a = np.ones((2, FEAT), np.float32)
    b = 2 * np.ones((4, FEAT), np.float32)
    out = spec.pad_batch([a, b], batch=4, length=8)
    assert out.shape == (4, 8, FEAT)
    np.testing.assert_array_equal(out[0, :2], a)
    np.testing.assert_array_equal(out[1, :4], b)
    assert (out[0, 2:] == 0).all() and (out[2:] == 0).all()  # dead rows


def test_fixed_shape_spec():
    spec = serve.BucketSpec(batch_sizes=(1, 2), example_shape=(3, 2))
    assert spec.validate(np.zeros((3, 2))) is None
    assert spec.pick(2, None) == (2, None)
    assert spec.bucket_shapes() == [(1, 3, 2), (2, 3, 2)]
    with pytest.raises(mx.MXNetError):
        serve.BucketSpec(batch_sizes=(1,), example_shape=(None, 2))  # no lengths
    with pytest.raises(mx.MXNetError):
        serve.BucketSpec(batch_sizes=(1,), example_shape=(3, 2),
                         lengths=(4,))  # lengths without a variable axis


# ---------------------------------------------------------------------------
# Batcher (unit level, no device work)


def _req(length=2, deadline_ms=None):
    from concurrent.futures import Future

    return _Request(np.zeros((length, FEAT), np.float32), length,
                    Future(), deadline_ms=deadline_ms)


def test_batcher_overload_and_close():
    b = Batcher(max_queue=2, linger_ms=0)
    b.put(_req())
    b.put(_req())
    with pytest.raises(serve.ServerOverloadedError):
        b.put(_req())
    b.close()
    with pytest.raises(serve.ServerClosedError):
        b.put(_req())
    group, expired = b.next_group(max_batch=4, timeout=0)
    assert len(group) == 2 and not expired
    assert b.drained()


def test_batcher_deadline_expiry_at_dequeue():
    b = Batcher(max_queue=8, linger_ms=0)
    b.put(_req(deadline_ms=1))
    b.put(_req(deadline_ms=10_000))
    time.sleep(0.02)
    group, expired = b.next_group(max_batch=4, timeout=0)
    assert len(group) == 1 and len(expired) == 1
    assert expired[0].deadline < time.monotonic()


# ---------------------------------------------------------------------------
# ModelServer


def test_padding_correctness_vs_unbatched_forward():
    """Bucket-padded batched results match the plain per-request
    forward: padded positions and dead rows never leak into real rows
    (per-position Dense net; attention-style cross-position models need
    masks, docs/serving.md)."""
    net = _make_net()
    rng = np.random.RandomState(0)
    reqs = _requests(12, rng)
    srv = serve.ModelServer(net, _spec(), max_queue=64, linger_ms=2.0)
    with srv:
        outs = [f.result(timeout=60)
                for f in [srv.submit(x) for x in reqs]]
    for x, o in zip(reqs, outs):
        assert o.shape == (x.shape[0], 5)
        ref = net(mx.nd.array(x[None])).asnumpy()[0]
        np.testing.assert_allclose(o, ref[:x.shape[0]],
                                   rtol=2e-5, atol=2e-5)


def test_batch_coalescing():
    """Concurrent submitters end up in shared padded batches — the
    whole point of the batcher thread."""
    srv = serve.ModelServer(_make_net(), _spec(), max_queue=64,
                            linger_ms=10.0)
    rng = np.random.RandomState(1)
    with srv:
        futs = [srv.submit(x) for x in _requests(16, rng)]
        for f in futs:
            f.result(timeout=60)
        s = srv.stats()
    assert s["served"] == 16
    assert s["batches"] < 16  # coalesced, not one batch per request
    assert s["batch_fill_ratio"] > 0.5
    assert set(s["bucket_hits"]) <= {
        srv._spec.key(b, l) for b in (1, 2, 4) for l in (4, 8)}


def test_zero_post_warmup_compiles_mixed_stream():
    """ISSUE acceptance: a warmed server takes >=100 requests across
    >=3 distinct lengths with ZERO new XLA compilations — by the
    CachedOp compile counters AND the global executable count."""
    srv = serve.ModelServer(_make_net(), _spec(), max_queue=256,
                            linger_ms=1.0)
    srv.start()
    warmed = srv.stats()["graph"]
    assert warmed["compiles"] == 6 and warmed["post_warmup_compiles"] == 0
    execs_before = _imperative.compiled_executable_count()
    rng = np.random.RandomState(2)
    reqs = _requests(120, rng, lengths=(2, 3, 5, 7, 8))
    assert len({r.shape[0] for r in reqs}) >= 3
    futs = [srv.submit(x) for x in reqs]
    for f in futs:
        f.result(timeout=120)
    srv.drain()
    s = srv.stats()
    assert s["served"] == 120
    assert s["graph"]["post_warmup_compiles"] == 0
    assert _imperative.compiled_executable_count() == execs_before
    assert s["graph"]["reuses"] >= s["batches"]


def _slow_hook(delay):
    def hook(_block, _args):
        time.sleep(delay)

    return hook


def test_deadline_expiry():
    net = _make_net()
    srv = serve.ModelServer(net, _spec(), max_queue=16, linger_ms=0.5)
    srv.start()
    handle = net.register_forward_pre_hook(_slow_hook(0.2))
    try:
        rng = np.random.RandomState(3)
        # first request occupies the worker ~200ms; the second's 20ms
        # deadline passes while it waits in the queue
        slow = srv.submit(rng.rand(4, FEAT).astype(np.float32))
        time.sleep(0.05)  # let the worker dequeue + start the slow batch
        doomed = srv.submit(rng.rand(4, FEAT).astype(np.float32),
                            deadline_ms=20)
        assert slow.result(timeout=60).shape == (4, 5)
        with pytest.raises(serve.DeadlineExceededError):
            doomed.result(timeout=60)
    finally:
        handle.detach()
        srv.drain()
    s = srv.stats()
    assert s["expired_deadline"] == 1
    assert s["submitted"] == s["served"] + s["expired_deadline"]


def test_overload_rejection():
    net = _make_net()
    srv = serve.ModelServer(net, _spec(), max_queue=2, linger_ms=0.5)
    srv.start()
    handle = net.register_forward_pre_hook(_slow_hook(0.1))
    try:
        rng = np.random.RandomState(4)
        futs, rejected = [], 0
        for _ in range(24):
            try:
                futs.append(srv.submit(rng.rand(4, FEAT)
                                       .astype(np.float32)))
            except serve.ServerOverloadedError:
                rejected += 1
        assert rejected > 0  # the bounded queue actually sheds load
        for f in futs:
            f.result(timeout=60)
    finally:
        handle.detach()
        srv.drain()
    s = srv.stats()
    assert s["rejected_overload"] == rejected
    assert s["served"] == s["submitted"] == 24 - rejected


def test_predict_timeout_voids_queued_request():
    """ISSUE 11 satellite: a caller-side predict(timeout=) expiry used
    to leave the request queued and still consuming a batch row when it
    finally dequeued; it must be cancelled at the caller and voided at
    dequeue (counted ``cancelled``), like an expired deadline."""
    from concurrent.futures import TimeoutError as FutTimeout

    net = _make_net()
    srv = serve.ModelServer(net, _spec(), max_queue=16, linger_ms=0.5)
    srv.start()
    handle = net.register_forward_pre_hook(_slow_hook(0.2))
    try:
        rng = np.random.RandomState(13)
        # occupy the worker, then time out on a queued request
        slow = srv.submit(rng.rand(4, FEAT).astype(np.float32))
        time.sleep(0.05)
        with pytest.raises(FutTimeout):
            srv.predict(rng.rand(4, FEAT).astype(np.float32),
                        timeout=0.01)
        assert slow.result(timeout=60).shape == (4, 5)
    finally:
        handle.detach()
        srv.drain()
    s = srv.stats()
    # the abandoned request was voided at dequeue, never served
    assert s["cancelled"] == 1
    assert s["served"] == 1
    assert s["submitted"] == s["served"] + s["cancelled"]
    assert s["in_flight"] == 0 and s["queue_depth"] == 0


def test_predict_deadline_derives_wait_bound(monkeypatch):
    """ISSUE 14 satellite: predict(deadline_ms=) without an explicit
    timeout derives the caller-side wait from the deadline (plus a
    compute grace) instead of blocking indefinitely — a wedged server
    fails the call in bounded time.  An explicit timeout still wins."""
    from concurrent.futures import Future
    from concurrent.futures import TimeoutError as FutTimeout

    from mxnet_tpu.serve import server as server_mod

    monkeypatch.setattr(server_mod, "PREDICT_GRACE_S", 0.2)
    srv = serve.ModelServer(_make_net(), _spec(), max_queue=16,
                            linger_ms=0.5)
    srv.start()
    try:
        # a wedged submit path: the future never resolves
        monkeypatch.setattr(
            srv, "submit", lambda example, deadline_ms=None: Future())
        x = np.zeros((4, FEAT), np.float32)
        t0 = time.monotonic()
        with pytest.raises(FutTimeout):
            srv.predict(x, deadline_ms=100)     # would hang before
        dt = time.monotonic() - t0
        assert 0.1 <= dt < 2.0                  # ~deadline + grace
        t0 = time.monotonic()
        with pytest.raises(FutTimeout):
            srv.predict(x, deadline_ms=60_000, timeout=0.05)
        assert time.monotonic() - t0 < 1.0      # explicit timeout wins
    finally:
        monkeypatch.undo()
        srv.drain()


def test_per_bucket_padding_and_fill_stats():
    """ISSUE 11 satellite: stats() exposes per-bucket fill-ratio and
    padding-overhead splits (not just the aggregates), and the /metrics
    collector exports them as labeled gauges."""
    from mxnet_tpu.telemetry import metrics as tmetrics

    srv = serve.ModelServer(_make_net(), _spec(), max_queue=64,
                            linger_ms=1.0)
    rng = np.random.RandomState(14)
    reg = tmetrics.Registry()
    with srv:
        tmetrics.register_server(srv, registry=reg)
        futs = [srv.submit(x) for x in _requests(12, rng)]
        for f in futs:
            f.result(timeout=60)
        page = reg.render()
    s = srv.stats()
    assert set(s["bucket_fill_ratio"]) == set(s["bucket_hits"])
    assert set(s["bucket_padding_overhead"]) == set(s["bucket_hits"])
    for k, hits in s["bucket_hits"].items():
        assert 0 < s["bucket_fill_ratio"][k] <= 1.0
        assert s["bucket_padding_overhead"][k] >= 0.0
    # labeled gauges on the scrape, one sample per bucket
    assert "mxtpu_serve_bucket_fill_ratio{" in page
    assert "mxtpu_serve_bucket_padding_overhead{" in page
    some_bucket = next(iter(s["bucket_hits"]))
    assert f'bucket="{some_bucket}"' in page
    # reset=True window-scopes the new per-bucket splits too
    srv.stats(reset=True)
    s2 = srv.stats()
    assert s2["bucket_fill_ratio"] == {}
    assert s2["bucket_padding_overhead"] == {}


def test_drain_leaves_zero_in_flight():
    srv = serve.ModelServer(_make_net(), _spec(), max_queue=256,
                            linger_ms=1.0)
    srv.start()
    rng = np.random.RandomState(5)
    futs = [srv.submit(x) for x in _requests(40, rng)]
    srv.drain()
    assert all(f.done() for f in futs)
    s = srv.stats()
    assert s["queue_depth"] == 0 and s["in_flight"] == 0
    assert s["served"] == s["submitted"] == 40
    with pytest.raises(serve.ServerClosedError):
        srv.submit(np.zeros((4, FEAT), np.float32))


def test_hot_reload_swaps_weights(tmp_path):
    trained = _make_net(seed=11)
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(7, params=trained, sync=True)
    mgr.wait_until_finished()

    serving = _make_net(seed=99)  # same arch, different weights
    srv = serve.ModelServer(serving, _spec(), max_queue=64,
                            linger_ms=1.0, checkpoint=str(tmp_path))
    srv.start()
    x = np.random.RandomState(6).rand(4, FEAT).astype(np.float32)
    before = srv.predict(x, timeout=60)
    meta = srv.reload_weights()  # CheckpointManager.latest()
    after = srv.predict(x, timeout=60)
    srv.drain()
    assert meta["step"] == 7
    assert np.abs(before - after).max() > 1e-6  # weights really swapped
    ref = trained(mx.nd.array(x[None])).asnumpy()[0]
    np.testing.assert_allclose(after, ref, rtol=2e-5, atol=2e-5)
    s = srv.stats()
    assert s["reloads"] == 1
    # reload reuses the warmed executables — no recompile
    assert s["graph"]["post_warmup_compiles"] == 0


def test_restart_after_drain_and_shutdown():
    """A drained or abruptly shut-down server can start() again: the
    queue reopens, the warmed executables are reused (zero new
    compiles), and requests are served — not rejected with a confusing
    ServerClosedError."""
    srv = serve.ModelServer(_make_net(), _spec(), max_queue=16,
                            linger_ms=0.5)
    rng = np.random.RandomState(7)
    srv.start()
    assert srv.predict(rng.rand(4, FEAT).astype(np.float32),
                       timeout=60).shape == (4, 5)
    srv.drain()
    srv.start()  # restart after graceful drain
    assert srv.predict(rng.rand(3, FEAT).astype(np.float32),
                       timeout=60).shape == (3, 5)
    srv.shutdown(drain=False)  # abrupt path sets _abort
    srv.start()  # restart after abrupt shutdown
    assert srv.predict(rng.rand(6, FEAT).astype(np.float32),
                       timeout=60).shape == (6, 5)
    srv.drain()
    s = srv.stats()
    assert s["served"] == 3
    assert s["graph"]["post_warmup_compiles"] == 0  # restarts reuse


def test_batch_failure_resolves_futures_and_worker_survives():
    """A model whose output breaks the result-split contract (no batch
    axis to index) must fail THOSE futures, not kill the batcher thread
    — a dead worker would strand every later request forever."""
    class BatchEater(nn.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.sum(x)  # scalar: o[i] in the split loop raises

    net = BatchEater()
    net.initialize()
    srv = serve.ModelServer(net, _spec(), max_queue=16, linger_ms=0.5)
    srv.start(warmup=False)  # warmup only reads back, so it would pass
    futs = [srv.submit(np.ones((4, FEAT), np.float32)) for _ in range(3)]
    for f in futs:
        with pytest.raises(IndexError):
            f.result(timeout=60)
    s = srv.stats()
    assert s["failed"] == 3 and s["in_flight"] == 0
    # the worker thread survived: drain() completes instead of hanging
    srv.drain(timeout=30)
    srv = serve.ModelServer(_make_net(), _spec())
    srv.start()
    try:
        with pytest.raises(mx.MXNetError):
            srv.reload_weights()
    finally:
        srv.drain()


def test_metric_thread_safety():
    """Serve-side accuracy tracking calls EvalMetric.update from worker
    threads; the read-modify-write on sum_metric/num_inst must not
    drop updates."""
    metric = mx.metric.create("acc")
    labels = np.arange(4) % 2
    preds = np.eye(4, 2)[labels.astype(int)]
    n_threads, n_iter = 8, 200

    def worker():
        for _ in range(n_iter):
            metric.update(labels, preds)
            metric.get()

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    name, value = metric.get()
    assert metric.num_inst == n_threads * n_iter * len(labels)
    assert value == pytest.approx(1.0)


def test_profiler_surfaces_graph_cache_counters():
    import json

    from mxnet_tpu import profiler
    from mxnet_tpu.gluon import block as gblock

    gblock.reset_cached_graph_stats()
    srv = serve.ModelServer(_make_net(), _spec((1, 2), (4,)),
                            max_queue=8, linger_ms=0)
    with srv:
        srv.predict(np.zeros((4, FEAT), np.float32), timeout=60)
    data = json.loads(profiler.dumps())
    assert data["cachedGraph"]["compiles"] == 2  # the two warmup buckets
    assert data["cachedGraph"]["reuses"] >= 1    # the served request


@pytest.mark.slow
def test_serve_stress_concurrent_submitters():
    """Many concurrent submitters + a mid-stream hot reload: every
    accepted request resolves, the stats invariant holds, the compile
    surface stays closed, and the runtime lock-order checker observes
    zero inversions across the batcher/stats/exec-lock nest."""
    from mxnet_tpu.analysis import runtime as lock_order

    lock_order.reset()
    # record-don't-raise: a raise inside the batcher thread would
    # strand the submitters' futures and hang the test
    assert lock_order.enable(raise_on_inversion=False), \
        "lock-order checker was already on"
    lock_order.wrap_existing()
    try:
        _serve_stress_body()
    finally:
        lock_order.disable()
        lock_order.unwrap_existing()
    assert lock_order.inversions() == []
    assert lock_order.stats()["acquires"] > 0


def _serve_stress_body():
    srv = serve.ModelServer(_make_net(), _spec((1, 2, 4, 8), (4, 8)),
                            max_queue=512, linger_ms=2.0)
    srv.start()
    n_threads, per_thread = 8, 50
    results, errors = [], []
    lock = threading.Lock()

    def submitter(seed):
        rng = np.random.RandomState(seed)
        futs = [srv.submit(x) for x in _requests(per_thread, rng)]
        for f in futs:
            try:
                r = f.result(timeout=300)
                with lock:
                    results.append(r)
            except Exception as e:  # noqa: BLE001 — collected for assert
                with lock:
                    errors.append(e)

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.drain()
    s = srv.stats()
    assert not errors
    assert len(results) == n_threads * per_thread
    assert s["served"] == s["submitted"] == n_threads * per_thread
    assert s["in_flight"] == 0 and s["queue_depth"] == 0
    assert s["graph"]["post_warmup_compiles"] == 0
    assert s["batches"] < s["served"]  # real coalescing under load


# ---------------------------------------------------------------------------
# telemetry: window-scoped stats + histogram + request spans


def test_stats_window_reset_histogram_and_request_spans(tmp_path):
    """ISSUE 8 satellites: stats(reset=True) window-scopes the serving
    counters like every profiler section (the latency ring was
    process-lifetime before), the latency readout carries cumulative
    Prometheus-style buckets, and a traced burst leaves balanced
    serve.request async spans with queue/compute attribution."""
    import json

    from mxnet_tpu import telemetry
    from mxnet_tpu.serve.stats import LatencyWindow

    # LatencyWindow histogram mechanics in isolation
    w = LatencyWindow(capacity=8, buckets=(1.0, 10.0))
    for v in (0.5, 5.0, 5.0, 100.0):
        w.record(v)
    snap = w.snapshot()
    assert snap["histogram"]["buckets"] == [[1.0, 1], [10.0, 3],
                                            [float("inf"), 4]]
    assert snap["histogram"]["count"] == 4
    assert snap["histogram"]["sum_ms"] == 110.5
    w.reset()
    assert w.snapshot()["count"] == 0
    assert w.snapshot()["histogram"]["buckets"][-1][1] == 0

    srv = serve.ModelServer(_make_net(), _spec(), max_queue=64,
                            linger_ms=1.0)
    srv.start()
    rng = np.random.RandomState(4)
    trace_path = str(tmp_path / "serve.trace.json")
    with telemetry.trace(trace_path):
        futs = [srv.submit(x) for x in _requests(10, rng)]
        for f in futs:
            f.result(timeout=120)

    # request spans: one balanced b/e pair per request, attribution on
    # the close event, batch-phase spans present
    events = json.load(open(trace_path))["traceEvents"]
    begins = [e for e in events if e["ph"] == "b"
              and e["name"] == "serve.request"]
    ends = [e for e in events if e["ph"] == "e"
            and e["name"] == "serve.request"]
    assert len(begins) == len(ends) == 10
    assert {e["id"] for e in begins} == {e["id"] for e in ends}
    assert all("length" in e["args"] for e in begins)
    for e in ends:
        assert e["args"]["outcome"] == "served"
        assert e["args"]["queue_ms"] >= 0
        assert e["args"]["compute_ms"] > 0
        assert e["args"]["bucket"] in {_spec().key(b, l)
                                       for b in (1, 2, 4)
                                       for l in (4, 8)}
    names = {e["name"] for e in events}
    assert {"serve.pad", "serve.split"} <= names
    assert any(n.startswith("serve.batch.") for n in names)

    # window reset: read-and-rewind, gauges stay live
    s = srv.stats(reset=True)
    assert s["served"] == 10
    hist = s["latency"]["histogram"]
    assert hist["count"] == 10
    assert hist["buckets"][-1][1] == 10      # cumulative +Inf == count
    assert s["latency"]["p99_ms"] is not None
    s2 = srv.stats()
    assert s2["served"] == s2["submitted"] == s2["batches"] == 0
    assert s2["latency"]["count"] == 0
    assert s2["latency"]["histogram"]["count"] == 0
    assert s2["bucket_hits"] == {}
    assert s2["graph"]["compiles"] > 0       # gauges unaffected
    # the next window books fresh traffic on the warmed server
    srv.submit(_requests(1, rng)[0]).result(timeout=120)
    srv.drain()
    s3 = srv.stats()
    assert s3["served"] == s3["submitted"] == 1
    assert s3["graph"]["post_warmup_compiles"] == 0
