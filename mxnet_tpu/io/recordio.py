"""RecordIO / IndexedRecordIO (ref: 3rdparty/dmlc-core/include/dmlc/
recordio.h + python/mxnet/recordio.py).

Byte-format compatible with the reference so .rec files pack/unpack
across frameworks: each record is
  [kMagic u32][lrec u32][data][pad to 4B]
where lrec's upper 3 bits are the continuation flag and lower 29 bits
the length.  IRHeader (image records) = [flag u32][label f32][id u64]
[id2 u64] optionally followed by extra float labels when flag > 1.

A C++ twin of this reader lives in src/recordio.cc (built to
libmxtpu_io.so) for the multi-threaded decode pipeline; this Python
implementation is the reference/oracle and fallback.
"""
from __future__ import annotations

import collections
import os
import struct

import numpy as np

from ..base import MXNetError

KMAGIC = 0xCED7230A
_LEN_MASK = (1 << 29) - 1


class MXRecordIO:
    """Sequential record reader/writer (ref: dmlc::RecordIOWriter/Reader)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag}")

    def close(self):
        if self.record is not None:
            self.record.close()
            self.record = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.record.tell()

    def seek(self, pos):
        assert not self.writable
        self.record.seek(pos)

    def _write_chunk(self, chunk, cflag):
        if len(chunk) > _LEN_MASK:
            raise MXNetError(
                "record chunk too large (>512MB between aligned magic "
                "words) — the recordio length field cannot represent it")
        lrec = (cflag << 29) | len(chunk)
        self.record.write(struct.pack("<II", KMAGIC, lrec))
        self.record.write(chunk)
        pad = (4 - len(chunk) % 4) % 4
        if pad:
            self.record.write(b"\x00" * pad)

    def write(self, buf):
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode()
        # dmlc recordio escaping: the payload must never contain the
        # magic word at a 4-byte boundary, so the writer splits the
        # record at each aligned magic occurrence (dropping those 4
        # bytes — the reader re-inserts them) and marks the pieces with
        # the cflag in the top 3 bits of the length word
        # (0 whole, 1 begin, 2 middle, 3 end)
        view = memoryview(buf)
        magic = struct.pack("<I", KMAGIC)
        # C-speed scan: find() hops between candidates; only 4-byte-
        # aligned hits split (the common no-magic payload costs one find)
        splits = []
        pos = buf.find(magic)
        while pos != -1:
            if pos % 4 == 0:
                splits.append(pos)
                pos = buf.find(magic, pos + 4)
            else:
                pos = buf.find(magic, pos + 1)
        if not splits:
            self._write_chunk(view, 0)
            return
        bounds = [0] + [p + 4 for p in splits]
        ends = splits + [len(buf)]
        # validate EVERY chunk before writing any bytes: raising midway
        # would leave a dangling continuation chunk in the file
        for b, e in zip(bounds, ends):
            if e - b > _LEN_MASK:
                raise MXNetError(
                    "record chunk too large (>512MB between aligned "
                    "magic words) — the recordio length field cannot "
                    "represent it")
        n_chunks = len(bounds)
        for i, (b, e) in enumerate(zip(bounds, ends)):
            flag = 1 if i == 0 else (3 if i == n_chunks - 1 else 2)
            self._write_chunk(view[b:e], flag)

    def _read_chunk(self):
        header = self.record.read(8)
        if len(header) < 8:
            return None, 0
        magic, lrec = struct.unpack("<II", header)
        if magic != KMAGIC:
            raise MXNetError(f"{self.uri}: bad record magic {magic:#x}")
        n = lrec & _LEN_MASK
        data = self.record.read(n)
        if len(data) != n:
            raise MXNetError(
                f"{self.uri}: truncated record (wanted {n} bytes, got "
                f"{len(data)})")
        pad = (4 - n % 4) % 4
        if pad:
            self.record.read(pad)
        return data, lrec >> 29

    def read(self):
        assert not self.writable
        data, cflag = self._read_chunk()
        if data is None:
            return None
        if cflag == 0:
            return data
        if cflag != 1:
            raise MXNetError(
                f"{self.uri}: dangling continuation chunk (cflag={cflag})")
        # the writer removed an aligned magic word at every split point;
        # reassembly re-inserts it (dmlc RecordIOReader behavior)
        magic = struct.pack("<I", KMAGIC)
        parts = [data]
        while True:
            piece, cf = self._read_chunk()
            if piece is None:
                raise MXNetError(f"{self.uri}: truncated chunked record")
            parts.append(magic)
            parts.append(piece)
            if cf == 3:
                return b"".join(parts)
            if cf != 2:
                raise MXNetError(
                    f"{self.uri}: bad continuation cflag {cf}")


class MXIndexedRecordIO(MXRecordIO):
    """Random-access records via a .idx file (ref: MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.exists(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) >= 2:
                        k = self.key_type(parts[0])
                        self.idx[k] = int(parts[1])
                        self.keys.append(k)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


class NativeIndexedRecordIO:
    """Write-side MXIndexedRecordIO backed by the native C++ writer
    (src/recordio.cc MXTPURecordIOWriter*) — the `tools/im2rec.cc`
    binary's role (VERDICT r3 #8; ref: dmlc recordio.h writer).  The
    record/index output is byte-identical to the Python writer: same
    magic-escape chunking, same `idx\\tpos` index lines."""

    def __init__(self, idx_path, uri, flag="w", key_type=int):
        from ..base import MXNetError
        from ..utils import native

        if flag != "w":
            raise MXNetError(
                "NativeIndexedRecordIO is the packer (write) side; "
                "read through MXIndexedRecordIO or the native pipeline")
        lib = native.load()
        if lib is None:
            raise MXNetError(
                "native IO library unavailable (build lib/libmxtpu_io.so"
                " or use MXIndexedRecordIO)")
        self._lib = lib
        self._h = lib.MXTPURecordIOWriterCreate(uri.encode())
        if not self._h:
            raise MXNetError(f"cannot open {uri} for writing")
        self.idx_path = idx_path
        self.key_type = key_type
        self.fidx = open(idx_path, "w")
        self.idx = {}
        self.keys = []

    def write_idx(self, idx, buf):
        from ..base import MXNetError

        if self._h is None or self.fidx is None:
            # a NULL handle would be dereferenced by the C writer
            raise MXNetError("NativeIndexedRecordIO is closed")
        key = self.key_type(idx)
        pos = self._lib.MXTPURecordIOWrite(self._h, bytes(buf), len(buf))
        if pos < 0:
            raise MXNetError("native recordio write failed "
                             f"(record {key}, {len(buf)} bytes)")
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)

    def close(self):
        if self._h:
            self._lib.MXTPURecordIOWriterFree(self._h)
            self._h = None
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


IRHeader = collections.namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack an IRHeader + payload (ref: mx.recordio.pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        hdr = struct.pack(_IR_FORMAT, header.flag, float(header.label),
                          header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        hdr = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                          header.id2) + label.tobytes()
    if isinstance(s, str):
        s = s.encode()
    return hdr + s


def unpack(s):
    """Unpack to (IRHeader, payload) (ref: mx.recordio.unpack)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        # packed float label vector (size-1 included — ref strips for
        # flag > 0, not flag > 1)
        label = np.frombuffer(payload[:4 * flag], dtype=np.float32)
        payload = payload[4 * flag:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an image array and pack (ref: mx.recordio.pack_img)."""
    import io as _io

    from PIL import Image

    arr = np.asarray(img)
    if arr.ndim == 3 and arr.shape[2] == 3:
        pil = Image.fromarray(arr.astype(np.uint8))
    else:
        pil = Image.fromarray(arr.astype(np.uint8))
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG"
    pil.save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=-1):
    """Unpack to (IRHeader, image ndarray HWC BGR-free/RGB)."""
    import io as _io

    from PIL import Image

    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    if iscolor == 0:
        img = img.convert("L")
    elif iscolor == 1:
        img = img.convert("RGB")
    return header, np.asarray(img)
