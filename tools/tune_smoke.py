"""Autotuner gate for `make verify` (docs/tuning.md).

Start from a deliberately bad config — 1 MB kvstore buckets,
aggregate_num=1, no pipeline prefetch/overlap, zero batcher linger,
and ONE giant serve bucket (every request padded to batch 1 x len 512)
— then run the closed loop on a real training+serving rehearsal and
hold it to the acceptance bar:

1. the tuner ESCAPES: best/baseline objective ratio past a gated
   margin, with the winning knob moves named;
2. autotuned >= hand-tuned: the registry defaults are measured as a
   first-class reference trial and the recommendation beats-or-ties
   them;
3. the evidence trail is real: every trial landed in the history
   jsonl and `bench_diff --file` can diff it;
4. the settled config's serving surface is closed: a fresh server
   built FROM the recommendation serves a mixed burst with zero
   post-warmup compiles;
5. geometry feeds the search: a grid derived from the probe burst's
   ServerStats shape histograms joins the serve_buckets domain.

Runs on the CPU backend so the gate is deterministic and fast anywhere.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon, pipeline, profiler, serve, tune  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.tune import (derive_bucket_spec, format_grid,  # noqa: E402
                            parse_grid, reset_tune_stats)

FEAT, BS, N_TRAIN, N_SERVE = 8, 4, 32, 64
FETCH_MS = 4.0          # simulated remote-storage latency per sample
GATE = 1.10             # best/baseline ratio the tuner must clear

#: the knobs the rehearsal searches (a subset keeps wall time modest;
#: the full registry is still validated below)
KNOBS = ["serve_buckets", "serve_linger_ms", "pipeline_prefetch",
         "pipeline_map_inflight", "aggregate_num", "kvstore_bucket_mb"]

BAD_CONFIG = {
    "kvstore_bucket_mb": 1.0,      # tiny buckets: max dispatches
    "aggregate_num": 1,            # sequential optimizer updates
    "pipeline_prefetch": 0,        # no h2d overlap
    "pipeline_map_inflight": 1,    # fetch latency fully serialized
    "serve_linger_ms": 0.0,        # no coalescing window
    "serve_buckets": "1x512",      # one giant bucket: batch 1, pad 512
}


def build_train():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=FEAT, activation="relu"),
            nn.Dense(1, in_units=16))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, whole_step=True)
    return net, trainer


def build_serve_net():
    mx.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, flatten=False, in_units=FEAT,
                     activation="relu"),
            nn.Dense(4, flatten=False, in_units=16))
    net.initialize(mx.init.Xavier())
    return net


def loss_fn(out, y):
    return (out - y.reshape((-1, 1))) ** 2


def make_train_data():
    rng = np.random.RandomState(0)
    return [(rng.rand(FEAT).astype(np.float32), np.float32(i % 2))
            for i in range(N_TRAIN)]


def make_requests():
    """Heavy-tailed request lengths: mostly short, thin tail to 48."""
    rng = np.random.RandomState(1)
    lens = rng.choice([6, 8, 12, 16, 24, 32, 48], size=N_SERVE,
                      p=[0.30, 0.25, 0.18, 0.12, 0.08, 0.05, 0.02])
    return [rng.rand(int(L), FEAT).astype(np.float32) for L in lens]


def spec_from_grid(grid):
    batches, lengths = parse_grid(grid)
    return serve.BucketSpec(batch_sizes=batches,
                            example_shape=(None, FEAT),
                            lengths=lengths)


def slow_fetch(sample):
    time.sleep(FETCH_MS / 1e3)
    return sample


def serve_burst(srv, requests):
    futs = [srv.submit(x) for x in requests]
    for f in futs:
        f.result(timeout=120)
    return len(futs)


def measure(cfg, train_data, requests, serve_net):
    """One rehearsal window: a pipeline-fed whole-step training burst
    plus a mixed-length serving burst, on freshly built components so
    every env-backed knob actually reaches a constructor.  Warmup
    (XLA compiles) happens OUTSIDE the timed window — the knobs are
    judged on steady-state throughput, and the compile cost they
    induce is accounted separately by the trial runner's recompile
    debit."""
    net, trainer = build_train()
    xw = mx.nd.array(np.zeros((BS, FEAT), np.float32))
    yw = mx.nd.array(np.zeros((BS,), np.float32))
    trainer.whole_step(net, loss_fn, xw, yw)          # warm the step
    pipe = pipeline.Pipeline(train_data).map(
        slow_fetch).batch(BS, last_batch="discard").prefetch_to_device()
    t0 = time.perf_counter()
    n_samples = 0
    for x, y in pipe:
        trainer.whole_step(net, loss_fn, x, y)
        n_samples += BS
    t_train = time.perf_counter() - t0

    srv = serve.ModelServer(serve_net, spec_from_grid(
        cfg["serve_buckets"]), max_queue=2 * N_SERVE)
    srv.start()                                       # AOT warmup
    t1 = time.perf_counter()
    n_served = serve_burst(srv, requests)
    t_serve = time.perf_counter() - t1
    srv.shutdown(drain=True)

    total = t_train + t_serve
    return {"samples_per_s": (n_samples + n_served) / total,
            "train_ms": t_train * 1e3, "serve_ms": t_serve * 1e3}


def main():
    reset_tune_stats()
    reg = tune.default_registry()
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "docs", "ENV_VARS.md")) as f:
        doc = f.read()
    reg.validate(documented_env=set(
        w for w in doc.replace("`", " ").replace("|", " ").split()
        if w.startswith("MXTPU_")))

    train_data = make_train_data()
    requests = make_requests()
    serve_net = build_serve_net()

    # -- the deliberately bad starting config -------------------------------
    reg.get("serve_buckets").extend_domain(BAD_CONFIG["serve_buckets"])
    reg.apply(BAD_CONFIG)

    # -- probe burst: observed shapes -> derived grid joins the search -----
    probe = serve.ModelServer(serve_net, spec_from_grid(
        BAD_CONFIG["serve_buckets"]), max_queue=2 * N_SERVE)
    probe.start()
    serve_burst(probe, requests)
    snap = probe.stats()
    probe.shutdown(drain=True)
    assert snap["request_lengths"], "probe recorded no shape stats"
    derived = derive_bucket_spec(snap, (None, FEAT), max_buckets=3)
    derived_grid = format_grid(derived.batch_sizes, derived.lengths)
    reg.get("serve_buckets").extend_domain(derived_grid)

    # -- the closed loop ----------------------------------------------------
    hist = os.path.join(tempfile.mkdtemp(prefix="tune-smoke-"),
                        "TUNE_HISTORY.jsonl")
    runner = tune.TrialRunner(
        reg, lambda cfg: measure(cfg, train_data, requests, serve_net),
        history=hist, seed=0, recompile_penalty=0.001)
    tuner = tune.Tuner(reg, runner=runner, knobs=KNOBS, seed=0,
                       top_k=1)
    rec = tuner.run()
    print(rec.summary())

    failures = []

    def check(name, cond):
        if not cond:
            failures.append(name)

    # 1: escaped the bad config by the gated margin, with real moves
    check(f"ratio {rec.ratio:.3f} >= {GATE}", rec.ratio >= GATE)
    check("tuner moved at least one knob", rec.moved())
    check("no move was silently blocked", rec.blocked_moves == 0)

    # 2: autotuned >= hand-tuned defaults (measured, not assumed)
    refs = [t for t in rec.trials if t["label"] == "ref:defaults"]
    check("defaults measured as a reference trial", len(refs) == 1)
    check("autotuned >= hand-tuned defaults",
          refs and rec.best["score"] >= refs[0]["score"])

    # 3: evidence trail — every trial on disk, bench_diff can read it
    with open(hist) as f:
        lines = [json.loads(line) for line in f]
    check("history holds every trial",
          len(lines) == len(rec.trials) and
          all(r["kind"] == "tune_trial" for r in lines))
    diff = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "bench_diff.py"), "--file", hist],
        capture_output=True, text=True)
    check("bench_diff --file reads the trail",
          diff.returncode == 0 and "BENCH_DIFF" in diff.stdout)

    # 4: the settled config's serving surface is closed
    final_grid = reg.get("serve_buckets").read()
    check("winning grid applied to the env surface",
          final_grid == rec.config["serve_buckets"])
    srv = serve.ModelServer(serve_net, spec_from_grid(final_grid),
                            max_queue=2 * N_SERVE)
    srv.start()
    serve_burst(srv, requests)
    s = srv.stats()
    srv.shutdown(drain=True)
    check("zero post-warmup compiles after settling",
          s["graph"]["post_warmup_compiles"] == 0)

    # 5: the tune profiler section saw the whole run
    sec = profiler.sections()["tune"]
    check("tune section counted every trial",
          sec["trials"] == len(rec.trials))
    check("tune section best_over_baseline agrees",
          abs(sec["best_over_baseline"] - rec.ratio) < 1e-9)

    if failures:
        print("TUNE_SMOKE_FAILED:")
        for f in failures:
            print(f"  - {f}")
        sys.exit(1)

    print(f"TUNE_SMOKE_OK trials={len(rec.trials)} "
          f"ratio={rec.ratio:.3f} moved={len(rec.moved())} "
          f"derived_grid={derived_grid} "
          f"final_grid={final_grid} "
          f"recompiles_spent={sec['recompiles_spent']} "
          f"post_warmup_compiles=0")


if __name__ == "__main__":
    main()
