"""ZeRO-1 sharding gate for `make verify` (docs/performance.md).

On the virtual 8-device replica mesh: 50 post-warmup SHARDED whole
steps under a decaying LR schedule must execute as ONE counted device
dispatch each with ZERO post-warmup XLA compiles, the sharded path must
actually engage (zero_steps == steps, zero fallbacks), a 5-step sharded
vs unsharded whole-step A/B must leave BIT-identical weights, and the
measured per-replica optimizer-state bytes must come in under HALF the
unsharded footprint (the 1/world_size memory contract, padding
included).  Runs on the CPU backend so the gate is deterministic and
fast on any host.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# the gate A/Bs sharded vs unsharded — exported knobs would collapse
# or skew the arms
for _var in ("MXNET_OPTIMIZER_AGGREGATION_SIZE",
             "MXTPU_OPTIMIZER_AGGREGATION_SIZE",
             "MXTPU_WHOLE_STEP", "MXNET_WHOLE_STEP",
             "MXTPU_ZERO_SHARD", "MXNET_ZERO_SHARD",
             "MXTPU_KVSTORE_BUCKET_MB", "MXNET_KVSTORE_BUCKET_MB"):
    os.environ.pop(_var, None)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # XLA_FLAGS above already provides the 8-device mesh

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import _imperative, gluon, lr_scheduler, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.gluon import trainer as trainer_mod  # noqa: E402

N_LAYERS, UNITS, WARMUP, STEPS, WORLD = 6, 13, 5, 50, 8
CTXS = [mx.xla(i) for i in range(WORLD)]


def loss_fn(out, y):
    return (out - y) ** 2


def build(zero):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    for _ in range(N_LAYERS):
        # 13 units: bucket sizes are NOT multiples of the 8-rank
        # world, so every chunk exercises the zero-pad path; tanh
        # keeps the stack bounded for the array_equal parity gate
        net.add(nn.Dense(UNITS, in_units=UNITS, activation="tanh"))
    net.initialize(mx.init.Xavier(), ctx=CTXS)
    kwargs = {"learning_rate": 0.1, "momentum": 0.9,
              "lr_scheduler": lr_scheduler.FactorScheduler(
                  step=5, factor=0.95, base_lr=0.1)}
    trainer = gluon.Trainer(net.collect_params(), "sgd", kwargs,
                            whole_step=True, zero_shard=zero)
    x = np.random.rand(8, UNITS).astype(np.float32)
    y = np.random.rand(8, UNITS).astype(np.float32)
    return net, trainer, x, y


def main():
    net, trainer, x, y = build(True)
    for _ in range(WARMUP):
        trainer.whole_step(net, loss_fn, x, y)
    nd.waitall()
    lr0 = trainer.learning_rate
    trainer_mod.reset_trainer_step_stats()
    c0 = _imperative.compiled_executable_count()
    d0 = _imperative.device_dispatch_count()
    for _ in range(STEPS):
        trainer.whole_step(net, loss_fn, x, y)
    nd.waitall()
    compiles = _imperative.compiled_executable_count() - c0
    dispatches = _imperative.device_dispatch_count() - d0
    stats = trainer_mod.trainer_step_stats()
    assert compiles == 0, \
        f"sharded whole step recompiled: {compiles} new executables " \
        f"in {STEPS} post-warmup steps (lr schedule must ride as a " \
        "traced scalar)"
    assert dispatches == STEPS, \
        f"{dispatches} device dispatches for {STEPS} sharded whole " \
        "steps — eager work is leaking into the compiled step loop"
    assert stats["zero_steps"] == STEPS and \
        stats["zero_fallbacks"] == 0, \
        f"ZeRO-1 path did not engage: {stats}"
    assert stats["whole_step_steps"] == STEPS and \
        stats["whole_step_compiles"] == 0, \
        f"whole-step signature churn post-warmup: {stats}"
    assert trainer.learning_rate < lr0, \
        f"LR schedule did not decay ({lr0} -> {trainer.learning_rate})"

    # 5-step bit parity + state-bytes contract vs the unsharded
    # whole-step arm on the SAME mesh
    net_u, tr_u, x_u, y_u = build(False)
    net_z, tr_z, x_z, y_z = build(True)
    for _ in range(5):
        tr_u.whole_step(net_u, loss_fn, x_u, y_u)
        tr_z.whole_step(net_z, loss_fn, x_z, y_z)
    for (na, a), (nb, b) in zip(
            net_u.collect_params().items(),
            net_z.collect_params().items()):
        if not np.array_equal(a.data(CTXS[0]).asnumpy(),
                              b.data(CTXS[0]).asnumpy()):
            raise AssertionError(
                f"sharded/unsharded weight divergence at {na}")
    full = tr_u.optimizer_state_bytes()["per_replica"]
    shard = tr_z.optimizer_state_bytes()["per_replica"]
    assert full > 0 and shard < full / 2, \
        f"per-replica optimizer state did not shrink: {shard} vs " \
        f"{full} unsharded (world {WORLD})"

    print(f"ZERO_SHARD_SMOKE_OK steps={STEPS} "
          f"post_warmup_compiles={compiles} "
          f"dispatches_per_step={dispatches / STEPS:.2f} "
          f"zero_steps={stats['zero_steps']} "
          f"state_bytes_per_replica={shard} (unsharded {full}, "
          f"world {WORLD}) lr {lr0:.4f}->{trainer.learning_rate:.4f}")


if __name__ == "__main__":
    main()
