"""Multi-process distributed tests, launched the reference's way:
tools/launch.py -n N --launcher local (ref: tests/nightly/)."""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dist_sync_kvstore_two_workers():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # script forces cpu itself
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local", sys.executable,
         os.path.join(_ROOT, "tests", "nightly", "dist_sync_kvstore.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=_ROOT)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    assert "worker 0/2: dist_sync kvstore OK" in out
    assert "worker 1/2: dist_sync kvstore OK" in out


@pytest.mark.slow
@pytest.mark.parametrize("num_servers", [0, 1])
def test_dist_async_kvstore_two_workers(tmp_path, num_servers):
    """num_servers=0: worker 0 hosts the PS thread; =1: dedicated
    DMLC_ROLE=server process (ref: tools/launch.py -s)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["MXTPU_TEST_TMPDIR"] = str(tmp_path)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "2", "-s", str(num_servers), "--launcher", "local",
         sys.executable,
         os.path.join(_ROOT, "tests", "nightly", "dist_async_kvstore.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=_ROOT)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    for r in (0, 1):
        assert f"worker {r}/2: dist_async kvstore OK" in out


@pytest.mark.slow
def test_dist_sync_kvstore_four_workers():
    """The reference nightly ran -n 4 (VERDICT r2 #5: scale past 2);
    also the >=3-process exercise of the in-graph DCN collective."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "4", "--launcher", "local", sys.executable,
         os.path.join(_ROOT, "tests", "nightly", "dist_sync_kvstore.py")],
        capture_output=True, text=True, timeout=360, env=env, cwd=_ROOT)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    for r in range(4):
        assert f"worker {r}/4: dist_sync kvstore OK" in out


@pytest.mark.slow
@pytest.mark.parametrize("num_servers", [0, 1])
def test_dist_async_conflict_three_workers(tmp_path, num_servers):
    """Conflicting + out-of-order pushes at n=3 with exact merge
    assertions (VERDICT r2 weak #5)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["MXTPU_TEST_TMPDIR"] = str(tmp_path)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "3", "-s", str(num_servers), "--launcher", "local",
         sys.executable,
         os.path.join(_ROOT, "tests", "nightly", "dist_async_conflict.py")],
        capture_output=True, text=True, timeout=360, env=env, cwd=_ROOT)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    for r in range(3):
        assert f"worker {r}/3: dist_async conflict OK" in out


@pytest.mark.slow
@pytest.mark.parametrize("failure_mode", ["sigkill", "sigstop"])
def test_dist_async_server_death_fails_fast(tmp_path, failure_mode):
    """Kill the dedicated parameter-server PROCESS mid-run: the worker
    must surface a diagnosable MXNetError quickly — not hang (VERDICT
    r2 weak #5 'heartbeat marks dead -> then what?').

    Two failure shapes exercise two detection paths:
    - sigkill: the kernel closes the socket (RST) -> the connect/retry
      path reports the server unreachable immediately;
    - sigstop: the process freezes but its socket STAYS OPEN (the
      network-partition/power-loss shape, no RST) -> only the
      HEARTBEAT detector can mark it dead."""
    import random
    import signal
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.parallel.ps import PSClient

    port = 19700 + (os.getpid() + random.randrange(500)) % 1000

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"DMLC_PS_SERVER_PORT": str(port), "DMLC_NUM_SERVER": "1",
                "DMLC_SERVER_ID": "0"})
    server = subprocess.Popen(
        [sys.executable, "-c",
         "from mxnet_tpu.parallel import ps; ps.run_server()"],
        env=env, cwd=_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        cli = None
        for _ in range(80):  # server cold start
            if server.poll() is not None:
                break  # died at startup: surface its stderr below
            try:
                cli = PSClient([("127.0.0.1", port)], timeout=2,
                               retries=1, worker_id=0,
                               heartbeat_interval=0.05, dead_after=4)
                break
            except OSError:
                time.sleep(0.25)
        if cli is None:
            server.kill()
            out, err = server.communicate(timeout=10)
            raise AssertionError(
                f"server never came up on port {port}; stderr:\n"
                f"{err[-2000:]}")
        cli.init("w", np.zeros(4, np.float32))
        cli.push("w", np.ones(4, np.float32))
        assert cli.pull("w")[0] == 1.0

        t0 = time.time()
        if failure_mode == "sigkill":
            server.send_signal(signal.SIGKILL)
            server.wait(timeout=10)
        else:
            server.send_signal(signal.SIGSTOP)  # frozen, socket open
            # the heartbeat thread must mark it dead on its own
            deadline = time.time() + 20
            while cli.alive() and time.time() < deadline:
                time.sleep(0.05)
            assert cli.alive() == [], (
                "heartbeat never marked the frozen server dead")
        with pytest.raises(mx.MXNetError,
                           match="dead" if failure_mode == "sigstop"
                                 else "dead|unreachable"):
            for _ in range(40):  # the kill path may need a few misses
                cli.push("w", np.ones(4, np.float32))
                time.sleep(0.1)
        # diagnosable AND prompt: well under a one-minute hang
        assert time.time() - t0 < 40, "fail-fast took too long"
        cli.close()
    finally:
        if server.poll() is None:
            try:
                server.send_signal(signal.SIGCONT)
            except Exception:
                pass
            server.kill()
