"""dist_sync failure semantics (VERDICT r4 #6; ref: ps-lite van
timeouts + the reference's kill-and-restart elastic story).

Worker script with two modes, driven by env:

MXTPU_FAILTEST_MODE=die
    All workers train with gluon.Trainer(kvstore='dist_sync'); the
    worker whose rank == MXTPU_FAILTEST_DIE_RANK exits abruptly
    mid-step (no shutdown handshake — the crashed-worker shape).
    Survivors must surface a diagnosable MXNetError within the
    MXTPU_BARRIER_TIMEOUT_S bound instead of hanging, then checkpoint
    their state and exit cleanly, printing how long detection took.

MXTPU_FAILTEST_MODE=resume
    Every worker restarts from the checkpoint the killed run left
    behind (params + Trainer optimizer states) and finishes the
    remaining steps; final per-step losses must continue the oracle
    trajectory and params must be identical across workers.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel import dist  # noqa: E402

dist.init()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import MXNetError, autograd, gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402

rank, size = dist.rank(), dist.num_workers()
MODE = os.environ["MXTPU_FAILTEST_MODE"]
CKPT_DIR = os.environ["MXTPU_FAILTEST_CKPT"]
DIE_RANK = int(os.environ.get("MXTPU_FAILTEST_DIE_RANK", "1"))
DIE_STEP = int(os.environ.get("MXTPU_FAILTEST_DIE_STEP", "3"))
STEPS = int(os.environ.get("MXTPU_FAILTEST_STEPS", "6"))

GLOBAL_BATCH, FEAT, NCLS = 16, 12, 4
PER = GLOBAL_BATCH // size
rng = np.random.RandomState(0)
X = rng.rand(GLOBAL_BATCH, FEAT).astype(np.float32)
Y = rng.randint(0, NCLS, GLOBAL_BATCH).astype(np.float32)

mx.random.seed(0)
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu"), nn.Dense(NCLS))
net.initialize(mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1, "momentum": 0.9},
                        kvstore="dist_sync")
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

shard = slice(rank * PER, rank * PER + PER)
xw, yw = nd.array(X[shard]), nd.array(Y[shard])

params_f = os.path.join(CKPT_DIR, "net.params")
states_f = os.path.join(CKPT_DIR, "trainer.states")
step_f = os.path.join(CKPT_DIR, "step.txt")

start_step = 0
if MODE == "resume":
    # rejoin-from-checkpoint: every worker (including the replacement
    # for the dead one) loads the surviving checkpoint
    net.load_parameters(params_f)
    trainer.load_states(states_f)
    start_step = int(open(step_f).read())
    assert start_step >= 1, "resume run found no checkpointed step"


def checkpoint(step):
    # rank-0-writes / everyone-barriers: atomic rename so a crash
    # mid-write never leaves a torn checkpoint for the resume run
    if rank == 0:
        for fname, writer in ((params_f, net.save_parameters),
                              (states_f, trainer.save_states)):
            tmp = fname + ".tmp"
            writer(tmp)
            os.replace(tmp, fname)
        tmp = step_f + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(step))
        os.replace(tmp, step_f)


losses = []
for step in range(start_step, STEPS):
    if MODE == "die" and rank == DIE_RANK and step == DIE_STEP:
        # crash shape: no handshake, no cleanup. Exit code 0 keeps the
        # launcher's rc aggregation meaningful for the survivors.
        print(f"worker {rank}/{size}: dying abruptly at step {step}",
              flush=True)
        os._exit(0)
    try:
        with autograd.record():
            loss = loss_fn(net(xw), yw).sum()
        loss.backward()
        t0 = time.monotonic()
        trainer.step(GLOBAL_BATCH)
        total = dist.allreduce(nd.array(
            np.asarray([float(loss.asscalar())], np.float32)))
    except MXNetError as e:
        took = time.monotonic() - t0
        bound = float(os.environ["MXTPU_BARRIER_TIMEOUT_S"]) + 5.0
        assert took < bound, f"detection took {took:.1f}s > {bound}s"
        assert "peer process is likely dead" in str(e), str(e)
        assert "checkpoint" in str(e), str(e)
        print(f"worker {rank}/{size}: peer failure detected in "
              f"{took:.1f}s at step {step} OK", flush=True)
        sys.stdout.flush()
        # fail-fast exit: skip the interpreter-shutdown distributed
        # barrier — with a peer already dead it can only abort (the
        # jax client terminates the process on shutdown-barrier
        # failure); the restart-from-checkpoint run re-inits cleanly
        os._exit(0)
    losses.append(float(total.asnumpy()[0]) / GLOBAL_BATCH)
    # checkpoint AFTER the optimizer step so a resume replays from the
    # next step; barrier orders the rank-0 write against peers racing
    # into the next step's collective
    checkpoint(step + 1)
    dist.barrier("ckpt")

if MODE == "die":
    # ranks that never hit a collective after the death (e.g. all
    # steps completed before DIE_STEP) should not get here
    raise AssertionError(
        f"worker {rank}: no failure detected across {STEPS} steps")

# resume mode: verify the continued trajectory against the oracle
ref = np.asarray(np.load(os.environ["MXTPU_ORACLE_FILE"])["losses"])
tail = ref[start_step:STEPS]
assert np.allclose(losses, tail, atol=1e-5), (losses, tail.tolist())

flat = np.concatenate([p.data().asnumpy().ravel()
                       for p in net.collect_params().values()])
peer_sum = dist.allreduce(nd.array(flat)).asnumpy()
assert np.allclose(peer_sum, size * flat, atol=1e-6), \
    float(np.abs(peer_sum - size * flat).max())

print(f"worker {rank}/{size}: rejoined from step {start_step} and "
      f"finished OK (loss {losses[0]:.4f}->{losses[-1]:.4f})",
      flush=True)
