"""MXA5xx — knob-registry invariants (the autotuner's control surface).

The tune registry (:mod:`mxnet_tpu.tune.knobs`) is the list of
settings the tuner is allowed to move.  Two things make a registry
entry trustworthy, and both are statically checkable off the literal
``Knob(...)`` constructor kwargs:

MXA501  undocumented / unbound env var — a ``Knob`` whose ``env=``
        kwarg is missing or non-literal, or whose ``MXTPU_<env>``
        spelling does not appear in docs/ENV_VARS.md.  The registry
        is MXA402's rule applied one layer up: every knob the tuner
        may move must be a documented config surface, or an adopted
        recommendation is un-reproducible outside the tuner's
        process.
MXA502  missing bounds — a numeric ``Knob`` with neither a literal
        non-empty ``domain=`` candidate set nor a literal
        ``bounds=(lo, hi)`` with ``lo < hi``.  An unbounded knob
        gives the search an open-ended space and the trial runner a
        license to apply nonsense; ``kind="bool"``/``"choice"``
        knobs carry their domain by construction and are exempt
        (choice still needs the ``domain=`` itself, which MXA502
        checks).

Both passes read the constructor call sites, so drift between the
registry and the docs is a CI finding, not a reviewer catch.  The pass
is a no-op when the configured knobs module does not exist (fixture
packages without a tune tier).
"""
from __future__ import annotations

import ast
import re

from .core import Finding


def _literal(node):
    return node.value if isinstance(node, ast.Constant) else None


def _str_literal(node):
    v = _literal(node)
    return v if isinstance(v, str) else None


def _seq_elts(node):
    """Elements of a literal tuple/list expression, else None."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return node.elts
    return None


def run(index):
    cfg = index.cfg
    mod = index.modules.get(cfg.tune_knobs_module)
    if mod is None:
        return []
    doc = index.doc_text(cfg.env_doc) or ""
    documented = set(re.findall(r"[A-Z][A-Z0-9_]{2,}", doc))

    findings = []
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in cfg.knob_ctor_names):
            continue
        kname = (_str_literal(node.args[0]) if node.args else None)
        if kname is None:
            for kw in node.keywords:
                if kw.arg == "name":
                    kname = _str_literal(kw.value)
        anchor = kname or "<dynamic>"
        sym = f"{index.enclosing(mod, node.lineno)}:{anchor}"
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}

        env = kwargs.get("env")
        env_name = _str_literal(env) if env is not None else None
        if env_name is None:
            findings.append(Finding(
                "MXA501", mod.relpath, node.lineno, sym,
                f"knob {anchor} has no literal env= kwarg — every "
                f"registry knob must name its backing MXTPU_ env var "
                f"so an adopted recommendation is reproducible"))
        elif "MXTPU_" + env_name not in documented:
            findings.append(Finding(
                "MXA501", mod.relpath, node.lineno, sym,
                f"knob {anchor}: env var MXTPU_{env_name} is not "
                f"documented in {cfg.env_doc} — registry and docs "
                f"have drifted"))

        kind = "int"
        if "kind" in kwargs:
            kind = _str_literal(kwargs["kind"]) or "<dynamic>"
        dom = _seq_elts(kwargs["domain"]) if "domain" in kwargs \
            else None
        has_domain = bool(dom) and all(
            _literal(e) is not None for e in dom)
        has_bounds = False
        bnd = _seq_elts(kwargs["bounds"]) if "bounds" in kwargs \
            else None
        if bnd is not None and len(bnd) == 2:
            lo, hi = _literal(bnd[0]), _literal(bnd[1])
            has_bounds = (isinstance(lo, (int, float))
                          and isinstance(hi, (int, float))
                          and lo < hi)
        if kind != "bool" and not (has_domain or has_bounds):
            findings.append(Finding(
                "MXA502", mod.relpath, node.lineno, sym,
                f"knob {anchor} declares neither a literal non-empty "
                f"domain= nor literal bounds=(lo, hi) with lo < hi — "
                f"an unbounded knob is untunable"))
    return findings
