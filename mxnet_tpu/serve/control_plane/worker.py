"""Replica worker entry point:

    python -m mxnet_tpu.serve.control_plane.worker \\
        --registry /shared/ctrl --id 0 --kind decode --seed 4

Builds a deterministic demo server (every worker launched with the
same ``--seed`` holds BIT-IDENTICAL weights, so failover between
replicas is invisible in the outputs — the pool convention), runs its
full AOT-warming ``start()``, and only THEN registers the endpoint's
lease: a replica a router can discover is a replica that will never
compile in traffic.  Runs until SIGTERM/SIGINT.

Real deployments supply their own worker that loads real weights; the
contract is only "start() before serve_replica()".
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading


def _csv_ints(s):
    return tuple(int(x) for x in s.split(",") if x)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="mxnet_tpu control-plane replica worker")
    ap.add_argument("--registry", required=True,
                    help="shared lease/registry directory")
    ap.add_argument("--id", required=True, help="replica id (lease key)")
    ap.add_argument("--kind", choices=("decode", "model"),
                    default="decode")
    ap.add_argument("--seed", type=int, default=4,
                    help="weight seed — same seed => bit-identical "
                         "replicas")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    # decode knobs
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--embed", type=int, default=16)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--max-len", type=int, default=32)
    # shared bucket grid
    ap.add_argument("--batch-sizes", type=_csv_ints, default=(1, 2))
    ap.add_argument("--lengths", type=_csv_ints, default=(4, 8))
    # model (ModelServer) knobs
    ap.add_argument("--feat", type=int, default=6)
    ap.add_argument("--out-units", type=int, default=5)
    ap.add_argument("--max-queue", type=int, default=64)
    args = ap.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import serve
    from mxnet_tpu.serve.control_plane import serve_replica

    if args.kind == "decode":
        mx.random.seed(args.seed)
        model = serve.TinyDecoder(vocab=args.vocab, embed=args.embed)
        model.initialize(mx.init.Xavier())
        spec = serve.BucketSpec(batch_sizes=args.batch_sizes,
                                example_shape=(None,),
                                lengths=args.lengths, dtype="int32")
        server = serve.DecodeServer(model, spec,
                                    max_slots=args.max_slots,
                                    max_len=args.max_len)
    else:
        from mxnet_tpu.gluon import nn
        mx.random.seed(args.seed)
        model = nn.HybridSequential()
        model.add(nn.Dense(8, flatten=False, in_units=args.feat,
                           activation="relu"),
                  nn.Dense(args.out_units, flatten=False, in_units=8))
        model.initialize(mx.init.Xavier())
        spec = serve.BucketSpec(batch_sizes=args.batch_sizes,
                                example_shape=(None, args.feat),
                                lengths=args.lengths)
        server = serve.ModelServer(model, spec,
                                   max_queue=args.max_queue)

    server.start()          # the full AOT warmup — BEFORE registering
    endpoint = serve_replica(server, host=args.host, port=args.port,
                             registry_dir=args.registry,
                             replica_id=args.id)
    print(f"replica {args.id} ({args.kind}) serving on "
          f"{endpoint.host}:{endpoint.port}", flush=True)

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    endpoint.stop()
    server.shutdown(drain=False, timeout=10.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
