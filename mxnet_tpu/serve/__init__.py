"""mxnet_tpu.serve — dynamic-batching inference serving.

See docs/serving.md for bucket selection, warmup, deadline and
backpressure semantics, and the hot-reload workflow::

    from mxnet_tpu import serve

    spec = serve.BucketSpec(batch_sizes=(1, 4, 8),
                            example_shape=(None, 64),
                            lengths=(16, 32, 64))
    with serve.ModelServer(net, spec, checkpoint="/ckpts") as srv:
        fut = srv.submit(request_array, deadline_ms=50)
        result = fut.result()
        print(srv.stats())
"""
from .batcher import (Batcher, DeadlineExceededError,  # noqa: F401
                      ServerClosedError, ServerOverloadedError)
from .buckets import BucketOverflowError, BucketSpec  # noqa: F401
from .decode import (DecodeHandle, DecodeServer,  # noqa: F401
                     TinyDecoder, TinyDraft)
from .paging import (PageAllocator, PrefixIndex,  # noqa: F401
                     chunk_keys, pages_spanned)
from .router import (NoHealthyReplicaError, PooledStreamHandle,  # noqa: F401
                     Replica, ReplicaPool, Router,
                     TenantQuotaExceededError)
from .server import ModelServer  # noqa: F401
from .stats import LatencyWindow, ServerStats  # noqa: F401
from .control_plane import (Autoscaler, ControlPlane,  # noqa: F401,E402
                            RPCConnectionError, RemoteReplica,
                            ReplicaEndpoint, ReplicaProcess,
                            ReplicaSpawnError, serve_replica)
