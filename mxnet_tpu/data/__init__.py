"""Dataset-readiness pipelines for the BASELINE workload configs
(VERDICT r3 #6).

Everything here runs on synthetic corpora in CI; a session WITH the
real datasets (BookCorpus/Wikipedia, WMT14, GluonTS datasets) points
the same loaders at files and trains — download-and-run.

- text:       WordPiece + BPE subword tokenizers (trainable)
- bert:       MLM masking + NSP pairing batch stream (GluonNLP
              create_pretraining_data.py role)
- nmt:        parallel-corpus BPE + length-bucketed batching (WMT
              prep + Sockeye/GluonNLP data pipeline role)
- timeseries: GluonTS-style ListDataset, age/scale/time features,
              instance splitting, train/predict split (DeepAR)
"""
from . import bert, nmt, text, timeseries  # noqa: F401
from .text import BPETokenizer, WordPieceTokenizer, learn_bpe  # noqa: F401
