"""Multi-tensor fused optimizer ops.

Ref: src/operator/contrib/multi_sum_sq.{cc,cu},
src/operator/optimizer_op.cc multi_sgd_update/multi_sgd_mom_update/
multi_mp_sgd_* — one kernel launch updating MANY parameter tensors
(the launch-overhead amortization trick behind large-batch trainers).

TPU-native: a single jitted computation over the whole tensor list;
XLA fuses the per-tensor elementwise updates into few kernels, which is
the same amortization without hand-written multi-tensor-apply. Variadic
ops: inputs arrive flat, `num_arrays`/`num_weights` recovers the
grouping (matching the reference's flattened-input calling convention).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _k_multi_sum_sq(*arrays, num_arrays=0):
    """Per-tensor sum of squares -> (num_arrays,) vector
    (ref: multi_sum_sq; the grad-clipping global-norm building block)."""
    arrays = arrays[:num_arrays] if num_arrays else arrays
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32)))
                      for a in arrays])


def _split_wg(arrays, n):
    """Flat [w0,g0,w1,g1,...] -> (weights, grads) (reference layout)."""
    ws = [arrays[2 * i] for i in range(n)]
    gs = [arrays[2 * i + 1] for i in range(n)]
    return ws, gs


def _k_multi_sgd_update(*arrays, lrs=(), wds=(), num_weights=0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    """Fused SGD over many tensors (ref: multi_sgd_update)."""
    n = num_weights or len(arrays) // 2
    ws, gs = _split_wg(arrays, n)
    outs = []
    for w, g, lr, wd in zip(ws, gs, lrs, wds):
        g = g * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        outs.append(w - lr * (g + wd * w))
    return tuple(outs)


def _k_multi_sgd_mom_update(*arrays, lrs=(), wds=(), momentum=0.0,
                            num_weights=0, rescale_grad=1.0,
                            clip_gradient=-1.0):
    """Fused momentum SGD: flat [w0,g0,m0, w1,g1,m1, ...]
    (ref: multi_sgd_mom_update). Returns (new_w..., new_m...)."""
    n = num_weights or len(arrays) // 3
    outs_w, outs_m = [], []
    for i in range(n):
        w, g, m = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        lr, wd = lrs[i], wds[i]
        g = g * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        new_m = momentum * m - lr * (g + wd * w)
        outs_w.append(w + new_m)
        outs_m.append(new_m)
    return tuple(outs_w) + tuple(outs_m)


def _k_multi_mp_sgd_update(*arrays, lrs=(), wds=(), num_weights=0,
                           rescale_grad=1.0, clip_gradient=-1.0):
    """Multi-precision variant: flat [w0,g0,w32_0, ...]; the master
    fp32 copy carries the update, the bf16/fp16 weight is a cast
    (ref: multi_mp_sgd_update). Returns (new_w..., new_w32...)."""
    n = num_weights or len(arrays) // 3
    outs_w, outs_w32 = [], []
    for i in range(n):
        w, g, w32 = arrays[3 * i], arrays[3 * i + 1], arrays[3 * i + 2]
        lr, wd = lrs[i], wds[i]
        g = g.astype(jnp.float32) * rescale_grad
        if clip_gradient > 0:
            g = jnp.clip(g, -clip_gradient, clip_gradient)
        new_w32 = w32 - lr * (g + wd * w32)
        outs_w.append(new_w32.astype(w.dtype))
        outs_w32.append(new_w32)
    return tuple(outs_w) + tuple(outs_w32)


register("multi_sum_sq", _k_multi_sum_sq, arg_names=(), variadic=True,
         aliases=("_contrib_multi_sum_sq",), nondiff=True)
register("multi_sgd_update", _k_multi_sgd_update, arg_names=(),
         variadic=True, nondiff=True, num_outputs=-1)
register("multi_sgd_mom_update", _k_multi_sgd_mom_update, arg_names=(),
         variadic=True, nondiff=True, num_outputs=-1)
register("multi_mp_sgd_update", _k_multi_mp_sgd_update, arg_names=(),
         variadic=True, nondiff=True, num_outputs=-1)
