"""Pipeline stage graph — composable, checkpointable input stages.

Design (see docs/data.md): a :class:`Pipeline` is a chain of stateful
iterator stages over a ``Dataset``/``DataIter``/iterable source.  The
chain's LOGICAL core is synchronous and pull-based — every stage knows
exactly how far the consumer has advanced — while asynchrony lives in
the two places it pays off:

- :class:`MapStage` runs its fn on the engine host pool (NumPy/PIL
  release the GIL), keeping a bounded window of ordered futures in
  flight — the decode-thread role of the reference's C++ iterators.
- :class:`PrefetchToDeviceStage` pulls whole upstream batches on the
  engine's per-context ``h2d`` stream and lands them on device through
  ONE ``engine.batched_put`` submission each, ``depth`` batches ahead —
  host build + transfer overlap the consumer's previous fused step.

Every stage carries explicit iterator state (``state_dict()`` /
``load_state_dict()``): source cursor, shuffle ring + RNG, batch
rollover remainder, and — for the async stages — the in-flight items
themselves, drained to host arrays.  Restoring that state into a
freshly built identical pipeline replays the remaining batch sequence
bit-identically (the contract ``tools/pipeline_smoke.py`` gates on).
"""
from __future__ import annotations

import collections
import concurrent.futures
import time

import numpy as np

from .. import engine, profiler
from ..base import MXNetError, getenv
from ..ndarray import ndarray as _nd
from ..ndarray.ndarray import NDArray
from ..telemetry import tracer as _tracer
from . import stats as _stats

# sentinel a prefetch pull-job returns instead of raising StopIteration
# across the future boundary (futures re-raise StopIteration as a
# RuntimeError on some Python versions)
_EOS = object()


def _done_future(value):
    f = concurrent.futures.Future()
    f.set_result(value)
    return f


def default_batchify(data):
    """Stack samples into a batch (ref: default_batchify_fn — this is
    the canonical copy; gluon.data.dataloader re-exports it)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return _nd.from_jax(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        return tuple(default_batchify(list(x)) for x in zip(*data))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return _nd.array(arr)


# ---------------------------------------------------------------------------
# state packing: in-flight items (map results, prefetched device batches,
# shuffle-ring elements) are saved as host trees so a checkpoint is
# device-free and a restore can re-stage them onto any replica.


def _pack(obj):
    """Tree -> host-serializable tree (NDArray/jax leaves -> numpy)."""
    if isinstance(obj, NDArray):
        return {"__kind__": "ndarray", "v": obj.asnumpy()}
    try:
        import jax

        if isinstance(obj, jax.Array):
            return {"__kind__": "ndarray", "v": np.asarray(obj)}
    except ImportError:  # pragma: no cover
        pass
    from ..io.io import DataBatch

    if isinstance(obj, DataBatch):
        return {"__kind__": "databatch",
                "data": _pack(obj.data), "label": _pack(obj.label),
                "pad": obj.pad, "index": obj.index}
    if isinstance(obj, dict):
        return {"__kind__": "dict",
                "v": {k: _pack(v) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__kind__": type(obj).__name__,
                "v": [_pack(v) for v in obj]}
    return obj


def _unpack(obj):
    if isinstance(obj, dict) and "__kind__" in obj:
        kind = obj["__kind__"]
        if kind == "ndarray":
            return _nd.array(obj["v"], dtype=obj["v"].dtype)
        if kind == "databatch":
            from ..io.io import DataBatch

            return DataBatch(_unpack(obj["data"]), _unpack(obj["label"]),
                             pad=obj["pad"], index=obj["index"])
        if kind == "dict":
            return {k: _unpack(v) for k, v in obj["v"].items()}
        seq = [_unpack(v) for v in obj["v"]]
        return tuple(seq) if kind == "tuple" else seq
    return obj


def _flatten(obj, leaves):
    """Split a batch tree into transferable leaves + a rebuild spec, so
    one ``engine.batched_put`` moves EVERY array of the batch."""
    if isinstance(obj, NDArray):
        leaves.append(obj._data)
        return ("leaf", len(leaves) - 1)
    if isinstance(obj, np.ndarray):
        leaves.append(obj)
        return ("leaf", len(leaves) - 1)
    if isinstance(obj, (list, tuple)):
        return ("seq", type(obj) is tuple,
                [_flatten(v, leaves) for v in obj])
    if isinstance(obj, dict):
        return ("dict", [(k, _flatten(v, leaves)) for k, v in obj.items()])
    return ("raw", obj)


def _rebuild(spec, outs):
    tag = spec[0]
    if tag == "leaf":
        return _nd.from_jax(outs[spec[1]])
    if tag == "seq":
        seq = [_rebuild(s, outs) for s in spec[2]]
        return tuple(seq) if spec[1] else seq
    if tag == "dict":
        return {k: _rebuild(s, outs) for k, s in spec[1]}
    return spec[1]


# ---------------------------------------------------------------------------
# stages


class Stage:
    """One stateful iterator node; ``_up`` is the upstream stage."""

    def __init__(self, up=None):
        self._up = up

    def __iter__(self):
        return self

    def __next__(self):
        raise NotImplementedError

    def reset(self):
        if self._up is not None:
            self._up.reset()

    def state_dict(self):
        return {}

    def load_state_dict(self, state):
        pass


class DatasetSource(Stage):
    """Random-access source over a ``Dataset`` (or anything with
    ``__getitem__``/``__len__``); state is just the cursor."""

    def __init__(self, dataset):
        super().__init__()
        self._dataset = dataset
        self._cursor = 0

    def __next__(self):
        if self._cursor >= len(self._dataset):
            raise StopIteration
        item = self._dataset[self._cursor]
        self._cursor += 1
        return item

    def reset(self):
        self._cursor = 0

    def state_dict(self):
        return {"cursor": self._cursor}

    def load_state_dict(self, state):
        self._cursor = int(state["cursor"])


class IterableSource(Stage):
    """Forward-only source over any iterable (``DataIter`` included).

    A source exposing its own ``state_dict``/``load_state_dict`` (e.g.
    ``io.NDArrayIter``) resumes exactly through that; otherwise resume
    is replay-based — ``reset()`` + skip ``count`` items — which is
    bit-exact only for deterministic sources (document per source)."""

    def __init__(self, src):
        super().__init__()
        self._src = src
        self._it = None
        self._count = 0

    def _iter(self):
        if self._it is None:
            self._it = iter(self._src)
        return self._it

    def __next__(self):
        item = next(self._iter())
        self._count += 1
        return item

    def reset(self):
        if hasattr(self._src, "reset"):
            self._src.reset()
        self._it = None
        self._count = 0

    def state_dict(self):
        st = {"count": self._count}
        if hasattr(self._src, "state_dict"):
            st["src"] = self._src.state_dict()
        return st

    def load_state_dict(self, state):
        if state.get("src") is not None and hasattr(self._src,
                                                    "load_state_dict"):
            # exact resume: no reset() first — a reset may draw from the
            # global RNG (e.g. NDArrayIter's reshuffle) and desync the
            # restored stream
            self._src.load_state_dict(state["src"])
            self._it = None
            self._count = int(state["count"])
            return
        self.reset()
        for _ in range(int(state["count"])):  # replay-skip
            next(self)


class ShuffleStage(Stage):
    """Seeded ring-buffer shuffle (ref: the C++ iterators' shuffle
    chunk).  The ring holds ``buffer_size`` upstream items; each draw
    swap-pops a seeded-random slot.  ``reset()`` does NOT reseed — the
    RNG stream continues, so every epoch shuffles differently yet the
    whole multi-epoch sequence is a pure function of the seed."""

    def __init__(self, up, buffer_size, seed=0):
        super().__init__(up)
        if buffer_size < 1:
            raise MXNetError(f"shuffle buffer_size must be >= 1, "
                             f"got {buffer_size}")
        self._size = int(buffer_size)
        self._rng = np.random.RandomState(seed)
        self._ring = []
        self._exhausted = False

    def __next__(self):
        _tracer.span_begin("pipeline.shuffle.fill", "dataPipeline")
        try:
            while not self._exhausted and len(self._ring) < self._size:
                try:
                    self._ring.append(next(self._up))
                except StopIteration:
                    self._exhausted = True
        finally:
            _tracer.span_end("pipeline.shuffle.fill", "dataPipeline",
                             ring=len(self._ring))
        if not self._ring:
            raise StopIteration
        j = int(self._rng.randint(len(self._ring)))
        item = self._ring[j]
        self._ring[j] = self._ring[-1]
        self._ring.pop()
        return item

    def reset(self):
        super().reset()
        self._ring = []
        self._exhausted = False

    def state_dict(self):
        return {"ring": [_pack(v) for v in self._ring],
                "rng": self._rng.get_state(),
                "exhausted": self._exhausted}

    def load_state_dict(self, state):
        self._ring = [_unpack(v) for v in state["ring"]]
        self._rng.set_state(state["rng"])
        self._exhausted = bool(state["exhausted"])


class MapStage(Stage):
    """Ordered async map on the engine host pool, ``inflight`` items
    ahead.  State = the in-flight results themselves (materialized to
    host), so upstream state — which already reflects the pulls — stays
    consistent and a restore replays them first."""

    def __init__(self, up, fn, inflight=None, timeout=None, sync=False):
        super().__init__(up)
        self._fn = fn
        self._inflight = max(1, int(
            inflight if inflight is not None
            else getenv("PIPELINE_MAP_INFLIGHT", 4, int)))
        # 0 and None both disable the bound (ref DataLoader convention)
        self._timeout = timeout if timeout else None
        self._sync = sync
        self._pending = collections.deque()
        self._replay = collections.deque()
        self._delivered = 0
        self._exhausted = False

    def _run(self, item):
        t0 = time.perf_counter()
        with profiler.op_scope("pipeline.map", cat="dataPipeline"):
            engine.fault_point("pipeline.map")
            out = self._fn(item)
        _stats.add("host_build_ms", (time.perf_counter() - t0) * 1e3)
        return out

    def _fill(self):
        while not self._exhausted and len(self._pending) < self._inflight:
            try:
                item = next(self._up)
            except StopIteration:
                self._exhausted = True
                break
            if self._sync:
                self._pending.append(_done_future(self._run(item)))
            else:
                self._pending.append(engine.push_host(self._run, item))

    def __next__(self):
        if self._replay:
            out = self._replay.popleft()
            self._delivered += 1
            self._fill()
            return out
        self._fill()
        if not self._pending:
            raise StopIteration
        fut = self._pending.popleft()
        try:
            out = fut.result(self._timeout)
        except concurrent.futures.TimeoutError:
            raise MXNetError(
                f"pipeline map timed out after {self._timeout}s waiting "
                f"for batch {self._delivered}: the map fn (dataset "
                "__getitem__ / batchify) is stuck or too slow — raise "
                "timeout=, or inspect that batch's samples") from None
        self._delivered += 1
        self._fill()
        return out

    def reset(self):
        for f in self._pending:  # drain: fns may touch shared state
            try:
                f.result()
            except Exception:
                pass
        super().reset()
        self._pending.clear()
        self._replay.clear()
        self._delivered = 0
        self._exhausted = False

    def state_dict(self):
        # in-flight waits honor the stage timeout: a stuck map fn must
        # fail a preemption-window checkpoint loudly, not hang it past
        # the SIGKILL escalation
        try:
            drained = [f.result(self._timeout) for f in self._pending]
        except concurrent.futures.TimeoutError:
            raise MXNetError(
                f"pipeline state capture timed out after {self._timeout}s "
                "waiting for an in-flight map item: the map fn (dataset "
                "__getitem__ / batchify) is stuck — the checkpoint was "
                "NOT taken") from None
        buffered = list(self._replay) + drained
        return {"buffer": [_pack(v) for v in buffered],
                "delivered": self._delivered,
                "exhausted": self._exhausted}

    def load_state_dict(self, state):
        self._pending.clear()
        self._replay = collections.deque(
            _unpack(v) for v in state["buffer"])
        self._delivered = int(state["delivered"])
        self._exhausted = bool(state["exhausted"])


class BatchStage(Stage):
    """Group elements into batches; with a ``bucket_spec`` (a
    ``serve.BucketSpec``) the batch is padded into the spec's closed
    shape grid so a train loop sees ZERO post-warmup compiles over
    mixed-length data — the data-side twin of the serving tier's
    AOT-warmed buckets.

    ``last_batch``: 'keep' yields the partial tail, 'discard' drops it,
    'rollover' carries it into the next epoch (state: the remainder)."""

    def __init__(self, up, batch_size, last_batch="keep", batchify_fn=None,
                 bucket_spec=None):
        super().__init__(up)
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(f"unknown last_batch {last_batch!r}")
        self._bs = int(batch_size)
        self._last = last_batch
        self._fn = batchify_fn or default_batchify
        self._spec = bucket_spec
        self._rollover = []

    def __next__(self):
        batch, self._rollover = self._rollover, []
        while len(batch) < self._bs:
            try:
                batch.append(next(self._up))
            except StopIteration:
                break
        if not batch:
            raise StopIteration
        if len(batch) < self._bs:
            if self._last == "discard":
                raise StopIteration
            if self._last == "rollover":
                self._rollover = batch
                raise StopIteration
        t0 = time.perf_counter()
        with profiler.op_scope("pipeline.batch", cat="dataPipeline"):
            out = self._build(batch)
        _stats.add("host_build_ms", (time.perf_counter() - t0) * 1e3)
        return out

    def _build(self, batch):
        if self._spec is None:
            return self._fn(batch)
        # bucket padding: the FIRST component is the variable-shape
        # array the spec covers; remaining components ride along padded
        # to the same batch-bucket rows (dead rows hold zeros)
        first = [b[0] if isinstance(b, tuple) else b for b in batch]
        lengths = [self._spec.validate(np.asarray(x)) for x in first]
        b, l = self._spec.pick(
            len(batch), max(lengths) if lengths[0] is not None else None)
        if b < len(batch):
            raise MXNetError(
                f"batch_size {len(batch)} exceeds the largest bucket "
                f"batch {self._spec.max_batch}; add a bucket entry")
        data = _nd.array(self._spec.pad_batch(
            [np.asarray(x) for x in first], b, l))
        if not isinstance(batch[0], tuple):
            return data
        rest = []
        for i in range(1, len(batch[0])):
            col = np.asarray([np.asarray(x[i]) for x in batch])
            if col.dtype == np.float64:
                col = col.astype(np.float32)
            pad = np.zeros((b - col.shape[0],) + col.shape[1:], col.dtype)
            rest.append(_nd.array(np.concatenate([col, pad])
                                  if b > col.shape[0] else col))
        return (data,) + tuple(rest)

    def reset(self):
        # rollover survives reset, matching gluon BatchSampler semantics
        super().reset()

    def state_dict(self):
        return {"rollover": [_pack(v) for v in self._rollover]}

    def load_state_dict(self, state):
        self._rollover = [_unpack(v) for v in state["rollover"]]


class RebatchStage(Stage):
    """Re-chunk incoming BATCHES (arrays / tuples of arrays / DataBatch)
    to a new leading-dim size — how ``DataIter`` sources with a baked-in
    batch size adapt into a pipeline's geometry.  Host-side: leaves are
    buffered as numpy rows; state is the rollover remainder."""

    def __init__(self, up, batch_size, last_batch="keep"):
        super().__init__(up)
        if last_batch not in ("keep", "discard"):
            raise MXNetError(
                f"rebatch last_batch must be 'keep' or 'discard', "
                f"got {last_batch!r}")
        self._bs = int(batch_size)
        self._last = last_batch
        self._buf = None   # list per leaf: list of numpy chunks
        self._rows = 0
        self._exhausted = False

    @staticmethod
    def _leaves(item):
        from ..io.io import DataBatch

        pad = 0
        if isinstance(item, DataBatch):
            pad = int(item.pad or 0)  # wrap-around rows are NOT samples
            item = tuple(item.data) + tuple(item.label or ())
        if not isinstance(item, (list, tuple)):
            item = (item,)
        out = [v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
               for v in item]
        if pad:
            out = [v[:-pad] for v in out]
        return out

    def __next__(self):
        with profiler.op_scope("pipeline.rebatch", cat="dataPipeline"):
            return self._next_impl()

    def _next_impl(self):
        while self._rows < self._bs and not self._exhausted:
            try:
                leaves = self._leaves(next(self._up))
            except StopIteration:
                self._exhausted = True
                break
            if self._buf is None:
                self._buf = [[] for _ in leaves]
            if len(leaves) != len(self._buf):
                raise MXNetError(
                    f"rebatch saw {len(leaves)} leaves after "
                    f"{len(self._buf)}: upstream batches must share one "
                    "structure")
            for col, leaf in zip(self._buf, leaves):
                col.append(leaf)
            self._rows += leaves[0].shape[0]
        if self._rows == 0:
            raise StopIteration
        if self._rows < self._bs and self._last == "discard":
            self._rows = 0
            self._buf = None
            raise StopIteration
        n = min(self._bs, self._rows)
        outs, remain = [], []
        for col in self._buf:
            flat = np.concatenate(col) if len(col) > 1 else col[0]
            outs.append(_nd.array(flat[:n], dtype=flat.dtype))
            remain.append([flat[n:]] if flat.shape[0] > n else [])
        self._buf = remain if any(r for r in remain) else None
        self._rows -= n
        out = tuple(outs)
        return out[0] if len(out) == 1 else out

    def reset(self):
        super().reset()
        self._buf = None
        self._rows = 0
        self._exhausted = False

    def state_dict(self):
        buf = None
        if self._buf is not None:
            buf = [[np.concatenate(c) if len(c) > 1 else c[0]]
                   if c else [] for c in self._buf]
        return {"buf": buf, "rows": self._rows,
                "exhausted": self._exhausted}

    def load_state_dict(self, state):
        self._buf = state["buf"]
        self._rows = int(state["rows"])
        self._exhausted = bool(state["exhausted"])


class ShardStage(Stage):
    """Per-replica partition of the element stream — the data-side dual
    of cross-replica sharded weight updates (arXiv:2004.13336).

    Every rank pulls identical groups of ``num_replicas`` consecutive
    elements from its own (identically-seeded) upstream and keeps
    element ``rank``.  The uneven-tail contract is deterministic and
    rank-symmetric: ``tail='drop'`` discards the partial group on EVERY
    rank (all ranks yield the same count); ``tail='pad'`` has each rank
    take element ``rank % len(partial)`` so all ranks still yield the
    same count, with tail elements reused."""

    def __init__(self, up, num_replicas, rank, tail="drop"):
        super().__init__(up)
        if num_replicas < 1 or not 0 <= rank < num_replicas:
            raise MXNetError(
                f"need 0 <= rank < num_replicas, got rank={rank} "
                f"num_replicas={num_replicas}")
        if tail not in ("drop", "pad"):
            raise MXNetError(f"shard tail must be 'drop' or 'pad', "
                             f"got {tail!r}")
        self._n = int(num_replicas)
        self._rank = int(rank)
        self._tail = tail

    def __next__(self):
        group = []
        for _ in range(self._n):
            try:
                group.append(next(self._up))
            except StopIteration:
                break
        if not group:
            raise StopIteration
        if len(group) < self._n:
            if self._tail == "drop":
                raise StopIteration
            return group[self._rank % len(group)]
        return group[self._rank]


class PrefetchToDeviceStage(Stage):
    """Device double-buffering: ``depth`` whole batches are pulled from
    upstream AND staged onto ``ctx`` ahead of the consumer.

    Each prefetch job runs on the engine's per-context ``h2d`` stream
    (one FIFO lane — upstream is only ever advanced there, serially),
    does the upstream pull — so host batch BUILD work also runs off the
    consumer thread — and lands every array of the batch in ONE
    ``engine.batched_put`` submission.  The consumer thread only ever
    pops ready futures; with the previous fused step executing
    asynchronously on device, transfer and build overlap it fully.

    State = the prefetched-but-unconsumed batches, drained back to host;
    a restore re-stages them through the same transfer path."""

    def __init__(self, up, ctx=None, depth=None, sync=False):
        super().__init__(up)
        from ..context import Context, current_context

        self._ctx = ctx if isinstance(ctx, Context) else \
            (Context(ctx) if isinstance(ctx, str) else
             ctx or current_context())
        self._depth = max(1, int(
            depth if depth is not None
            else getenv("PIPELINE_PREFETCH", 2, int)))
        self._sync = sync
        self._stream = engine.h2d_stream(self._ctx)
        self._pending = collections.deque()
        self._exhausted = False

    def _transfer(self, item):
        t0 = time.perf_counter()
        with profiler.op_scope("pipeline.h2d", cat="dataPipeline"):
            leaves = []
            spec = _flatten(item, leaves)
            if leaves:
                outs = engine.batched_put(leaves, self._ctx.jax_device())
            else:
                outs = []
            out = _rebuild(spec, outs)
        _stats.add("h2d_ms", (time.perf_counter() - t0) * 1e3)
        return out

    def _job(self):
        try:
            item = next(self._up)
        except StopIteration:
            return _EOS
        return self._transfer(item)

    def _fill(self):
        while not self._exhausted and len(self._pending) < self._depth:
            if self._sync:
                self._pending.append(_done_future(self._job()))
                if self._pending[-1].result() is _EOS:
                    break
            else:
                self._pending.append(self._stream.push(self._job))

    def __next__(self):
        self._fill()
        while self._pending:
            fut = self._pending.popleft()
            ready = fut.done()
            out = fut.result()
            if out is _EOS:
                self._exhausted = True
                continue  # sentinel, not a batch: keep hit ratio honest
            _stats.add("prefetch_hits" if ready else "prefetch_misses", 1)
            self._fill()
            return out
        raise StopIteration

    def reset(self):
        self._drain()
        super().reset()
        self._pending.clear()
        self._exhausted = False

    def _drain(self):
        for f in self._pending:
            try:
                f.result()
            except Exception:
                pass

    def state_dict(self):
        # in-flight jobs advance upstream on the stream thread; waiting
        # them out quiesces the lane so upstream state is stable to read
        buffered = []
        for f in self._pending:
            out = f.result()
            if out is not _EOS:
                buffered.append(out)
        return {"buffer": [_pack(v) for v in buffered],
                "exhausted": self._exhausted}

    def load_state_dict(self, state):
        self._pending.clear()
        self._exhausted = bool(state["exhausted"])
        for v in state["buffer"]:  # re-stage through the transfer path
            item = _unpack(v)
            if self._sync:
                self._pending.append(_done_future(self._transfer(item)))
            else:
                self._pending.append(self._stream.push(self._transfer,
                                                       item))


# ---------------------------------------------------------------------------
# the user-facing graph


class Pipeline:
    """Composable input pipeline over a Dataset / DataIter / iterable.

    ::

        pipe = (pipeline.Pipeline(dataset)
                .shuffle(1024, seed=7)
                .map(augment)
                .batch(32, bucket_spec=spec)
                .shard(num_replicas, rank)
                .prefetch_to_device(mx.xla(0), depth=2))
        for data, label in pipe:      # one epoch; pipe.reset() for next
            ...

    A Pipeline is a single-pass stateful iterator: iterating continues
    from the current position (which is what makes a restored pipeline
    resume mid-epoch); call :meth:`reset` to start a new epoch.
    ``state_dict()``/``load_state_dict()`` snapshot/restore every
    stage; ``checkpoint.CheckpointManager.save(..., pipeline=pipe)``
    persists it atomically alongside params and trainer states.

    ``sync=True`` (or ``MXTPU_PIPELINE_SYNC=1``) forces every stage
    synchronous — the NaiveEngine-style debugging escape hatch.
    """

    def __init__(self, source, sync=None):
        self._sync = getenv("PIPELINE_SYNC", False, bool) \
            if sync is None else bool(sync)
        if isinstance(source, Stage):
            self._stages = [source]
        elif hasattr(source, "__getitem__") and hasattr(source, "__len__"):
            self._stages = [DatasetSource(source)]
        elif hasattr(source, "__iter__") or hasattr(source, "next"):
            self._stages = [IterableSource(source)]
        else:
            raise MXNetError(
                f"cannot build a pipeline from {type(source).__name__}: "
                "need a Dataset (__getitem__/__len__), a DataIter, or "
                "an iterable")

    @property
    def _tail(self):
        return self._stages[-1]

    def _add(self, stage):
        self._stages.append(stage)
        return self

    # -- stage builders ------------------------------------------------------

    def map(self, fn, inflight=None, timeout=None):
        """Apply ``fn`` per element on the host thread pool (ordered,
        ``inflight`` items ahead).  ``timeout`` (seconds) bounds the
        wait per element, raising an error naming the stuck index."""
        return self._add(MapStage(self._tail, fn, inflight=inflight,
                                  timeout=timeout, sync=self._sync))

    def shuffle(self, buffer_size, seed=0):
        """Seeded ring-buffer shuffle of ``buffer_size`` elements."""
        return self._add(ShuffleStage(self._tail, buffer_size, seed=seed))

    def batch(self, batch_size, last_batch="keep", batchify_fn=None,
              bucket_spec=None):
        """Group elements into batches; ``bucket_spec`` (a
        ``serve.BucketSpec``) pads into its closed shape grid so mixed
        lengths compile once per bucket, never per batch."""
        return self._add(BatchStage(self._tail, batch_size,
                                    last_batch=last_batch,
                                    batchify_fn=batchify_fn,
                                    bucket_spec=bucket_spec))

    def rebatch(self, batch_size, last_batch="keep"):
        """Re-chunk incoming batches (e.g. a DataIter's) to a new
        leading-dim size, carrying remainders across inputs."""
        return self._add(RebatchStage(self._tail, batch_size,
                                      last_batch=last_batch))

    def shard(self, num_replicas, rank, tail="drop"):
        """Keep this replica's 1/num_replicas of the element stream
        (deterministic drop/pad contract for uneven tails)."""
        return self._add(ShardStage(self._tail, num_replicas, rank,
                                    tail=tail))

    def prefetch_to_device(self, ctx=None, depth=None):
        """Double-buffer ``depth`` batches onto ``ctx`` via one
        ``engine.batched_put`` each, on the dedicated h2d stream."""
        return self._add(PrefetchToDeviceStage(self._tail, ctx=ctx,
                                               depth=depth,
                                               sync=self._sync))

    # -- iteration -----------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        # the wait-on-input span IS the input-bound signal in a trace:
        # long pipeline.wait slices on the consumer lane mean the chip
        # is starving (same number wait_ms aggregates)
        _tracer.span_begin("pipeline.wait", "dataPipeline")
        t0 = time.perf_counter()
        try:
            item = next(self._tail)
        finally:
            _tracer.span_end("pipeline.wait", "dataPipeline")
        _stats.add("wait_ms", (time.perf_counter() - t0) * 1e3)
        _stats.add("batches", 1)
        return item

    def reset(self):
        """Rewind every stage for a new epoch (rollover remainders and
        the shuffle RNG stream carry over, by design)."""
        self._tail.reset()

    # -- state ---------------------------------------------------------------

    def state_dict(self):
        """Snapshot every stage's iterator state (source position,
        shuffle ring + RNG, rollover remainders, in-flight batches
        drained to host).  Capture happens stage-tail-first so the
        async lanes are quiesced before upstream positions are read."""
        tail_first = [(type(s).__name__, s.state_dict())
                      for s in reversed(self._stages)]
        return {"version": 1,
                "stages": [{"type": t, "state": st}
                           for t, st in reversed(tail_first)]}

    def load_state_dict(self, state):
        """Restore into a freshly built, identically composed pipeline;
        the remaining stream replays bit-identically."""
        stages = state.get("stages")
        if state.get("version") != 1 or stages is None:
            raise MXNetError(
                f"unrecognized pipeline state (version="
                f"{state.get('version')!r}); was it saved by a newer "
                "build?")
        if len(stages) != len(self._stages) or any(
                s["type"] != type(mine).__name__
                for s, mine in zip(stages, self._stages)):
            raise MXNetError(
                "pipeline state does not match this pipeline's stages: "
                f"saved [{', '.join(s['type'] for s in stages)}] vs "
                f"built [{', '.join(type(s).__name__ for s in self._stages)}]"
                " — rebuild the pipeline with the same composition")
        for s, mine in zip(stages, self._stages):
            mine.load_state_dict(s["state"])
