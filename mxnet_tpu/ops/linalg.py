"""Linear-algebra operator family.

Ref: src/operator/tensor/la_op.{cc,cu,-inl.h} — the linalg_* ops
(BLAS3/LAPACK on mshadow streams). TPU-native: jnp.linalg/lax.linalg
primitives; XLA lowers to MXU matmuls and vendored LAPACK-style
routines, and every op is differentiable through jax autodiff (the
reference hand-writes each backward in la_op-inl.h).

Conventions follow the reference: matrices live in the last two axes,
leading axes broadcast/batch; `transpose` flags swap the last two axes;
triangular ops take `lower` (default True).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _t(x, flag):
    return jnp.swapaxes(x, -1, -2) if flag else x


def _k_gemm(A, B, C, *, transpose_a=False, transpose_b=False, alpha=1.0,
            beta=1.0, axis=-2):
    """C <- alpha * op(A) @ op(B) + beta * C (ref: linalg_gemm)."""
    out = alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))
    return out + beta * C


def _k_gemm2(A, B, *, transpose_a=False, transpose_b=False, alpha=1.0,
             axis=-2):
    """alpha * op(A) @ op(B) (ref: linalg_gemm2)."""
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))


def _k_potrf(A, *, lower=True):
    """Cholesky factor (ref: linalg_potrf)."""
    L = jnp.linalg.cholesky(A)
    return L if lower else jnp.swapaxes(L, -1, -2)


def _k_potri(A, *, lower=True):
    """Inverse from a Cholesky factor: (L L^T)^-1 (ref: linalg_potri)."""
    L = A if lower else jnp.swapaxes(A, -1, -2)
    eye = jnp.broadcast_to(jnp.eye(L.shape[-1], dtype=L.dtype), L.shape)
    Linv = jax.scipy.linalg.solve_triangular(L, eye, lower=True)
    return jnp.swapaxes(Linv, -1, -2) @ Linv


def _k_trsm(A, B, *, transpose=False, rightside=False, lower=True,
            alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B) with triangular A
    (ref: linalg_trsm)."""
    from jax.scipy.linalg import solve_triangular

    if rightside:
        # X op(A) = alpha B  <=>  op(A)^T X^T = alpha B^T
        sol = solve_triangular(
            jnp.swapaxes(A, -1, -2), jnp.swapaxes(alpha * B, -1, -2),
            trans=1 if transpose else 0, lower=not lower)
        return jnp.swapaxes(sol, -1, -2)
    return solve_triangular(A, alpha * B,
                            trans=1 if transpose else 0, lower=lower)


def _k_trmm(A, B, *, transpose=False, rightside=False, lower=True,
            alpha=1.0):
    """Triangular matmul: alpha op(tri(A)) @ B (ref: linalg_trmm)."""
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = _t(tri, transpose)
    return alpha * (B @ tri if rightside else tri @ B)


def _k_syrk(A, *, transpose=False, alpha=1.0):
    """alpha * A @ A^T (or A^T @ A) (ref: linalg_syrk)."""
    At = jnp.swapaxes(A, -1, -2)
    return alpha * ((At @ A) if transpose else (A @ At))


def _k_sumlogdiag(A):
    """sum(log(diag(A))) per matrix (ref: linalg_sumlogdiag)."""
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


def _k_makediag(A, *, offset=0):
    """Vector(s) -> diagonal matrix (ref: linalg_makediag)."""
    return jnp.apply_along_axis(
        lambda v: jnp.diag(v, k=offset), -1, A) \
        if A.ndim > 1 else jnp.diag(A, k=offset)


def _k_extractdiag(A, *, offset=0):
    """Diagonal of matrix (ref: linalg_extractdiag)."""
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


def _k_maketrian(A, *, offset=0, lower=True):
    """Packed vector -> triangular matrix (ref: linalg_maketrian)."""
    n_pack = A.shape[-1]
    # n*(n+1)/2 = n_pack (offset 0)
    import math

    n = int((math.isqrt(8 * n_pack + 1) - 1) // 2) + abs(offset)
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    out_shape = A.shape[:-1] + (n, n)
    out = jnp.zeros(out_shape, A.dtype)
    return out.at[..., rows, cols].set(A)


def _k_extracttrian(A, *, offset=0, lower=True):
    """Triangle of matrix -> packed vector (ref: linalg_extracttrian)."""
    n = A.shape[-1]
    rows, cols = jnp.tril_indices(n, k=offset) if lower \
        else jnp.triu_indices(n, k=offset)
    return A[..., rows, cols]


def _k_inverse(A):
    """Matrix inverse (ref: linalg_inverse)."""
    return jnp.linalg.inv(A)


def _k_det(A):
    """Determinant (ref: linalg_det)."""
    return jnp.linalg.det(A)


def _k_slogdet(A):
    """(sign, log|det|) (ref: linalg_slogdet)."""
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


def _k_syevd(A):
    """Symmetric eigendecomposition: (U, lambda) with A = U^T diag(l) U
    (ref: linalg_syevd; note the reference returns row-eigenvector U)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


register("linalg_gemm", _k_gemm, arg_names=("A", "B", "C"),
         aliases=("_linalg_gemm",))
register("linalg_potrf", _k_potrf, arg_names=("A",),
         aliases=("_linalg_potrf",))
register("linalg_potri", _k_potri, arg_names=("A",),
         aliases=("_linalg_potri",))
register("linalg_trsm", _k_trsm, arg_names=("A", "B"),
         aliases=("_linalg_trsm",))
register("linalg_trmm", _k_trmm, arg_names=("A", "B"),
         aliases=("_linalg_trmm",))
register("linalg_syrk", _k_syrk, arg_names=("A",),
         aliases=("_linalg_syrk",))
register("linalg_sumlogdiag", _k_sumlogdiag, arg_names=("A",),
         aliases=("_linalg_sumlogdiag",))
register("linalg_makediag", _k_makediag, arg_names=("A",),
         aliases=("_linalg_makediag",))
register("linalg_extractdiag", _k_extractdiag, arg_names=("A",),
         aliases=("_linalg_extractdiag",))
register("linalg_maketrian", _k_maketrian, arg_names=("A",),
         aliases=("_linalg_maketrian",))
register("linalg_extracttrian", _k_extracttrian, arg_names=("A",),
         aliases=("_linalg_extracttrian",))
register("linalg_inverse", _k_inverse, arg_names=("A",),
         aliases=("_linalg_inverse",))
register("linalg_det", _k_det, arg_names=("A",),
         aliases=("_linalg_det",))
register("linalg_slogdet", _k_slogdet, arg_names=("A",),
         aliases=("_linalg_slogdet",), num_outputs=2)
register("linalg_syevd", _k_syevd, arg_names=("A",),
         aliases=("_linalg_syevd",), num_outputs=2)
