"""Bounded-exponential-backoff retry policy — the supervisor's recovery
knob for the 'transient' fault class.

Deterministic: with ``jitter`` enabled the perturbation comes from the
policy's own seeded RNG, so a rehearsed recovery schedule replays
exactly (the same property :mod:`.faults` guarantees on the injection
side).
"""
from __future__ import annotations

import time

import numpy as np

from ..base import MXNetError


class RetryPolicy:
    """``delay_for(attempt)`` grows ``base_delay * multiplier**(n-1)``
    capped at ``max_delay``; ``should_retry`` bounds total attempts.

    max_retries : failures tolerated before giving up (0 = never retry)
    base_delay  : first backoff sleep, seconds
    max_delay   : backoff cap, seconds
    multiplier  : exponential growth factor
    jitter      : +/- fraction of the delay drawn from the seeded RNG
                  (0 disables; keeps herds of workers from re-trying in
                  lockstep while staying replayable)
    """

    def __init__(self, max_retries=5, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=0.0, seed=0):
        if max_retries < 0:
            raise MXNetError(f"max_retries must be >= 0, got {max_retries}")
        if base_delay < 0 or max_delay < 0 or multiplier < 1:
            raise MXNetError(
                f"need base_delay/max_delay >= 0 and multiplier >= 1, got "
                f"base_delay={base_delay} max_delay={max_delay} "
                f"multiplier={multiplier}")
        if not 0.0 <= jitter < 1.0:
            raise MXNetError(f"jitter must be in [0, 1), got {jitter}")
        self.max_retries = int(max_retries)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self._rng = np.random.RandomState(self.seed & 0x7FFFFFFF)

    def should_retry(self, attempt):
        """``attempt`` = 1-based count of failures so far."""
        return int(attempt) <= self.max_retries

    def delay_for(self, attempt):
        """Backoff before retry number ``attempt`` (1-based), seconds."""
        n = max(int(attempt), 1)
        d = min(self.base_delay * self.multiplier ** (n - 1),
                self.max_delay)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * float(self._rng.random_sample())
                                      - 1.0)
        return d

    def call(self, fn, *args, retriable=None, on_retry=None, **kwargs):
        """Run ``fn`` retrying ``retriable`` exception types with this
        policy's backoff.  ``on_retry(attempt, exc)`` (optional) is
        called before each sleep — the supervisor uses it to book the
        retry into the resilience stats."""
        if retriable is None:
            from .faults import TransientFault

            retriable = (TransientFault,)
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except retriable as e:
                attempt += 1
                if not self.should_retry(attempt):
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                time.sleep(self.delay_for(attempt))
