"""Deterministic fault-injection harness (the chaos half of
``mxnet_tpu.resilience``).

A :class:`FaultPlan` arms named **fault points** — call sites the
runtime's failure-prone seams expose via ``engine.fault_point(name,
**ctx)``.  When no plan is armed the hook is a module-level no-op (the
call is the whole cost: zero branches taken, see ``engine._fault_noop``
and the zero-overhead test); arming a plan rebinds it to the plan's
dispatcher.  Every trigger decision is a pure function of the plan
(seed + specs) and the site-hit sequence, so a chaos test replays
bit-identically.

Fault-point catalog (site -> where it fires -> ctx keys):

========================  =====================================  ==========
``train.step``            ``Supervisor`` ctx.step_done()         ``step``
``kvstore.pushpull``      top of ``KVStore.pushpull``            —
``dist.allreduce``        top of ``parallel.dist.allreduce``     —
``dist.barrier``          top of ``parallel.dist.barrier``       ``name``
``dist.rendezvous``       top of ``parallel.dist.shrink``        ``world,
                          (elastic survivor rendezvous)          dead,
                                                                 round_index``
``engine.h2d``            ``engine.batched_put``                 ``n, device``
``engine.d2h``            checkpoint d2h readback                —
``checkpoint.commit``     after shard writes, pre-manifest       ``dir, step``
``checkpoint.reshard``    elastic restore, before the            ``kind,
                          repartition is applied                 saved_world,
                                                                 world``
``pipeline.map``          ``MapStage`` worker, before the fn     —
``serve.decode``          ``DecodeServer`` token loop, pre-step  ``step, live``
``serve.replica.submit``  ``Router`` dispatch, before the        ``replica,
                          replica's ``submit()``                 attempt``
``serve.replica.health``  ``Router`` health prober, before the   ``replica``
                          probe request
``serve.rpc.send``        ``RemoteReplica`` client, before each  ``replica,
                          control-plane frame send (a raise      attempt``
                          drops the WHOLE connection — every
                          in-flight stream on it)
``serve.replica.spawn``   ``ReplicaProcess.spawn``, before the   ``replica``
                          worker process is forked
========================  =====================================  ==========

Actions:

- ``kill``      — ``os.kill(os.getpid(), SIGTERM)``: a preemption
  notice, exercising the CheckpointManager final-save hook and the
  supervisor's preemption path.
- ``raise``     — raise :class:`TransientFault` (classified by the
  supervisor as retriable: backoff + re-run from the last checkpoint).
- ``peer_death`` — raise :class:`PeerDeathFault` carrying the spec's
  ``dead_ranks``: the rank-loss rehearsal for the elastic supervisor
  (classified ``peer_death``; with elastic resize on, the supervisor
  shrinks the world by the dead ranks and resumes from the latest
  checkpoint through the resharding restore).
- ``delay`` / ``stall`` — sleep ``delay_s`` at the site (exercises the
  pipeline map timeout and the progress watchdog).
- ``truncate``  — truncate a shard file inside the in-flight checkpoint
  commit directory, so the COMMITTED checkpoint is corrupt — the
  injected failure behind the restore-fallback regression test.

``MXTPU_FAULT_PLAN`` (inline JSON or a path to a JSON file) arms a plan
for the whole process::

    MXTPU_FAULT_PLAN='{"seed": 7, "faults": [
        {"site": "train.step", "action": "kill", "match": {"step": 3}},
        {"site": "kvstore.pushpull", "action": "raise", "on_hit": 6}
    ]}'
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from contextlib import contextmanager

import numpy as np

from .. import engine
from ..base import MXNetError, getenv

_ACTIONS = ("kill", "raise", "peer_death", "delay", "stall", "truncate")


class FaultInjected(MXNetError):
    """Base class for errors raised by an armed fault plan."""


class TransientFault(FaultInjected):
    """Injected retriable failure (the supervisor's 'transient' class —
    same recovery path as a real flaky collective / transport error)."""


class PeerDeathFault(FaultInjected):
    """Injected rank loss (the supervisor's 'peer_death' class — the
    message carries the stable peer-death signature, and
    ``dead_ranks`` names the ranks the rehearsal declares lost so an
    elastic supervisor can shrink the virtual world by exactly them)."""

    def __init__(self, msg, dead_ranks=()):
        super().__init__(msg)
        self.dead_ranks = [int(r) for r in dead_ranks]


class FaultSpec:
    """One armed fault: where (``site``), what (``action``), when
    (``on_hit``/``match``/``prob``), how often (``times``).

    site    : fault-point name (see the module catalog)
    action  : 'kill' | 'raise' | 'delay' | 'stall' | 'truncate'
    on_hit  : fire only on the Nth invocation of the site (1-based);
              default: every eligible hit
    match   : dict of ctx keys that must equal the site's ctx (e.g.
              ``{"step": 3}`` on ``train.step``)
    prob    : fire with this probability per eligible hit, drawn from
              the spec's own seeded RNG (deterministic replay)
    times   : maximum fires before the spec disarms itself (default 1;
              ``None`` = unbounded)
    delay_s : sleep for 'delay'/'stall' actions (default 0.05)
    signum  : signal for 'kill' (default SIGTERM)
    dead_ranks : ranks the 'peer_death' action declares lost (the
              elastic supervisor shrinks the virtual world by them)
    """

    def __init__(self, site, action, on_hit=None, match=None, prob=None,
                 times=1, delay_s=0.05, signum=signal.SIGTERM,
                 dead_ranks=None):
        if action not in _ACTIONS:
            raise MXNetError(
                f"unknown fault action {action!r}; valid: {_ACTIONS}")
        if on_hit is not None and int(on_hit) < 1:
            raise MXNetError(f"on_hit is 1-based, got {on_hit}")
        if prob is not None and not 0.0 < float(prob) <= 1.0:
            raise MXNetError(f"prob must be in (0, 1], got {prob}")
        if times is not None and int(times) < 1:
            raise MXNetError(f"times must be >= 1 (or None), got {times}")
        self.site = str(site)
        self.action = action
        self.on_hit = None if on_hit is None else int(on_hit)
        self.match = dict(match) if match else None
        self.prob = None if prob is None else float(prob)
        self.times = None if times is None else int(times)
        self.delay_s = float(delay_s)
        self.signum = int(signum)
        self.dead_ranks = [int(r) for r in (dead_ranks or ())]
        if self.action == "peer_death" and not self.dead_ranks:
            raise MXNetError(
                "a 'peer_death' fault needs dead_ranks=[...] — the "
                "rehearsal must name which ranks the failure kills")
        self._left = self.times  # None = unbounded
        self._rng = None         # seeded by the owning plan

    def _reset(self, seed, index):
        self._left = self.times
        self._rng = np.random.RandomState((int(seed) + 7919 * index)
                                          & 0x7FFFFFFF)


class FaultPlan:
    """A seeded, deterministic set of :class:`FaultSpec`\\ s.

    ``arm()`` rebinds ``engine.fault_point`` to this plan's dispatcher;
    ``disarm()`` restores the no-op.  ``fired()`` returns the replay
    record — the exact (site, action, hit) sequence that fired — which
    is a pure function of the plan and the site-hit sequence.
    """

    def __init__(self, faults=(), seed=0):
        self.seed = int(seed)
        self._specs = []
        self._lock = threading.Lock()
        self._hits = {}
        self._fired = []
        for f in faults:
            self.add(f if isinstance(f, FaultSpec) else FaultSpec(**f))

    def add(self, spec):
        if not isinstance(spec, FaultSpec):
            raise MXNetError(
                f"FaultPlan.add wants a FaultSpec, got {type(spec).__name__}")
        spec._reset(self.seed, len(self._specs))
        self._specs.append(spec)
        return self

    # -- arming --------------------------------------------------------------

    def arm(self):
        engine.set_fault_dispatcher(self.fire)
        return self

    def disarm(self):
        # `fault_point` holds a bound `fire`; compare receivers (a fresh
        # `self.fire` is a new bound-method object, `is` would miss)
        if getattr(engine.fault_point, "__self__", None) is self:
            engine.set_fault_dispatcher(None)

    def reset(self):
        """Rewind hit counters, fire budgets and per-spec RNGs so the
        same plan replays the same decisions (determinism contract)."""
        with self._lock:
            self._hits.clear()
            self._fired.clear()
            for i, spec in enumerate(self._specs):
                spec._reset(self.seed, i)
        return self

    # -- introspection -------------------------------------------------------

    def fired(self):
        """The replay record: list of {site, action, hit, ctx} dicts in
        fire order."""
        with self._lock:
            return [dict(f) for f in self._fired]

    def hits(self, site=None):
        with self._lock:
            return dict(self._hits) if site is None \
                else self._hits.get(site, 0)

    # -- dispatch ------------------------------------------------------------

    def fire(self, site, /, **ctx):
        """The armed ``engine.fault_point`` binding: count the hit, find
        the first eligible spec, perform its action.  (`site` is
        positional-only so ctx keys like `name` never clash.)"""
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            todo = None
            for spec in self._specs:
                if spec.site != site or spec._left == 0:
                    continue
                if spec.match is not None and any(
                        ctx.get(k) != v for k, v in spec.match.items()):
                    continue
                if spec.on_hit is not None and hit != spec.on_hit:
                    continue
                if spec.prob is not None and \
                        float(spec._rng.random_sample()) >= spec.prob:
                    continue
                if spec._left is not None:
                    spec._left -= 1
                self._fired.append({
                    "site": site, "action": spec.action, "hit": hit,
                    "ctx": {k: v for k, v in ctx.items()
                            if isinstance(v, (int, float, str, bool))}})
                todo = spec
                break
        if todo is not None:
            self._perform(todo, site, hit, ctx)

    def _perform(self, spec, site, hit, ctx):
        if spec.action in ("delay", "stall"):
            time.sleep(spec.delay_s)
            return
        if spec.action == "raise":
            raise TransientFault(
                f"injected transient fault at {site!r} (hit {hit}) — "
                "armed by the active FaultPlan (chaos rehearsal, not a "
                "real failure)")
        if spec.action == "peer_death":
            # the stable phrase below is dist._peer_death_msg's
            # signature, so classify() routes this like a real dead peer
            raise PeerDeathFault(
                f"injected peer death at {site!r} (hit {hit}): rank(s) "
                f"{spec.dead_ranks} likely dead or partitioned — armed "
                "by the active FaultPlan (chaos rehearsal, not a real "
                "failure); an elastic Supervisor treats this as a "
                "resize event",
                dead_ranks=spec.dead_ranks)
        if spec.action == "kill":
            os.kill(os.getpid(), spec.signum)
            return
        # truncate: corrupt a shard file inside the in-flight commit dir
        # so the checkpoint COMMITS with a truncated payload
        d = ctx.get("dir")
        if not d or not os.path.isdir(d):
            raise MXNetError(
                f"'truncate' fault fired at {site!r} without a commit "
                "dir in ctx — arm it on 'checkpoint.commit'")
        names = sorted(os.listdir(d))
        target = next((n for n in names if n.startswith("params-shard")),
                      None) or next(
            (n for n in names
             if os.path.isfile(os.path.join(d, n))), None)
        if target is None:  # empty commit (metadata-only save): no-op
            return
        p = os.path.join(d, target)
        size = os.path.getsize(p)
        with open(p, "r+b") as f:
            f.truncate(max(size // 2, 1))


# ---------------------------------------------------------------------------
# module-level install surface


def install_plan(plan):
    """Arm ``plan`` process-wide (programmatic form of
    ``MXTPU_FAULT_PLAN``)."""
    if not isinstance(plan, FaultPlan):
        raise MXNetError(
            f"install_plan wants a FaultPlan, got {type(plan).__name__}")
    return plan.arm()


def clear_plan():
    """Disarm any installed plan; ``engine.fault_point`` is the no-op
    again."""
    engine.set_fault_dispatcher(None)


@contextmanager
def armed(plan):
    """Scoped arming for tests: arm on enter, disarm on exit."""
    plan.arm()
    try:
        yield plan
    finally:
        plan.disarm()


def parse_plan(text):
    """Build a :class:`FaultPlan` from inline JSON or a JSON file path
    (the ``MXTPU_FAULT_PLAN`` format: ``{"seed": int, "faults":
    [{"site": ..., "action": ..., ...}, ...]}``)."""
    raw = text
    if os.path.isfile(text):
        with open(text) as f:
            raw = f.read()
    try:
        obj = json.loads(raw)
    except ValueError as e:
        raise MXNetError(
            f"MXTPU_FAULT_PLAN is neither a JSON object nor a readable "
            f"JSON file ({e}); see docs/resilience.md for the format") \
            from None
    if not isinstance(obj, dict) or not isinstance(obj.get("faults"),
                                                   list):
        raise MXNetError(
            "MXTPU_FAULT_PLAN must be a JSON object with a 'faults' "
            "list (and an optional integer 'seed')")
    try:
        return FaultPlan(obj["faults"], seed=obj.get("seed", 0))
    except TypeError as e:
        raise MXNetError(f"bad fault spec in MXTPU_FAULT_PLAN: {e}") \
            from None


_env_installed = False
_env_mu = threading.Lock()


def install_from_env():
    """Arm the ``MXTPU_FAULT_PLAN`` plan (idempotent; no-op when the
    env var is unset).  Called lazily by the engine's bootstrap hook on
    the first fault-point fire of a process started with the var set —
    which can land concurrently from pool workers, so exactly ONE plan
    instance must win (two would split hit counts and double-fire
    ``times``-budgeted specs, breaking the determinism contract)."""
    global _env_installed
    with _env_mu:
        if _env_installed:
            return
        spec = getenv("FAULT_PLAN")
        if not spec:
            engine.set_fault_dispatcher(None)  # clear a stale bootstrap
            _env_installed = True
            return
        install_plan(parse_plan(spec))
        _env_installed = True
