"""Gluon losses (ref: python/mxnet/gluon/loss.py)."""
from __future__ import annotations

from .block import HybridBlock


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, pred, label):
    if label.shape != pred.shape:
        return label.reshape(pred.shape)
    return label


class Loss(HybridBlock):
    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.square(label - pred)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.abs(label - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None,
                       pos_weight=None):
        label = _reshape_like(F, pred, label)
        if not self._from_sigmoid:
            if pos_weight is None:
                # log-sum-exp stable form
                loss = F.relu(pred) - pred * label + \
                    F.Activation(-F.abs(pred), act_type="softrelu")
            else:
                # pw*y*softplus(-x) + (1-y)*softplus(x), both softplus stable
                loss = (F.broadcast_mul(label, pos_weight)
                        * F.Activation(-pred, act_type="softrelu")
                        + (1.0 - label)
                        * F.Activation(pred, act_type="softrelu"))
        else:
            eps = 1e-12
            if pos_weight is None:
                loss = -(F.log(pred + eps) * label
                         + F.log(1.0 - pred + eps) * (1.0 - label))
            else:
                loss = -(F.broadcast_mul(F.log(pred + eps) * label,
                                         pos_weight)
                         + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """Ref: gluon.loss.SoftmaxCrossEntropyLoss."""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, pred, label)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.abs(label - pred)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, pred, label)
        if self._label_format == "signed":
            label = (label + 1.0) / 2.0
        loss = F.relu(pred) - pred * label + \
            F.Activation(-F.abs(pred), act_type="softrelu")
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative, sample_weight=None):
        positive = _reshape_like(F, pred, positive)
        negative = _reshape_like(F, pred, negative)
        loss = F.sum(F.square(positive - pred) - F.square(negative - pred),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        return _apply_weighting(F, loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def hybrid_forward(self, F, pred, target, sample_weight=None,
                       epsilon=1e-08):
        target = _reshape_like(F, pred, target)
        if self._from_logits:
            loss = F.exp(pred) - target * pred
        else:
            loss = pred - target * F.log(pred + epsilon)
        if self._compute_full:
            # Stirling approximation for log(target!)
            stirling = (target * F.log(target + 1e-12) - target
                        + 0.5 * F.log(2 * 3.1415926535 * (target + 1e-12)))
            stirling = F.where(target <= 1, F.zeros_like(target), stirling)
            loss = loss + stirling
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss)


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, input1, input2, label, sample_weight=None):
        eps = 1e-12
        prod = F.sum(input1 * input2, axis=-1)
        n1 = F.sqrt(F.sum(F.square(input1), axis=-1) + eps)
        n2 = F.sqrt(F.sum(F.square(input2), axis=-1) + eps)
        cos = prod / (n1 * n2)
        label = label.reshape(cos.shape)
        loss = F.where(label == 1, 1.0 - cos,
                       F.relu(cos - self._margin))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class CTCLoss(Loss):
    """Connectionist Temporal Classification loss (ref:
    gluon.loss.CTCLoss over src/operator/contrib/ctc_loss.cc).

    layout: 'NTC' (default, batch-major) or 'TNC'; label_layout 'NT'.
    """

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise ValueError(f"unsupported pred layout {layout!r}")
        if label_layout not in ("NT", "TN"):
            raise ValueError(f"unsupported label layout {label_layout!r}")
        self._layout = layout
        self._label_layout = label_layout
        batch_axis = 0 if label_layout == "NT" else 1
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        if self._layout == "NTC":
            pred = F.swapaxes(pred, dim1=0, dim2=1)    # -> (T, N, C)
        if self._label_layout == "TN":
            label = F.swapaxes(label, dim1=0, dim2=1)  # -> (N, L)
        loss = F.CTCLoss(pred, label, pred_lengths, label_lengths,
                         use_data_lengths=pred_lengths is not None,
                         use_label_lengths=label_lengths is not None,
                         blank_label="last")
        return _apply_weighting(F, loss, self._weight, sample_weight)
