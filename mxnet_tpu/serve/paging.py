"""Paged KV-cache bookkeeping: page allocator, refcounts, prefix index.

The decode tier's paged arena (``DecodeServer(page_tokens=...)``) keeps
its cache buffers as ``(num_pages + 1, page_tokens, ...)`` pools and
maps each slot's logical ``[0, pages_per_slot * page_tokens)`` token
range onto physical pages through a per-slot page table.  Everything in
this module is HOST-side bookkeeping — pure python over small ints,
mutated only between token boundaries by the decode loop thread — so
the device-side executables stay fixed-shape: the page table rides into
the step as a traced ``(max_slots, pages_per_slot)`` int32 input, and
gather/scatter against it happens inside the one pre-warmed executable.

Three pieces:

:class:`PageAllocator`
    Free-list + refcount ledger over ``num_pages`` physical pages.
    Index ``num_pages`` (``.trash``) is a reserved sink page appended
    to every pool: unmapped page-table entries point at it, so masked
    scatters of inactive/unallocated rows land somewhere harmless
    instead of needing data-dependent shapes.  ``check()`` asserts the
    no-leak invariant (every page is exactly one of free / refcounted
    live) — the fragmentation test's anchor.

:class:`PrefixIndex`
    Prompt-prefix dedup at page granularity.  Admission hashes each
    page-sized chunk of the prompt CHAINED (the key digests the whole
    prefix through that chunk, not the chunk alone, so equal chunks at
    different positions or after different histories never collide);
    a hit maps the new slot's page-table entry onto the existing page
    with a refcount bump, a miss allocates and registers.  Entries are
    dropped the moment their page's refcount hits zero (eviction only
    at refcount zero): sharing happens among overlapping-lifetime
    requests, and a freed page can never be resurrected stale.

:func:`chunk_keys` / :func:`pages_spanned`
    The hashing and sizing helpers the server's admission path uses.

Copy-on-write is decided here only in the sense that the allocator
exposes refcounts; the actual page copy is folded into the decode step
executable (see ``serve/decode.py``): when the decode loop finds the
write-frontier page shared (``ref > 1``) it allocates a private page,
redirects the slot's page-table entry, and passes the (src, dst) pair
into the step, which copies the page on-device before the gather — no
extra dispatch, no host round-trip.
"""
from __future__ import annotations

import hashlib

import numpy as np

from ..base import MXNetError

__all__ = ["PageAllocator", "PrefixIndex", "chunk_keys", "pages_spanned"]


def pages_spanned(tokens, page_tokens):
    """Pages covering ``tokens`` positions (ceil division)."""
    return -(-int(tokens) // int(page_tokens))


def chunk_keys(prompt, length, page_tokens):
    """Chained page-granularity prefix keys for one prompt.

    Returns one key per prompt page, in page order: full pages get a
    ``("F", i, digest-of-prompt[: (i+1)*T])`` key; a trailing partial
    page gets a ``("P", i, length, digest-of-prompt[:length])`` key.
    The digest always covers the WHOLE prefix through the chunk, so a
    hit guarantees every earlier page matched too, and the full/partial
    kind plus real length in the key keep a partial tail from ever
    colliding with a full page of a longer prompt.
    """
    t = int(page_tokens)
    n = int(length)
    p = np.ascontiguousarray(np.asarray(prompt)[:n], dtype=np.int32)
    keys = []
    h = hashlib.sha1()
    full = n // t
    for i in range(full):
        h.update(p[i * t:(i + 1) * t].tobytes())
        keys.append(("F", i, h.hexdigest()))
    rem = n - full * t
    if rem:
        h.update(p[full * t:n].tobytes())
        keys.append(("P", full, n, h.hexdigest()))
    return keys


class PageAllocator:
    """Free-list + refcount ledger for the paged arena's physical pages.

    Pages are plain ints in ``[0, num_pages)``; ``trash`` (==
    ``num_pages``) is the reserved sink page that exists in the device
    pools but is never allocated — page-table entries that map nothing
    point at it.  All methods are called from the decode loop thread
    only (admission and token boundaries are already serialized), so
    there is no internal lock.
    """

    def __init__(self, num_pages, page_tokens):
        self.num_pages = int(num_pages)
        self.page_tokens = int(page_tokens)
        if self.num_pages < 1 or self.page_tokens < 1:
            raise MXNetError(
                f"PageAllocator needs num_pages >= 1 and page_tokens "
                f">= 1, got {num_pages} x {page_tokens}")
        self.trash = self.num_pages
        # LIFO free list, low indices first out — steady churn reuses
        # a warm working set of pages instead of striding the pool
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._ref = [0] * self.num_pages
        self.allocs = 0
        self.frees = 0

    def alloc(self):
        """Take one free page at refcount 1.  Exhaustion here is a
        bookkeeping BUG (admission commits worst-case pages up front),
        so it raises instead of returning a sentinel."""
        if not self._free:
            raise MXNetError(
                f"page pool exhausted ({self.num_pages} pages of "
                f"{self.page_tokens} tokens) — admission token-budget "
                f"accounting let an uncovered allocation through")
        page = self._free.pop()
        self._ref[page] = 1
        self.allocs += 1
        return page

    def retain(self, page):
        """Add one reference to a live page (a prefix-sharing hit)."""
        if not 0 <= page < self.num_pages or self._ref[page] < 1:
            raise MXNetError(f"retain() of non-live page {page}")
        self._ref[page] += 1
        return page

    def release(self, page):
        """Drop one reference; frees the page (returns True) when the
        count hits zero — eviction happens at refcount zero, never
        earlier."""
        if not 0 <= page < self.num_pages or self._ref[page] < 1:
            raise MXNetError(f"release() of non-live page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)
            self.frees += 1
            return True
        return False

    def ref(self, page):
        """Current refcount (0 = free)."""
        return self._ref[page]

    def free_count(self):
        return len(self._free)

    def live_count(self):
        return self.num_pages - len(self._free)

    def check(self):
        """Assert the no-leak invariant: every page is exactly one of
        free (ref 0) or live (ref >= 1), with no duplicates in the free
        list.  Returns self so tests can chain."""
        if len(set(self._free)) != len(self._free):
            raise MXNetError("page free list holds duplicates")
        for page in self._free:
            if self._ref[page] != 0:
                raise MXNetError(
                    f"page {page} is free but has refcount "
                    f"{self._ref[page]}")
        live = sum(1 for r in self._ref if r > 0)
        if live + len(self._free) != self.num_pages:
            raise MXNetError(
                f"page ledger leak: {live} live + {len(self._free)} "
                f"free != {self.num_pages} pages")
        return self


class PrefixIndex:
    """Chained prefix-hash -> live page map (storage dedup).

    One entry per registered chunk key; the reverse map lets the
    allocator's free path invalidate every key pointing at a page the
    moment it is evicted, so a lookup can never hand out a freed (or
    recycled) page.
    """

    def __init__(self):
        self._by_key = {}
        self._by_page = {}

    def lookup(self, key):
        """Live page for this chunk key, or None (pure; no refcount
        side effects — the caller retains on use)."""
        return self._by_key.get(key)

    def register(self, key, page):
        """Publish a freshly written page under its chunk key.  First
        writer wins: re-registering a key is a no-op (two identical
        prompts admitted in one group race to the same key; the second
        should have hit instead, but dropping the duplicate keeps the
        index consistent either way)."""
        if key not in self._by_key:
            self._by_key[key] = page
            self._by_page.setdefault(page, set()).add(key)
        return self._by_key[key]

    def drop_page(self, page):
        """Invalidate every key for an evicted page."""
        for key in self._by_page.pop(page, ()):
            self._by_key.pop(key, None)

    def __len__(self):
        return len(self._by_key)
