"""AlexNet / VGG / MobileNet / SqueezeNet / DenseNet / LeNet
(ref: python/mxnet/gluon/model_zoo/vision/{alexnet,vgg,mobilenet,
squeezenet,densenet}.py)."""
from __future__ import annotations

from ....base import MXNetError
from ...block import HybridBlock
from ... import nn


class LeNet(HybridBlock):
    """The BASELINE LeNet/MNIST model (ref: example/image-classification)."""

    def __init__(self, classes=10, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(20, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(50, kernel_size=5, activation="tanh"),
            nn.MaxPool2D(2, 2),
            nn.Flatten(),
            nn.Dense(500, activation="tanh"))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class AlexNet(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(
            nn.Conv2D(64, 11, 4, 2, activation="relu"),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 5, padding=2, activation="relu"),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(384, 3, padding=1, activation="relu"),
            nn.Conv2D(256, 3, padding=1, activation="relu"),
            nn.Conv2D(256, 3, padding=1, activation="relu"),
            nn.MaxPool2D(3, 2),
            nn.Flatten(),
            nn.Dense(4096, activation="relu"), nn.Dropout(0.5),
            nn.Dense(4096, activation="relu"), nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


vgg_spec = {11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
            13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
            16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
            19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512])}


class VGG(HybridBlock):
    def __init__(self, layers, filters, classes=1000, batch_norm=False,
                 **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        for i, num in enumerate(layers):
            for _ in range(num):
                self.features.add(nn.Conv2D(filters[i], 3, padding=1))
                if batch_norm:
                    self.features.add(nn.BatchNorm())
                self.features.add(nn.Activation("relu"))
            self.features.add(nn.MaxPool2D(2, 2))
        self.features.add(nn.Flatten())
        self.features.add(nn.Dense(4096, activation="relu"), nn.Dropout(0.5))
        self.features.add(nn.Dense(4096, activation="relu"), nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def get_vgg(num_layers, **kwargs):
    if kwargs.pop("pretrained", False):
        raise MXNetError("pretrained weights unavailable (no egress)")
    layers, filters = vgg_spec[num_layers]
    return VGG(layers, filters, **kwargs)


class MobileNet(HybridBlock):
    """MobileNet v1 (depthwise separable convs)."""

    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()

        def conv_bn(c, k, s, p, g=1):
            self.features.add(nn.Conv2D(c, k, s, p, groups=g,
                                        use_bias=False))
            self.features.add(nn.BatchNorm())
            self.features.add(nn.Activation("relu"))

        dw_channels = [int(x * multiplier) for x in
                       [32, 64] + [128] * 2 + [256] * 2 + [512] * 6
                       + [1024]]
        channels = [int(x * multiplier) for x in
                    [64] + [128] * 2 + [256] * 2 + [512] * 6 + [1024] * 2]
        strides = [1, 2] * 3 + [1] * 5 + [2, 1]
        conv_bn(dw_channels[0], 3, 2, 1)
        for dwc, c, s in zip(dw_channels, channels, strides):
            conv_bn(dwc, 3, s, 1, g=dwc)  # depthwise
            conv_bn(c, 1, 1, 0)           # pointwise
        self.features.add(nn.GlobalAvgPool2D())
        self.features.add(nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class _InvertedResidual(HybridBlock):
    def __init__(self, in_c, c, stride, expand, **kwargs):
        super().__init__(**kwargs)
        self.use_shortcut = stride == 1 and in_c == c
        mid = in_c * expand
        self.out = nn.HybridSequential()
        if expand != 1:
            self.out.add(nn.Conv2D(mid, 1, use_bias=False), nn.BatchNorm())
            self.out.add(nn.Activation("relu"))
        self.out.add(nn.Conv2D(mid, 3, stride, 1, groups=mid,
                               use_bias=False), nn.BatchNorm())
        self.out.add(nn.Activation("relu"))
        self.out.add(nn.Conv2D(c, 1, use_bias=False), nn.BatchNorm())

    def hybrid_forward(self, F, x):
        out = self.out(x)
        if self.use_shortcut:
            return out + x
        return out


class MobileNetV2(HybridBlock):
    def __init__(self, multiplier=1.0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        m = multiplier
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(int(32 * m), 3, 2, 1, use_bias=False),
                          nn.BatchNorm(), nn.Activation("relu"))
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = int(32 * m)
        for t, c, n, s in cfg:
            c = int(c * m)
            for i in range(n):
                self.features.add(_InvertedResidual(
                    in_c, c, s if i == 0 else 1, t))
                in_c = c
        last = int(1280 * max(1.0, m))
        self.features.add(nn.Conv2D(last, 1, use_bias=False),
                          nn.BatchNorm(), nn.Activation("relu"),
                          nn.GlobalAvgPool2D(), nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class SqueezeNet(HybridBlock):
    def __init__(self, version="1.0", classes=1000, **kwargs):
        super().__init__(**kwargs)

        def fire(squeeze, expand):
            out = nn.HybridSequential()
            out.add(nn.Conv2D(squeeze, 1, activation="relu"))
            exp = _FireExpand(expand)
            out.add(exp)
            return out

        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, 7, 2, activation="relu"),
                              nn.MaxPool2D(3, 2))
            for sq, ex in [(16, 64), (16, 64), (32, 128)]:
                self.features.add(fire(sq, ex))
            self.features.add(nn.MaxPool2D(3, 2))
            for sq, ex in [(32, 128), (48, 192), (48, 192), (64, 256)]:
                self.features.add(fire(sq, ex))
            self.features.add(nn.MaxPool2D(3, 2))
            self.features.add(fire(64, 256))
        else:
            self.features.add(nn.Conv2D(64, 3, 2, activation="relu"),
                              nn.MaxPool2D(3, 2))
            for sq, ex in [(16, 64), (16, 64)]:
                self.features.add(fire(sq, ex))
            self.features.add(nn.MaxPool2D(3, 2))
            for sq, ex in [(32, 128), (32, 128)]:
                self.features.add(fire(sq, ex))
            self.features.add(nn.MaxPool2D(3, 2))
            for sq, ex in [(48, 192), (48, 192), (64, 256), (64, 256)]:
                self.features.add(fire(sq, ex))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, 1, activation="relu"),
                        nn.GlobalAvgPool2D(), nn.Flatten())

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


class _FireExpand(HybridBlock):
    def __init__(self, expand, **kwargs):
        super().__init__(**kwargs)
        self.e1 = nn.Conv2D(expand, 1, activation="relu")
        self.e3 = nn.Conv2D(expand, 3, padding=1, activation="relu")

    def hybrid_forward(self, F, x):
        return F.concat(self.e1(x), self.e3(x), dim=1)


class _DenseLayer(HybridBlock):
    def __init__(self, growth_rate, bn_size, dropout, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(bn_size * growth_rate, 1, use_bias=False),
                      nn.BatchNorm(), nn.Activation("relu"),
                      nn.Conv2D(growth_rate, 3, padding=1, use_bias=False))
        if dropout:
            self.body.add(nn.Dropout(dropout))

    def hybrid_forward(self, F, x):
        return F.concat(x, self.body(x), dim=1)


densenet_spec = {121: (64, 32, [6, 12, 24, 16]),
                 161: (96, 48, [6, 12, 36, 24]),
                 169: (64, 32, [6, 12, 32, 32]),
                 201: (64, 32, [6, 12, 48, 32])}


class DenseNet(HybridBlock):
    def __init__(self, num_init_features, growth_rate, block_config,
                 bn_size=4, dropout=0, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(nn.Conv2D(num_init_features, 7, 2, 3,
                                    use_bias=False),
                          nn.BatchNorm(), nn.Activation("relu"),
                          nn.MaxPool2D(3, 2, 1))
        channels = num_init_features
        for i, num_layers in enumerate(block_config):
            for _ in range(num_layers):
                self.features.add(_DenseLayer(growth_rate, bn_size, dropout))
            channels += num_layers * growth_rate
            if i != len(block_config) - 1:
                self.features.add(nn.BatchNorm(), nn.Activation("relu"),
                                  nn.Conv2D(channels // 2, 1,
                                            use_bias=False),
                                  nn.AvgPool2D(2, 2))
                channels //= 2
        self.features.add(nn.BatchNorm(), nn.Activation("relu"),
                          nn.GlobalAvgPool2D(), nn.Flatten())
        self.output = nn.Dense(classes)

    def hybrid_forward(self, F, x):
        return self.output(self.features(x))


def alexnet(**kw):
    kw.pop("pretrained", None)
    return AlexNet(**kw)


def lenet(**kw):
    return LeNet(**kw)


def vgg11(**kw):
    return get_vgg(11, **kw)


def vgg13(**kw):
    return get_vgg(13, **kw)


def vgg16(**kw):
    return get_vgg(16, **kw)


def vgg19(**kw):
    return get_vgg(19, **kw)


def vgg11_bn(**kw):
    return get_vgg(11, batch_norm=True, **kw)


def vgg13_bn(**kw):
    return get_vgg(13, batch_norm=True, **kw)


def vgg16_bn(**kw):
    return get_vgg(16, batch_norm=True, **kw)


def vgg19_bn(**kw):
    return get_vgg(19, batch_norm=True, **kw)


def mobilenet1_0(**kw):
    kw.pop("pretrained", None)
    return MobileNet(1.0, **kw)


def mobilenet0_5(**kw):
    kw.pop("pretrained", None)
    return MobileNet(0.5, **kw)


def mobilenet0_75(**kw):
    kw.pop("pretrained", None)
    return MobileNet(0.75, **kw)


def mobilenet0_25(**kw):
    kw.pop("pretrained", None)
    return MobileNet(0.25, **kw)


def mobilenet_v2_1_0(**kw):
    kw.pop("pretrained", None)
    return MobileNetV2(1.0, **kw)


def mobilenet_v2_0_75(**kw):
    kw.pop("pretrained", None)
    return MobileNetV2(0.75, **kw)


def mobilenet_v2_0_5(**kw):
    kw.pop("pretrained", None)
    return MobileNetV2(0.5, **kw)


def mobilenet_v2_0_25(**kw):
    kw.pop("pretrained", None)
    return MobileNetV2(0.25, **kw)


def squeezenet1_0(**kw):
    kw.pop("pretrained", None)
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(**kw):
    kw.pop("pretrained", None)
    return SqueezeNet("1.1", **kw)


def densenet121(**kw):
    kw.pop("pretrained", None)
    return DenseNet(*densenet_spec[121], **kw)


def densenet161(**kw):
    kw.pop("pretrained", None)
    return DenseNet(*densenet_spec[161], **kw)


def densenet169(**kw):
    kw.pop("pretrained", None)
    return DenseNet(*densenet_spec[169], **kw)


def densenet201(**kw):
    kw.pop("pretrained", None)
    return DenseNet(*densenet_spec[201], **kw)
