"""Utility subsystems: serialization, download, misc helpers."""
from . import serialization  # noqa: F401
