"""Fused conv1x1+BN+ReLU ops (the cuDNN fused-op era, tpu-style).

Ref: src/operator/nn/batch_norm.cu + cudnn
CUDNN_FUSED_SCALE_BIAS_ACTIVATION_CONV_BNSTATS — the reference's fused
scale-bias-act-conv-bnstats kernels.  Capability upgrade per the r2
roofline analysis (docs/BENCHMARKS.md): XLA keeps BN's stats and
normalize passes as separate HBM round trips, bounding ResNet-50 near
20% MFU on v5e; these ops fuse them into the 1x1 convolutions'
matmuls via the Pallas kernels in ops/pallas/conv_fused.py.

Two ops, chained by the model block (gluon model_zoo BottleneckV1 under
``MXTPU_CONV_EPILOGUE=pallas``, NHWC only):

- ``_contrib_conv1x1_bn_act``: 1x1 conv (optionally consuming the
  previous BN's normalize+ReLU fused into its input read) whose
  epilogue computes THIS layer's BN statistics; outputs the RAW conv
  activation plus the folded (scale, shift) for the next consumer and
  the updated moving stats.
- ``_contrib_bn_fold``: stats + affine folding WITHOUT materializing a
  normalized activation (for 3x3 convs that stay on the XLA conv path
  but whose consumer is a fused 1x1).

Gradients flow through scale/shift back into the producing stats
(standard train-mode BN autodiff, composed from the kernels' custom
VJPs).  Off-TPU or on non-tiling shapes the kernels fall back to jnp
reference forms, so these ops are correct everywhere and fast where it
matters.
"""
from __future__ import annotations

import warnings

import jax.numpy as jnp
from jax import lax

from .registry import register


def _fold_stats(s, q, n, gamma, beta, moving_mean, moving_var, *, eps,
                momentum, fix_gamma, train):
    """(scale, shift, new_mm, new_mv) from epilogue sums (train) or the
    moving stats (eval).  Mirrors ops/nn._k_batch_norm's math."""
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if train:
        mean = (s / n).reshape(-1)
        var = jnp.maximum((q / n).reshape(-1) - jnp.square(mean), 0.0)
        new_mm = moving_mean * momentum \
            + mean.astype(moving_mean.dtype) * (1 - momentum)
        new_mv = moving_var * momentum \
            + var.astype(moving_var.dtype) * (1 - momentum)
    else:
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        new_mm, new_mv = moving_mean, moving_var
    scale = g.astype(jnp.float32) * lax.rsqrt(var + eps)
    shift = beta.astype(jnp.float32) - mean * scale
    return (scale, shift, lax.stop_gradient(new_mm),
            lax.stop_gradient(new_mv))


def _k_conv1x1_bn_act(data, weight, gamma, beta, moving_mean, moving_var,
                      in_scale=None, in_shift=None, *, stride=1, eps=1e-5,
                      momentum=0.9, fix_gamma=False, in_act=True,
                      _train=False):
    """data NHWC (N,H,W,Cin); weight OHWI (Cout,1,1,Cin).

    Returns (y_raw NHWC, scale (Cout,), shift (Cout,), new_moving_mean,
    new_moving_var): y_raw is the UN-normalized conv output; the caller
    (or the next fused op) applies ``y*scale+shift``.  With
    in_scale/in_shift the previous BN's normalize (+ReLU when in_act)
    rides inside this matmul's input read."""
    from .pallas import conv_fused as _cf

    s = int(stride)
    N, H, W, Cin = data.shape
    Cout = weight.shape[0]
    if weight.shape[1] != 1 or weight.shape[2] != 1:
        raise ValueError(
            f"conv1x1_bn_act needs a 1x1 OHWI weight, got {weight.shape}")
    if s > 1:
        data = data[:, ::s, ::s, :]
        H, W = data.shape[1], data.shape[2]
    x2d = data.reshape(N * H * W, Cin)
    w2d = weight.reshape(Cout, Cin).T
    n = x2d.shape[0]

    if _train:
        if in_scale is not None:
            y2d, ss, qq = _cf.bn_act_matmul_stats(
                x2d, in_scale.reshape(1, -1), in_shift.reshape(1, -1),
                w2d, bool(in_act))
        else:
            y2d, ss, qq = _cf.matmul_bn_stats(x2d, w2d)
    else:
        ss = qq = None
        if in_scale is not None:
            y2d = _cf.bn_act_matmul(
                x2d, in_scale.reshape(1, -1), in_shift.reshape(1, -1),
                w2d, bool(in_act))
        else:
            y2d = jnp.dot(x2d, w2d,
                          preferred_element_type=jnp.float32
                          ).astype(x2d.dtype)
    scale, shift, new_mm, new_mv = _fold_stats(
        ss, qq, n, gamma, beta, moving_mean, moving_var, eps=eps,
        momentum=momentum, fix_gamma=fix_gamma, train=bool(_train))
    return (y2d.reshape(N, H, W, Cout), scale, shift, new_mm, new_mv)


register("_contrib_conv1x1_bn_act", _k_conv1x1_bn_act,
         arg_names=("data", "weight", "gamma", "beta", "moving_mean",
                    "moving_var", "in_scale", "in_shift"),
         aliases=("conv1x1_bn_act",), train_aware=True, num_outputs=5,
         mutate_aux=((4, 3), (5, 4)),
         doc=_k_conv1x1_bn_act.__doc__)


def _k_bn_fold(data, gamma, beta, moving_mean, moving_var, *, eps=1e-5,
               momentum=0.9, fix_gamma=False, _train=False):
    """Fold BN into (scale, shift) WITHOUT writing a normalized copy of
    ``data`` (channel-last input).  Train mode computes batch stats in
    one pass (the pallas bn_stats kernel when shapes allow); the
    consumer applies ``data*scale+shift`` — typically fused into a 1x1
    conv's input read via _contrib_conv1x1_bn_act."""
    C = data.shape[-1]
    n = data.size // C
    if _train:
        x2d = data.reshape(n, C)
        from .pallas import batch_norm as _pbn
        from .pallas.conv_fused import _use_pallas

        # same gate as the sibling kernels: off-TPU the pallas stats
        # kernel fails at XLA lowering, so only dispatch it when the
        # backend gate and shape support both say yes; the except
        # covers ONLY the pallas call itself, so a real kernel defect
        # is not silently hidden behind the jnp fallback
        ss = qq = None
        if _use_pallas() and _pbn.stats_supported(n, C):
            try:
                ss, qq = _pbn.bn_stats(x2d)
            except Exception as e:  # pragma: no cover - TPU-only path
                warnings.warn(
                    f"pallas bn_stats failed ({type(e).__name__}: {e}); "
                    "falling back to the XLA reduction")
        if ss is None:
            xf = x2d.astype(jnp.float32)
            ss = jnp.sum(xf, axis=0, keepdims=True)
            qq = jnp.sum(xf * xf, axis=0, keepdims=True)
    else:
        ss = qq = None
    return _fold_stats(ss, qq, n, gamma, beta, moving_mean, moving_var,
                       eps=eps, momentum=momentum, fix_gamma=fix_gamma,
                       train=bool(_train))


register("_contrib_bn_fold", _k_bn_fold,
         arg_names=("data", "gamma", "beta", "moving_mean", "moving_var"),
         aliases=("bn_fold",), train_aware=True, num_outputs=4,
         mutate_aux=((3, 2), (4, 3)),
         doc=_k_bn_fold.__doc__)
