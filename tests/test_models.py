"""Model family tests (ref: tests/python/unittest/test_gluon_model_zoo.py
+ train convergence tests)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn, rnn
from mxnet_tpu.gluon.model_zoo import vision


def test_lstm_layer_forward_and_states():
    layer = rnn.LSTM(16, 2)
    layer.initialize()
    x = nd.random.uniform(shape=(5, 3, 8))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 16)
    assert new_states[0].shape == (2, 3, 16)
    assert new_states[1].shape == (2, 3, 16)


def test_gru_layer_ntc_bidirectional():
    layer = rnn.GRU(8, 1, layout="NTC", bidirectional=True)
    layer.initialize()
    x = nd.random.uniform(shape=(2, 6, 4))
    out = layer(x)
    assert out.shape == (2, 6, 16)


def test_lstm_cell_unroll_matches_fused():
    """Cell-unrolled LSTM == fused scan LSTM (oracle pairing,
    ref: test_gluon_rnn.py consistency tests)."""
    np.random.seed(0)
    H, I, T, N = 6, 4, 5, 2
    cell = rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    layer = rnn.LSTM(H, 1, input_size=I)
    layer.initialize()
    # copy cell params into layer
    layer.l0_i2h_weight.set_data(cell.i2h_weight.data())
    layer.l0_h2h_weight.set_data(cell.h2h_weight.data())
    layer.l0_i2h_bias.set_data(cell.i2h_bias.data())
    layer.l0_h2h_bias.set_data(cell.h2h_bias.data())

    x_ntc = nd.random.uniform(shape=(N, T, I))
    outs_cell, _ = cell.unroll(T, x_ntc, layout="NTC")
    x_tnc = x_ntc.swapaxes(0, 1)
    out_fused = layer(x_tnc)
    assert np.allclose(outs_cell.asnumpy(),
                       out_fused.swapaxes(0, 1).asnumpy(), atol=1e-5)


def test_rnn_layer_hybridize():
    layer = rnn.LSTM(8, 1, input_size=4)
    layer.initialize()
    x = nd.random.uniform(shape=(3, 2, 4))
    eager = layer(x).asnumpy()
    layer.hybridize()
    hybrid = layer(x).asnumpy()
    assert np.allclose(eager, hybrid, atol=1e-5)


def test_resnet18_forward():
    net = vision.resnet18_v1(classes=10)
    net.initialize(mx.init.Xavier())
    out = net(nd.random.uniform(shape=(2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_resnet50_v2_forward():
    net = vision.resnet50_v2(classes=7)
    net.initialize(mx.init.Xavier())
    out = net(nd.random.uniform(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 7)


def test_model_zoo_factory():
    net = vision.get_model("lenet", classes=10)
    net.initialize()
    out = net(nd.random.uniform(shape=(2, 1, 28, 28)))
    assert out.shape == (2, 10)
    with pytest.raises(ValueError):
        vision.get_model("nonexistent_model")


def test_mobilenet_squeezenet_smoke():
    for name in ("mobilenet0_25", "squeezenet1_1"):
        net = vision.get_model(name, classes=4)
        net.initialize(mx.init.Xavier())
        out = net(nd.random.uniform(shape=(1, 3, 64, 64)))
        assert out.shape == (1, 4)


def test_model_zoo_width_variants():
    """Every reference factory name resolves; cheapest variant runs."""
    for name in ("densenet161", "mobilenet0_75", "mobilenet_v2_0_75",
                 "mobilenet_v2_0_5", "vgg11_bn", "vgg13_bn"):
        assert callable(getattr(vision, name))
        vision.get_model(name, classes=4)  # constructs without error
    net = vision.get_model("mobilenet_v2_0_25", classes=4)
    net.initialize(mx.init.Xavier())
    out = net(nd.random.uniform(shape=(1, 3, 64, 64)))
    assert out.shape == (1, 4)


def test_conv3d_transpose_layer():
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    t = nn.Conv3DTranspose(4, kernel_size=2, strides=2)
    t.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(2, 3, 4, 5, 6))
    with autograd.record():
        y = t(x)
    y.backward()
    assert y.shape == (2, 4, 8, 10, 12)
    assert t.weight.grad().shape == t.weight.shape


def test_bert_tiny_forward_and_grad():
    from mxnet_tpu.models import bert_tiny

    net = bert_tiny(vocab_size=100)
    net.initialize(mx.init.Normal(0.02))
    B, T = 2, 12
    tokens = nd.random.randint(0, 100, shape=(B, T))
    types = nd.zeros((B, T), dtype="int32")
    vlen = nd.array([12, 8])
    mlm, nsp = net(tokens, types, vlen)
    assert mlm.shape == (B, T, 100)
    assert nsp.shape == (B, 2)

    # MLM training step decreases loss
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    labels = nd.random.randint(0, 100, shape=(B, T))
    losses = []
    for _ in range(8):
        with autograd.record():
            mlm, _ = net(tokens, types, vlen)
            loss = loss_fn(mlm.reshape(-1, 100), labels.reshape(-1))
        loss.backward()
        trainer.step(B)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0], losses


def test_bert_masked_positions_gather():
    """masked_positions (gluonnlp contract): the MLM head decodes ONLY
    the gathered positions — scores must equal the dense decode at
    those positions, shape (b, K, vocab)."""
    import numpy as np

    from mxnet_tpu.models import bert_tiny

    mx.random.seed(1)
    net = bert_tiny(vocab_size=100)
    net.initialize(mx.init.Normal(0.02))
    B, T, K = 2, 12, 3
    rng = np.random.RandomState(0)
    tokens = nd.array(rng.randint(0, 100, (B, T)), dtype="int32")
    types = nd.zeros((B, T), dtype="int32")
    vlen = nd.array([12, 12])
    pos = nd.array(np.stack([rng.choice(T, K, replace=False)
                             for _ in range(B)]), dtype="int32")
    dense, _ = net(tokens, types, vlen)
    gathered, _ = net(tokens, types, vlen, pos)
    assert gathered.shape == (B, K, 100)
    d = dense.asnumpy()
    g = gathered.asnumpy()
    p = pos.asnumpy().astype(int)
    for r in range(B):
        for k in range(K):
            assert np.allclose(d[r, p[r, k]], g[r, k], atol=1e-5)


def test_bert_hybridize():
    from mxnet_tpu.models import bert_tiny

    net = bert_tiny(vocab_size=50)
    net.initialize(mx.init.Normal(0.02))
    tokens = nd.random.randint(0, 50, shape=(2, 8))
    types = nd.zeros((2, 8), dtype="int32")
    eager_mlm, eager_nsp = net(tokens, types)
    net.hybridize()
    h_mlm, h_nsp = net(tokens, types)
    assert np.allclose(eager_mlm.asnumpy(), h_mlm.asnumpy(), atol=1e-4)
    assert np.allclose(eager_nsp.asnumpy(), h_nsp.asnumpy(), atol=1e-4)


def test_transformer_tiny_train_and_decode():
    from mxnet_tpu.models import transformer_tiny

    np.random.seed(0)
    mx.random.seed(0)
    V = 20
    net = transformer_tiny(src_vocab=V, tgt_vocab=V)
    net.initialize(mx.init.Xavier())
    B, S, T = 4, 10, 9
    src = nd.random.randint(3, V, shape=(B, S))
    # task: copy source (shifted) — learnable by a tiny transformer
    tgt_in = nd.concat(nd.ones((B, 1)).astype("int32"),
                       src[:, :T - 1].astype("int32"), dim=1)
    tgt_out = src[:, :T].astype("int32")

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    losses = []
    for _ in range(20):
        with autograd.record():
            logits = net(src, tgt_in)
            loss = loss_fn(logits.reshape(-1, V), tgt_out.reshape(-1))
        loss.backward()
        trainer.step(B)
        losses.append(float(loss.mean().asscalar()))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])

    decoded = net.greedy_decode(src, max_len=5)
    assert decoded.shape[0] == B and decoded.shape[1] <= 5


def test_deepar_train_and_predict():
    from mxnet_tpu.models import deepar

    np.random.seed(1)
    mx.random.seed(1)
    net = deepar(num_cells=16, num_layers=1)
    net.initialize(mx.init.Xavier())
    B, T = 8, 24
    t = np.arange(T)
    target = nd.array(
        np.sin(2 * np.pi * t / 12)[None, :].repeat(B, 0)
        + np.random.rand(B, T) * 0.1)

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})
    losses = []
    for _ in range(15):
        with autograd.record():
            nll = net(target)
        nll.backward()
        trainer.step(B)
        losses.append(float(nll.asscalar()))
    assert losses[-1] < losses[0], losses

    samples = net.predict(target[:, :12], prediction_length=6,
                          num_samples=10)
    assert samples.shape == (B, 10, 6)
    assert np.isfinite(samples).all()


def test_attention_op_causal_and_mask():
    from mxnet_tpu.ops.attention import sdpa_reference
    import jax.numpy as jnp

    B, H, S, D = 2, 3, 5, 4
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.rand(B, H, S, D), jnp.float32)
    k = jnp.asarray(rng.rand(B, H, S, D), jnp.float32)
    v = jnp.asarray(rng.rand(B, H, S, D), jnp.float32)
    out = sdpa_reference(q, k, v, causal=True)
    # causal: first position attends only to itself => out[0] == v[0]
    assert np.allclose(np.asarray(out[:, :, 0]), np.asarray(v[:, :, 0]),
                       atol=1e-5)
    # numeric oracle vs explicit softmax
    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    tri = np.tril(np.ones((S, S), bool))
    logits = np.where(tri, logits, -1e9)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, np.asarray(v))
    assert np.allclose(np.asarray(out), ref, atol=1e-4)
