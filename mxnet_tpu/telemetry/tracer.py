"""Span tracer: event-level timelines for the production subsystems.

The profiler's counter sections answer "how much, in aggregate"; the
tracer answers "where did step 412 go" — nested, thread-lane-aware
spans with key/value attrs, recorded into a lock-cheap per-thread
buffer and exported as Chrome trace-event JSON (load the file straight
into Perfetto / chrome://tracing).

Disabled-by-default cost follows the ``engine.fault_point`` pattern:
every hook below (``span_begin``/``span_end``/``instant``/
``request_begin``/``request_instant``/``request_end``) is a rebindable
module global whose disarmed binding IS :func:`_noop` — one call,
zero branches taken, measured in ~ns and asserted by
``tests/test_telemetry.py``.  Arming (``start_trace`` /
``telemetry.trace(path)`` / ``MXTPU_TRACE=<path>`` / the flight
recorder) rebinds them to the recording implementations; callers
resolve the CURRENT binding through the module attribute
(``tracer.span_begin(...)``), exactly like ``engine.fault_point``.

Span model:

- **scope spans** — ``span_begin(name, cat)`` / ``span_end(name,
  cat, **attrs)`` pairs on one thread, exported as complete ``"X"``
  events (ts + dur).  ``profiler.op_scope`` emits these automatically
  while tracing is armed, so every existing op scope (trainer
  allreduce/fused_update, pipeline stages, serve batches, checkpoint
  phases) is a span for free.
- **instants** — ``instant(name, cat, **attrs)``: a point event
  (``"i"``, thread scope) for things with no duration (a supervisor
  retry, a watchdog fire).
- **request spans** — ``rid = request_begin(name, cat, **attrs)`` /
  ``request_instant`` / ``request_end``: Chrome *async* events
  (``"b"``/``"n"``/``"e"`` sharing an id) that follow one logical
  request across threads — how a serve request is traced
  submit→queue→dispatch→resolve.

Per-thread buffers: a thread's spans append to its own list (no lock
on the hot path); the global registry of lanes is only locked on
first-touch and at export.  Each lane is capped (``_LANE_CAP``) so a
runaway trace degrades by dropping (counted) instead of eating the
heap.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time

from ..base import MXNetError

_PID = os.getpid()
_LANE_CAP = 200_000          # events per thread lane before dropping

_lock = threading.Lock()     # lanes registry + arm/disarm + counters
_lanes = []                  # [{"tid", "name", "events": []}]
_state = threading.local()   # .events (this thread's lane), .stack
_trace_on = False
_trace_path = None
_flight_ring = None          # collections.deque(maxlen=...) when armed
_rid_counter = itertools.count(1)
# arming generation: bumped on every arm/disarm transition so a span
# begun in one session can never close (with a garbage duration) in a
# later one — begin/end must see the same epoch to emit
_epoch = 0

# window-scoped telemetry counters (the profiler's "telemetry" section)
_counters = {
    "spans": 0,              # completed scope spans recorded
    "instants": 0,           # point events recorded
    "requests": 0,           # async request spans opened
    "dropped": 0,            # events lost to the per-lane cap
    "flight_dumps": 0,       # flight-recorder files written
    "scrapes": 0,            # /metrics renders served
    "aggregations": 0,       # telemetry.aggregate() calls
}


def _noop(*_args, **_kwargs):
    """Disarmed telemetry hook: nothing beyond the call is evaluated
    (and ``request_begin`` callers get ``None`` for the request id, so
    the matching ``request_end(None)`` is a no-op too)."""
    return None


# -- recording implementations ----------------------------------------------


def _now_us():
    return time.perf_counter() * 1e6


def _lane_events():
    ev = getattr(_state, "events", None)
    if ev is None:
        ev = _state.events = []
        _state.stack = []
        th = threading.current_thread()
        with _lock:
            _lanes.append({"tid": th.ident % 100000, "name": th.name,
                           "events": ev})
    return ev


def _emit(ev):
    if _flight_ring is not None:
        _flight_ring.append(ev)     # deque.append is atomic
    if _trace_on:
        events = _lane_events()
        if len(events) >= _LANE_CAP:
            with _lock:
                _counters["dropped"] += 1
            return
        events.append(ev)


def _clean_attrs(attrs):
    return {k: (v if isinstance(v, (int, float, str, bool)) else str(v))
            for k, v in attrs.items()}


def _span_begin(name, cat="op"):
    _lane_events()                   # ensure .stack exists
    _state.stack.append((name, _now_us(), _epoch))


def _span_end(name, cat="op", **attrs):
    stack = getattr(_state, "stack", None)
    if not stack or stack[-1][0] != name:
        return                       # armed mid-span: nothing to close
    _nm, t0, epoch = stack.pop()
    if epoch != _epoch:
        return    # begun under a previous arming session: the t0 is
        # from another trace — emitting would fabricate a phantom span
    t1 = _now_us()
    ev = {"name": name, "ph": "X", "ts": t0,
          "dur": max(t1 - t0, 0.01), "pid": _PID,
          "tid": threading.get_ident() % 100000, "cat": cat}
    if attrs:
        ev["args"] = _clean_attrs(attrs)
    with _lock:
        _counters["spans"] += 1
    _emit(ev)


def _instant(name, cat="op", **attrs):
    ev = {"name": name, "ph": "i", "ts": _now_us(), "pid": _PID,
          "tid": threading.get_ident() % 100000, "cat": cat, "s": "t"}
    if attrs:
        ev["args"] = _clean_attrs(attrs)
    with _lock:
        _counters["instants"] += 1
    _emit(ev)


def _request_begin(name, cat="request", **attrs):
    rid = next(_rid_counter)
    ev = {"name": name, "ph": "b", "ts": _now_us(), "pid": _PID,
          "tid": threading.get_ident() % 100000, "cat": cat, "id": rid}
    if attrs:
        ev["args"] = _clean_attrs(attrs)
    with _lock:
        _counters["requests"] += 1
    _emit(ev)
    return rid


def _request_instant(name, rid, cat="request", **attrs):
    if rid is None:
        return
    ev = {"name": name, "ph": "n", "ts": _now_us(), "pid": _PID,
          "tid": threading.get_ident() % 100000, "cat": cat, "id": rid}
    if attrs:
        ev["args"] = _clean_attrs(attrs)
    _emit(ev)


def _request_end(name, rid, cat="request", **attrs):
    if rid is None:
        return
    ev = {"name": name, "ph": "e", "ts": _now_us(), "pid": _PID,
          "tid": threading.get_ident() % 100000, "cat": cat, "id": rid}
    if attrs:
        ev["args"] = _clean_attrs(attrs)
    _emit(ev)


# -- the rebindable hook surface (disarmed = _noop) --------------------------

span_begin = _noop
span_end = _noop
instant = _noop
request_begin = _noop
request_instant = _noop
request_end = _noop

_HOOKS = {
    "span_begin": _span_begin,
    "span_end": _span_end,
    "instant": _instant,
    "request_begin": _request_begin,
    "request_instant": _request_instant,
    "request_end": _request_end,
}


def _rebind():
    """Point the hook surface at the recording impls iff any consumer
    (trace export, flight ring) is armed; else back to the no-op.
    Every transition bumps the epoch, invalidating any span stack
    entries left dangling by a mid-span arm/disarm."""
    global _epoch
    _epoch += 1
    active = _trace_on or _flight_ring is not None
    g = globals()
    for name, impl in _HOOKS.items():
        g[name] = impl if active else _noop


def armed():
    """True when any hook is recording (tracing or flight ring)."""
    return span_begin is not _noop


def tracing():
    """True while a trace export is armed (``start_trace`` .. ``stop_trace``)."""
    return _trace_on


# -- arming ------------------------------------------------------------------


def start_trace(path):
    """Arm span recording; ``stop_trace()`` exports to ``path``."""
    global _trace_on, _trace_path
    if not path:
        raise MXNetError("start_trace needs an output path")
    with _lock:
        if _trace_on:
            raise MXNetError(
                f"tracing is already armed (exporting to {_trace_path});"
                " stop_trace() first")
        for lane in _lanes:
            del lane["events"][:]    # in place: thread-locals alias it
        _trace_path = str(path)
        _trace_on = True
    _rebind()


def stop_trace():
    """Disarm and export the collected spans as Chrome trace-event
    JSON; returns the path written (None when tracing was not armed)."""
    global _trace_on, _trace_path
    with _lock:
        if not _trace_on:
            return None
        _trace_on = False
        path = _trace_path
        _trace_path = None
        data = export_events()
        # release the buffered events now, not at the next arm: a
        # one-shot trace of a heavy window would otherwise pin up to
        # _LANE_CAP event dicts per thread for the process lifetime
        # (in place — thread-locals alias these lists)
        for lane in _lanes:
            del lane["events"][:]
    _rebind()
    with open(path, "w") as f:
        json.dump({"traceEvents": data, "displayTimeUnit": "ms"}, f)
    return path


def export_events():
    """The current event list (thread-name metadata first, then every
    lane's events) — what ``stop_trace`` writes under ``traceEvents``."""
    out = [{"name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
            "args": {"name": "mxnet_tpu"}}]
    for lane in _lanes:
        out.append({"name": "thread_name", "ph": "M", "pid": _PID,
                    "tid": lane["tid"], "args": {"name": lane["name"]}})
        out.extend(list(lane["events"]))
    out.sort(key=lambda ev: ev.get("ts", 0))
    return out


def set_flight_ring(ring):
    """Attach/detach the flight recorder's bounded ring (a deque with
    maxlen, or None); arming it turns span recording on even when no
    trace export is armed."""
    global _flight_ring
    with _lock:
        _flight_ring = ring
    _rebind()


def flight_ring():
    return _flight_ring


def bump(counter, n=1):
    """Count one telemetry-internal event (flight dump, scrape, ...)
    into the window-scoped ``telemetry`` profiler section."""
    with _lock:
        _counters[counter] += n


def telemetry_stats():
    """Snapshot of the telemetry counters since the last reset."""
    with _lock:
        return dict(_counters)


def reset_telemetry_stats():
    with _lock:
        for k in _counters:
            _counters[k] = 0
