"""gluon.Trainer over kvstore('dist_sync') — the reference's canonical
user-facing multi-node training loop (ref: gluon/trainer.py + dist
kvstore, SURVEY §3.3/§3.4; the nightlies above test the kvstore
directly, THIS one tests it through the Trainer the way users write
it).

2 workers, each computing gradients on its own half of the global
batch with plain autograd; Trainer.step pushpulls per-parameter grads
through the in-graph DCN all-reduce.  Per-step losses must match a
single-process full-batch oracle (computed by the launching pytest,
passed via MXTPU_ORACLE_FILE) and the final params must be identical
on both workers.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax

jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel import dist  # noqa: E402

dist.init()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import autograd, gluon, nd  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402

rank, size = dist.rank(), dist.num_workers()
assert size == 2, f"expected 2 workers, got {size}"

GLOBAL_BATCH, FEAT, NCLS, STEPS = 16, 12, 4, 6
rng = np.random.RandomState(0)
X = rng.rand(GLOBAL_BATCH, FEAT).astype(np.float32)
Y = rng.randint(0, NCLS, GLOBAL_BATCH).astype(np.float32)

mx.random.seed(0)
net = nn.HybridSequential()
net.add(nn.Dense(16, activation="relu"), nn.Dense(NCLS))
net.initialize(mx.init.Xavier())
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1}, kvstore="dist_sync")
# SUM loss per worker: the cross-worker grad sum then equals the
# full-batch sum, and step(GLOBAL_BATCH) rescales to the exact mean
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

half = slice(rank * 8, rank * 8 + 8)
xw, yw = nd.array(X[half]), nd.array(Y[half])

losses = []
for _ in range(STEPS):
    with autograd.record():
        out = net(xw)
        loss = loss_fn(out, yw).sum()
    loss.backward()
    trainer.step(GLOBAL_BATCH)
    # global mean loss for the parity check: sum across workers / B
    total = dist.allreduce(nd.array(
        np.asarray([float(loss.asscalar())], np.float32)))
    losses.append(float(total.asnumpy()[0]) / GLOBAL_BATCH)

ref = np.asarray(np.load(os.environ["MXTPU_ORACLE_FILE"])["losses"])
assert np.allclose(losses, ref, atol=1e-5), (losses, ref.tolist())

# both workers must hold IDENTICAL params after synchronized training
flat = np.concatenate([p.data().asnumpy().ravel()
                       for p in net.collect_params().values()])
peer_sum = dist.allreduce(nd.array(flat)).asnumpy()
assert np.allclose(peer_sum, 2 * flat, atol=1e-6), \
    float(np.abs(peer_sum - 2 * flat).max())

print(f"worker {rank}/{size}: gluon dist_sync trainer OK "
      f"(loss {losses[0]:.4f}->{losses[-1]:.4f})")
