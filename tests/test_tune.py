"""Closed-loop autotuner: registry validation, seeded trial
determinism, recompile debits, geometry derivation, cost-model
ranking, the `tune` profiler section's window scoping, and the
restart-class mid-burst guard (docs/tuning.md)."""
import json
import math
import os

import pytest

from mxnet_tpu import profiler, tune
from mxnet_tpu.base import MXNetError
from mxnet_tpu.tune import (CostModel, Knob, KnobRegistry, Tuner,
                            TrialRunner, derive_batches,
                            derive_bucket_spec, derive_decode_geometry,
                            derive_lengths, format_grid,
                            padding_overhead, parse_grid,
                            reset_tune_stats, tune_stats)
from mxnet_tpu.tune.cost_model import check_monotonic_agreement


def _mem_knob(name, store, env="GOOD_KNOB", **kw):
    """Env-free knob: applies into a plain dict (tests must not leak
    MXTPU_* state into each other)."""
    default = kw.get("default")
    return Knob(name, env=env,
                apply=lambda v: store.__setitem__(name, v),
                read=lambda: store.get(name, default), **kw)


def _two_knob_registry(store):
    reg = KnobRegistry()
    reg.register(_mem_knob("alpha", store, env="ALPHA_K", kind="int",
                           domain=(1, 2, 4, 8, 16, 32, 64),
                           default=8, restart="free"))
    reg.register(_mem_knob("beta", store, env="BETA_K", kind="int",
                           domain=(1, 2, 4, 8, 16), default=4,
                           restart="free"))
    return reg


# ---------------------------------------------------------------------------
# registry validation


def test_registry_validation_is_loud():
    store = {}
    with pytest.raises(MXNetError, match="bad bounds"):
        Knob("k", env="A_K", kind="int", bounds=(8, 1))
    with pytest.raises(MXNetError, match="empty domain"):
        Knob("k", env="A_K", kind="int", domain=())
    with pytest.raises(MXNetError, match="domain= or bounds="):
        Knob("k", env="A_K", kind="int")
    with pytest.raises(MXNetError, match="restart class"):
        Knob("k", env="A_K", domain=(1, 2), restart="maybe")
    with pytest.raises(MXNetError, match="env"):
        Knob("k", env=None, domain=(1, 2))
    with pytest.raises(MXNetError, match="outside bounds"):
        Knob("k", env="A_K", domain=(1, 2, 99), bounds=(1, 8))
    with pytest.raises(MXNetError, match="not in domain"):
        Knob("k", env="A_K", domain=(1, 2, 4), default=3)

    reg = KnobRegistry()
    reg.register(_mem_knob("dup", store, domain=(1, 2)))
    with pytest.raises(MXNetError, match="already registered"):
        reg.register(_mem_knob("dup", store, domain=(1, 2)))
    with pytest.raises(MXNetError, match="unknown knob"):
        reg.get("nope")

    # collection-level: two knobs claiming one env var, and the
    # documented-set check (the runtime face of MXA501)
    reg2 = KnobRegistry()
    reg2.register(_mem_knob("a", store, env="SAME_K", domain=(1, 2)))
    reg2.register(_mem_knob("b", store, env="SAME_K", domain=(1, 2)))
    with pytest.raises(MXNetError, match="both claim"):
        reg2.validate()
    reg3 = KnobRegistry()
    reg3.register(_mem_knob("c", store, env="UNDOC_K", domain=(1, 2)))
    with pytest.raises(MXNetError, match="not in the documented"):
        reg3.validate(documented_env={"MXTPU_OTHER_K"})
    reg3.validate(documented_env={"MXTPU_UNDOC_K"})


def test_default_registry_covers_issue_knobs_and_is_documented():
    reg = tune.default_registry()
    for name in ("kvstore_bucket_mb", "aggregate_num",
                 "pipeline_prefetch", "pipeline_map_inflight",
                 "serve_linger_ms", "serve_buckets",
                 "decode_max_slots", "decode_max_len", "zero_shard"):
        assert name in reg
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "docs", "ENV_VARS.md")) as f:
        doc = f.read()
    reg.validate(documented_env=set(
        w for w in doc.replace("`", " ").replace("|", " ").split()
        if w.startswith("MXTPU_")))


def test_knob_env_apply_roundtrip():
    """Default (un-overridden) hooks write/read through base.setenv/
    getenv under the canonical MXTPU_ spelling."""
    knob = Knob("linger", env="TEST_TUNE_LINGER", kind="float",
                bounds=(0.0, 10.0), default=2.0)
    try:
        assert knob.read() == 2.0          # unset -> default
        knob.apply(5.0)
        assert os.environ["MXTPU_TEST_TUNE_LINGER"] == "5.0"
        assert knob.read() == 5.0
        with pytest.raises(MXNetError, match="outside bounds"):
            knob.apply(99.0)
    finally:
        os.environ.pop("MXTPU_TEST_TUNE_LINGER", None)


# ---------------------------------------------------------------------------
# seeded trial determinism


def _quadratic_measure(cfg):
    a, b = cfg["alpha"], cfg["beta"]
    return {"goodput": 100.0 - (math.log2(a) - 4.0) ** 2 * 3.0
                      - (math.log2(b) - 3.0) ** 2 * 2.0}


def _run_tuner(tmp_path, tag, seed):
    store = {}
    reg = _two_knob_registry(store)
    hist = str(tmp_path / f"hist_{tag}.jsonl")
    runner = TrialRunner(reg, _quadratic_measure, history=hist,
                         seed=seed, compile_counter=lambda: 0)
    tuner = Tuner(reg, runner=runner, seed=seed,
                  reference_configs={})
    rec = tuner.recommend()
    return rec, hist


def test_seeded_trial_determinism(tmp_path):
    reset_tune_stats()
    rec1, h1 = _run_tuner(tmp_path, "a", seed=11)
    rec2, h2 = _run_tuner(tmp_path, "b", seed=11)
    with open(h1) as f1, open(h2) as f2:
        assert f1.read() == f2.read()      # bit-replayable records
    assert rec1.config == rec2.config
    seq1 = [(r["knob"], r["config"]) for r in rec1.trials]
    seq2 = [(r["knob"], r["config"]) for r in rec2.trials]
    assert seq1 == seq2
    # a different seed explores a different candidate sequence
    rec3, _h3 = _run_tuner(tmp_path, "c", seed=12)
    seq3 = [(r["knob"], r["config"]) for r in rec3.trials]
    assert seq3 != seq1
    # records carry no wallclock: every line survives a JSON roundtrip
    # with sorted keys and only declared fields
    with open(h1) as f:
        for line in f:
            rec = json.loads(line)
            assert rec["kind"] == "tune_trial"
            assert json.dumps(rec, sort_keys=True) == line.strip()


def test_tuner_beats_bad_start_on_synthetic_surface(tmp_path):
    """From the worst corner of the quadratic surface, one sweep must
    find a measurably better config (and never regress)."""
    reset_tune_stats()
    store = {}
    reg = _two_knob_registry(store)
    reg.apply({"alpha": 1, "beta": 1})      # the bad start
    runner = TrialRunner(reg, _quadratic_measure, history="",
                         seed=0, compile_counter=lambda: 0)
    tuner = Tuner(reg, runner=runner, seed=0, top_k=3, passes=2,
                  reference_configs={})
    rec = tuner.recommend()
    assert rec.ratio > 1.1
    assert rec.best["score"] >= rec.baseline["score"]
    assert rec.moved()                      # evidence of actual moves


# ---------------------------------------------------------------------------
# recompile debit accounting


def test_recompile_debit_accounting():
    reset_tune_stats()
    store = {}
    reg = KnobRegistry()
    reg.register(_mem_knob("bucket", store, env="BUCKET_K",
                           kind="int", domain=(1, 32), default=32,
                           restart="recompile"))
    compiles = [0]

    def measure(cfg):
        if cfg["bucket"] != 32:
            compiles[0] += 3        # shape-surface move re-warms
        return {"goodput": 50.0}

    runner = TrialRunner(reg, measure, history="", seed=0,
                         recompile_penalty=2.0,
                         compile_counter=lambda: compiles[0])
    base = runner.run({"bucket": 32}, baseline=True)
    assert base["recompiles"] == 0 and base["score"] == 50.0
    moved = runner.run({"bucket": 1}, knob="bucket")
    assert moved["recompiles"] == 3
    assert moved["score"] == 50.0 - 2.0 * 3
    assert tune_stats()["recompiles_spent"] == 3
    # penalty=0 still RECORDS the debit, just doesn't score it
    runner0 = TrialRunner(reg, measure, history="", seed=0,
                          recompile_penalty=0.0,
                          compile_counter=lambda: compiles[0])
    again = runner0.run({"bucket": 32}, baseline=True)
    assert again["recompiles"] == 0
    moved0 = runner0.run({"bucket": 1})
    assert moved0["recompiles"] == 3 and moved0["score"] == 50.0


# ---------------------------------------------------------------------------
# geometry derivation


#: heavy-tailed synthetic shape history: most requests short, a thin
#: tail out to 500
_HEAVY_TAIL = {8: 500, 16: 300, 24: 100, 120: 20, 500: 5}


def test_geometry_derived_grid_beats_default_on_heavy_tail():
    derived = derive_lengths(_HEAVY_TAIL, max_buckets=4, align=8)
    assert len(derived) <= 4
    assert derived[-1] >= 500               # tail must be covered
    default = (32, 64, 128)
    assert padding_overhead(derived, _HEAVY_TAIL) < \
        padding_overhead(default, _HEAVY_TAIL)
    # degenerate single-bucket budget still covers the max
    one = derive_lengths(_HEAVY_TAIL, max_buckets=1, align=8)
    assert len(one) == 1 and one[0] >= 500


def test_geometry_bucket_spec_and_grid_strings():
    snap = {"request_lengths": _HEAVY_TAIL,
            "group_sizes": {1: 40, 2: 25, 3: 10, 6: 5}}
    spec = derive_bucket_spec(snap, (None,), max_buckets=3, align=8)
    assert spec.lengths == derive_lengths(_HEAVY_TAIL, 3, 8)
    assert spec.batch_sizes == (1, 2, 4, 8)
    assert derive_batches({1: 3, 4: 1}, max_batch=2) == (1, 2)
    # grid string roundtrip (the serve_buckets env carrier)
    s = format_grid(spec.batch_sizes, spec.lengths)
    assert parse_grid(s) == (spec.batch_sizes, spec.lengths)
    assert parse_grid("1,2,4x") == ((1, 2, 4), None)
    with pytest.raises(MXNetError, match="bad bucket grid"):
        parse_grid("1,2x4,oops")
    with pytest.raises(MXNetError, match="no batch sizes"):
        parse_grid("x32,64")


def test_geometry_decode_arena():
    geo = derive_decode_geometry(_HEAVY_TAIL, max_new_tokens=32,
                                 slot_occupancy=0.9, max_slots=8)
    # p99 prompt is 120 (the 500-tail is 0.5% of mass), + 32 budget
    assert geo["max_len"] >= 120 + 32
    assert geo["max_len"] % 8 == 0
    assert geo["max_slots"] == 16           # saturated -> grow
    idle = derive_decode_geometry({16: 10}, max_new_tokens=16,
                                  slot_occupancy=0.1, max_slots=8)
    assert idle["max_slots"] == 4           # idle -> shrink
    assert idle["max_len"] >= 32


# ---------------------------------------------------------------------------
# cost model


def test_cost_model_ranking_agrees_with_measured_ordering():
    """On a smooth 2-knob surface, a model fitted on a 3x3 grid must
    reproduce the measured ordering of held-out candidates."""
    store = {}
    reg = _two_knob_registry(store)
    model = CostModel(reg)

    def score(cfg):
        return _quadratic_measure(cfg)["goodput"]

    for a in (1, 8, 64):
        for b in (1, 4, 16):
            cfg = {"alpha": a, "beta": b}
            model.observe(cfg, score(cfg))
    held_out = [{"alpha": a, "beta": b}
                for a, b in ((2, 2), (4, 8), (16, 4), (32, 16))]
    measured = [score(c) for c in held_out]
    assert check_monotonic_agreement(model, held_out, measured) >= 0.75
    # rank() puts the measured-best held-out candidate first
    best = max(zip(measured, range(len(held_out))))[1]
    assert model.rank(held_out)[0] == held_out[best]


def test_cost_model_phase_hint_prior_before_any_trials():
    """With zero observations, the analytic seed (phase breakdown)
    must already prefer moving the knob that attacks the dominant
    phase upward."""
    store = {}
    reg = KnobRegistry()
    reg.register(_mem_knob("pipeline_prefetch", store, env="A_K",
                           kind="int", domain=(0, 1, 2, 4, 8),
                           default=2))
    model = CostModel(reg, phase_hint={"input_wait_ms": 900.0,
                                       "compute_ms": 100.0})
    deep = {"pipeline_prefetch": 8}
    shallow = {"pipeline_prefetch": 0}
    assert model.predict(deep) > model.predict(shallow)
    assert model.rank([shallow, deep])[0] == deep


# ---------------------------------------------------------------------------
# profiler `tune` section


def test_tune_section_window_scoping():
    reset_tune_stats()
    store = {}
    reg = _two_knob_registry(store)
    runner = TrialRunner(reg, _quadratic_measure, history="", seed=0,
                         compile_counter=lambda: 0)
    runner.run({"alpha": 8, "beta": 4}, baseline=True)
    sec = profiler.sections()["tune"]
    assert sec["trials"] == 1 and sec["measurements"] == 1
    # reset=True closes the window: the next read starts from zero
    windowed = profiler.sections(reset=True)["tune"]
    assert windowed["trials"] == 1
    assert profiler.sections()["tune"]["trials"] == 0
    # and the gauges ride the standard section export path
    from mxnet_tpu.telemetry import metrics as _metrics
    text = _metrics.default_registry().render()
    assert "mxtpu_tune_trials" in text
    assert "mxtpu_tune_best_over_baseline" in text


# ---------------------------------------------------------------------------
# restart-class guard


def test_tuner_never_moves_restart_knobs_mid_burst():
    reset_tune_stats()
    store = {}
    reg = KnobRegistry()
    reg.register(_mem_knob("linger", store, env="L_K", kind="float",
                           domain=(0.0, 2.0, 5.0), default=2.0,
                           restart="free"))
    reg.register(_mem_knob("bucket_mb", store, env="B_K", kind="int",
                           domain=(1, 32, 128), default=32,
                           restart="recompile"))
    reg.register(_mem_knob("grid", store, env="G_K", kind="choice",
                           domain=("a", "b"), default="a",
                           restart="restart"))
    reg.apply({"linger": 0.0, "bucket_mb": 1, "grid": "a"})

    def measure(cfg):
        # every knob helps, so an unguarded tuner WOULD move them all
        return {"goodput": cfg["linger"] + cfg["bucket_mb"]
                + (10.0 if cfg["grid"] == "b" else 0.0)}

    runner = TrialRunner(reg, measure, history="", seed=0,
                         compile_counter=lambda: 0)
    tuner = Tuner(reg, runner=runner, seed=0, top_k=3,
                  busy_fn=lambda: True, reference_configs={})
    rec = tuner.recommend()
    for trial in rec.trials:
        assert trial["config"]["bucket_mb"] == 1    # never moved
        assert trial["config"]["grid"] == "a"
    assert rec.config["bucket_mb"] == 1
    assert rec.config["grid"] == "a"
    assert rec.config["linger"] == 5.0              # free knob moved
    assert rec.blocked_moves == 2
    assert tune_stats()["blocked_moves"] == 2
    # and the winner's restart-class values were not force-applied
    assert store["bucket_mb"] == 1 and store["grid"] == "a"

    # the registry-level guard is loud, not silent
    with pytest.raises(MXNetError, match="may not move mid-burst"):
        reg.apply({"bucket_mb": 128}, allow_restart=False)

    # once the burst ends, the same tuner setup moves everything
    reset_tune_stats()
    reg.apply({"linger": 0.0, "bucket_mb": 1, "grid": "a"})
    tuner2 = Tuner(reg, runner=TrialRunner(
        reg, measure, history="", seed=0,
        compile_counter=lambda: 0), seed=0, top_k=3,
        busy_fn=lambda: False, reference_configs={})
    rec2 = tuner2.recommend()
    assert rec2.config["bucket_mb"] == 128
    assert rec2.config["grid"] == "b"
    assert rec2.blocked_moves == 0
