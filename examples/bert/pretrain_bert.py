"""BERT-base MLM pretraining — BASELINE config #3.

Ref: GluonNLP's scripts/bert/run_pretraining.py shape: masked-LM +
next-sentence-prediction over the kvstore all-reduce. Here the whole
step (fwd + bwd + grad psum over the 'dp' mesh axis + AdamW) is ONE
compiled XLA computation. Synthetic corpus by default so the script is
runnable without data; --seq-len and --model pick the config.

  python examples/bert/pretrain_bert.py --model tiny --steps 20
  python examples/bert/pretrain_bert.py --model base --batch-size 64
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _common import add_cpu_flag, apply_backend  # noqa: E402

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import HybridBlock
from mxnet_tpu.models import bert


class BERTForPretrain(HybridBlock):
    """MLM + NSP loss head over the backbone, one scalar loss out."""

    def __init__(self, model, vocab_size, **kwargs):
        super().__init__(**kwargs)
        self.model = model
        self._vocab = vocab_size

    def hybrid_forward(self, F, inputs, token_types, mlm_targets,
                       nsp_labels, mask_weight, valid_length,
                       masked_positions):
        # valid_length masks attention over the [PAD] tail (real-corpus
        # batches are padded; the BERT recipe never attends to pads).
        # masked_positions (b, K): the MLM head decodes ONLY those
        # positions (gluonnlp run_pretraining shape) — targets and
        # mask_weight are (b, K) position-aligned.
        mlm_scores, nsp_scores = self.model(inputs, token_types,
                                            valid_length,
                                            masked_positions)
        mlm_log = F.log_softmax(mlm_scores)
        mlm_ll = F.pick(mlm_log, mlm_targets, axis=-1)
        mlm_loss = -F.sum(mlm_ll * mask_weight) / (F.sum(mask_weight) + 1)
        nsp_log = F.log_softmax(nsp_scores)
        nsp_loss = -F.mean(F.pick(nsp_log, nsp_labels, axis=-1))
        return mlm_loss + nsp_loss


def synthetic_batch(rng, bs, seq_len, vocab, mask_frac=0.15):
    K = max(1, int(round(seq_len * mask_frac)))
    tokens = rng.randint(4, vocab, (bs, seq_len))
    types = np.zeros((bs, seq_len), np.int32)
    half = seq_len // 2
    types[:, half:] = 1
    positions = np.stack([rng.choice(seq_len, K, replace=False)
                          for _ in range(bs)]).astype(np.int32)
    targets = np.take_along_axis(tokens, positions, 1)
    inputs = tokens.copy()
    np.put_along_axis(inputs, positions, 3, 1)  # 3 = [MASK]
    weights = np.ones((bs, K), np.float32)
    nsp = rng.randint(0, 2, (bs,))
    valid = np.full((bs,), seq_len, np.int32)
    return (inputs.astype(np.int32), types, targets.astype(np.int32),
            nsp.astype(np.int32), weights, valid, positions)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="base",
                   choices=["tiny", "base", "large"])
    p.add_argument("--vocab-size", type=int, default=30522)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--disp", type=int, default=10)
    add_cpu_flag(p)
    p.add_argument("--remat", action="store_true",
                   help="rematerialize activations per child block "
                        "(jax.checkpoint): more FLOPs for less HBM "
                        "when activations don't fit")
    p.add_argument("--data", default=None,
                   help="path to a pretraining corpus (one sentence "
                        "per line, blank line between documents); "
                        "default trains on synthetic batches")
    p.add_argument("--wordpiece-vocab", type=int, default=8000,
                   help="WordPiece vocab size learned from --data")
    p.add_argument("--save-params", default=None,
                   help="save the full pretrain checkpoint here "
                        "(backbone + MLM/NSP head params, "
                        "save_parameters format; finetune_classifier "
                        "--params warm-starts the backbone from it and "
                        "ignores the heads)")
    args = p.parse_args()
    apply_backend(args)
    if args.model == "tiny":
        args.vocab_size = min(args.vocab_size, 1000)

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    pipeline = None
    if args.data:
        # real-corpus path (VERDICT r3 #6): WordPiece + MLM/NSP from
        # mxnet_tpu.data — swap the corpus, keep the training loop
        from mxnet_tpu.data import WordPieceTokenizer
        from mxnet_tpu.data.bert import BertPretrainPipeline

        with open(args.data) as f:
            lines = f.readlines()
        tok = WordPieceTokenizer.build(
            [ln for ln in lines if ln.strip()],
            vocab_size=args.wordpiece_vocab)
        args.vocab_size = len(tok)
        pipeline = BertPretrainPipeline(lines, tok,
                                        seq_len=args.seq_len, seed=0)
        print(f"corpus {args.data}: wordpiece vocab {len(tok)}")

    backbone = getattr(bert, f"bert_{args.model}")(
        vocab_size=args.vocab_size)
    net = BERTForPretrain(backbone, args.vocab_size)
    net.initialize(mx.init.TruncNorm(stdev=0.02))

    from mxnet_tpu.parallel import data_parallel

    class _Identity(gluon.loss.Loss):
        # the model already returns the scalar loss
        def __init__(self, **kwargs):
            super().__init__(None, 0, **kwargs)

        def hybrid_forward(self, F, pred, label):
            return pred

    trainer = data_parallel.DataParallelTrainer(
        net, _Identity(), "adamw",
        {"learning_rate": args.lr, "wd": 0.01}, remat=args.remat)

    batch_stream = pipeline.batches(args.batch_size, args.steps) \
        if pipeline else None

    tic, tic_n = time.time(), 0
    for step in range(args.steps):
        if batch_stream is not None:
            b = next(batch_stream)
            batch = (b["input_ids"], b["token_types"],
                     b["mlm_targets_k"], b["nsp_labels"],
                     b["mask_weight_k"], b["valid_length"],
                     b["masked_positions"])
        else:
            batch = synthetic_batch(
                rng, args.batch_size, args.seq_len, args.vocab_size)
        loss = trainer.step(batch,
                            np.zeros((args.batch_size,), np.float32))
        tic_n += args.batch_size * args.seq_len
        if step % args.disp == 0 and step:
            loss.wait_to_read()
            tps = tic_n / (time.time() - tic)
            print(f"step {step} loss {float(loss.asscalar()):.4f} "
                  f"{tps:.0f} tokens/s")
            tic, tic_n = time.time(), 0
    loss.wait_to_read()
    print(f"done: final loss {float(loss.asscalar()):.4f}")

    if args.save_params:
        trainer.sync_to_block()
        net.model.save_parameters(args.save_params)
        print(f"saved pretrain checkpoint to {args.save_params}")


if __name__ == "__main__":
    main()
