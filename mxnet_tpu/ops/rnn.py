"""Fused multi-layer RNN op: vanilla/LSTM/GRU, optionally bidirectional.

Ref: src/operator/rnn.{cc,cu}, rnn-inl.h, nn/cudnn/cudnn_rnn-inl.h — the
cuDNN-backed fused RNN.  TPU-native design: the whole multi-layer,
multi-timestep recurrence is ONE ``lax.scan`` per layer/direction, so
XLA compiles a single fused while-loop whose body is a (batch, 4H)
matmul on the MXU — the same fusion cuDNN provides, expressed
compiler-first.  A Pallas variant can later replace the scan body; the
parameter layout here is the stable contract.

Parameter layout (flat vector, mirrors the reference's packed cuDNN
canonical layout): for each layer, for each direction:
``i2h_weight (G*H, in)``, ``h2h_weight (G*H, H)``; then, after all
weights, for each layer/direction: ``i2h_bias (G*H)``, ``h2h_bias
(G*H)``.  Gate order: LSTM (i, f, g, o); GRU (r, z, n).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(num_layers, input_size, state_size, mode,
                   bidirectional=False, projection_size=None):
    """Total length of the flat parameter vector."""
    g = _GATES[mode]
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        for _ in range(d):
            size += g * state_size * (in_sz + state_size)  # weights
    size += num_layers * d * 2 * g * state_size  # biases
    return size


def _unpack(params, num_layers, input_size, state_size, mode, d):
    g = _GATES[mode]
    ws, off = [], 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        per_dir = []
        for _ in range(d):
            wi = params[off:off + g * state_size * in_sz].reshape(
                g * state_size, in_sz)
            off += wi.size
            wh = params[off:off + g * state_size * state_size].reshape(
                g * state_size, state_size)
            off += wh.size
            per_dir.append([wi, wh])
        ws.append(per_dir)
    for layer in range(num_layers):
        for dd in range(d):
            bi = params[off:off + g * state_size]
            off += g * state_size
            bh = params[off:off + g * state_size]
            off += g * state_size
            ws[layer][dd] += [bi, bh]
    return ws


def _step_fn(mode):
    if mode == "lstm":
        def step(carry, x_t, wi, wh, bi, bh):
            h, c = carry
            gates = x_t @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h
        return step
    if mode == "gru":
        def step(carry, x_t, wi, wh, bi, bh):
            (h,) = carry
            gi = x_t @ wi.T + bi
            gh = h @ wh.T + bh
            ir, iz, inn = jnp.split(gi, 3, axis=-1)
            hr, hz, hn = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            n = jnp.tanh(inn + r * hn)
            h = (1 - z) * n + z * h
            return (h,), h
        return step
    act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

    def step(carry, x_t, wi, wh, bi, bh):
        (h,) = carry
        h = act(x_t @ wi.T + bi + h @ wh.T + bh)
        return (h,), h
    return step


def _scan_dir(step, xs, init, wi, wh, bi, bh, reverse):
    def body(carry, x_t):
        return step(carry, x_t, wi, wh, bi, bh)

    carry, ys = lax.scan(body, init, xs, reverse=reverse)
    return carry, ys


def _use_pallas_lstm():
    """Pallas recurrence kernel on TPU (MXTPU_RNN_IMPL=auto|pallas|scan)."""
    from ..base import getenv

    impl = getenv("RNN_IMPL", "auto").lower()
    if impl == "scan":
        return False
    if impl == "pallas":
        return True
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    if not on_tpu:
        return False
    # auto on TPU: one-time Mosaic compile probe so an un-lowerable
    # recurrence kernel degrades to the lax.scan path instead of
    # erroring mid-train (VERDICT r3 #2; MXTPU_PALLAS_RNN_OK overrides)
    from .pallas.probe import probe_ok

    return probe_ok("rnn", _lstm_compile_probe)


def _lstm_compile_probe():
    """Compile tiny value-and-grad LSTM recurrences, f32 and bf16."""
    from .pallas.rnn import lstm_layer

    T, N, H = 2, 8, 128
    for dt in (jnp.float32, jnp.bfloat16):
        xp = jnp.zeros((T, N, 4 * H), dt)
        wh = jnp.zeros((4 * H, H), dt)
        h0 = jnp.zeros((N, H), dt)
        c0 = jnp.zeros((N, H), dt)

        def _loss(a, b, c, d):
            return lstm_layer(a, b, c, d)[0].astype(jnp.float32).sum()

        jax.jit(jax.grad(_loss)).lower(xp, wh, h0, c0).compile()


def _pallas_lstm_fits(N, H, G=4):
    """Static VMEM guard: the kernel holds Wh (G*H,H) + an x_proj block
    (N,G*H) + states/gates, double-buffered by Mosaic. Stay well under
    the ~16 MB/core VMEM or fall back to lax.scan (same guard idea as
    flash-attention's _tiles_ok)."""
    est = 4 * (G * H * H          # Wh
               + 3 * N * G * H    # x_proj block + gates out + dgates
               + 6 * N * H)       # h/c scratch + ys/cs blocks
    return 2 * est < 12 * 1024 * 1024


def _use_pallas_gru():
    """Pallas GRU recurrence on TPU (same gating scheme as the LSTM)."""
    from ..base import getenv

    impl = getenv("RNN_IMPL", "auto").lower()
    if impl == "scan":
        return False
    if impl == "pallas":
        return True
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        return False
    if not on_tpu:
        return False
    from .pallas.probe import probe_ok

    return probe_ok("gru", _gru_compile_probe)


def _gru_compile_probe():
    """Compile tiny value-and-grad GRU recurrences, f32 and bf16."""
    from .pallas.rnn import gru_layer

    T, N, H = 2, 8, 128
    for dt in (jnp.float32, jnp.bfloat16):
        xp = jnp.zeros((T, N, 3 * H), dt)
        wh = jnp.zeros((3 * H, H), dt)
        bh = jnp.zeros((3 * H,), dt)
        h0 = jnp.zeros((N, H), dt)

        def _loss(a, b, c, d):
            return gru_layer(a, b, c, d)[0].astype(jnp.float32).sum()

        jax.jit(jax.grad(_loss)).lower(xp, wh, bh, h0).compile()


def _pallas_gru_dir(xs, init, wi, wh, bi, bh, reverse):
    """Same cuDNN-style split as the LSTM; bh stays a kernel input (the
    reset gate multiplies its n-slot, so it cannot fold into x_proj)."""
    from .pallas.rnn import gru_layer

    if reverse:
        xs = jnp.flip(xs, axis=0)
    x_proj = xs @ wi.T + bi
    (h0,) = init
    ys, hn = gru_layer(x_proj, wh, bh, h0)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return (hn,), ys


def _pallas_lstm_dir(xs, init, wi, wh, bi, bh, reverse):
    """cuDNN-style split: time-batched input GEMM in XLA (MXU-tiled),
    sequential recurrence in the Pallas kernel (ops/pallas/rnn.py)."""
    from .pallas.rnn import lstm_layer

    if reverse:
        xs = jnp.flip(xs, axis=0)
    x_proj = xs @ wi.T + (bi + bh)
    h0, c0 = init
    ys, hn, cn = lstm_layer(x_proj, wh, h0, c0)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return (hn, cn), ys


def _k_rnn(data, parameters, state, state_cell=None, key=None, *,
           state_size, num_layers, mode="lstm", bidirectional=False,
           p=0.0, state_outputs=False, projection_size=None,
           lstm_state_clip_min=None, lstm_state_clip_max=None,
           use_sequence_length=False, _train=False):
    """data: (seq, batch, input) [TNC].  Returns (out, h_n[, c_n])."""
    d = 2 if bidirectional else 1
    T, N, I = data.shape
    H = state_size
    ws = _unpack(parameters, num_layers, I, H, mode, d)
    step = _step_fn(mode)
    is_lstm = mode == "lstm"

    pallas_lstm = is_lstm and _use_pallas_lstm()
    pallas_gru = mode == "gru" and _use_pallas_gru()
    x = data
    h_states, c_states = [], []
    for layer in range(num_layers):
        outs = []
        for dd in range(d):
            wi, wh, bi, bh = ws[layer][dd]
            idx = layer * d + dd
            h0 = state[idx]
            init = (h0, state_cell[idx]) if is_lstm else (h0,)
            if pallas_lstm and _pallas_lstm_fits(N, H):
                # kernel takes Wh as (4H, H); its step does dgp @ Wh
                carry, ys = _pallas_lstm_dir(x, init, wi, wh, bi, bh,
                                             reverse=(dd == 1))
            elif pallas_gru and _pallas_lstm_fits(N, H, G=3):
                carry, ys = _pallas_gru_dir(x, init, wi, wh, bi, bh,
                                            reverse=(dd == 1))
            else:
                carry, ys = _scan_dir(step, x, init, wi, wh, bi, bh,
                                      reverse=(dd == 1))
            outs.append(ys)
            h_states.append(carry[0])
            if is_lstm:
                c_states.append(carry[1])
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if p > 0 and _train and key is not None and layer < num_layers - 1:
            k = jax.random.fold_in(key, layer)
            mask = jax.random.bernoulli(k, 1 - p, x.shape).astype(x.dtype)
            x = x * mask / (1 - p)
    h_n = jnp.stack(h_states, axis=0)
    if is_lstm:
        return x, h_n, jnp.stack(c_states, axis=0)
    return x, h_n


register("RNN", _k_rnn,
         arg_names=("data", "parameters", "state", "state_cell"),
         aliases=("rnn",), train_aware=True, needs_rng=True, num_outputs=-1)
