"""Shared example-script plumbing (backend selection).

Every example accepts --cpu to skip the TPU tunnel and run on the CPU
backend (tests, laptops, CI). The flag must take effect BEFORE first
device use, which is why examples call apply_backend(args) immediately
after parse_args().
"""


def add_cpu_flag(parser):
    parser.add_argument(
        "--cpu", action="store_true",
        help="force the CPU backend (skip the TPU tunnel)")
    return parser


def apply_backend(args):
    if getattr(args, "cpu", False):
        import jax

        jax.config.update("jax_platforms", "cpu")
