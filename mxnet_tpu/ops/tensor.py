"""Tensor operator family: elemwise, broadcast, reduce, matrix, indexing.

Ref: src/operator/tensor/ (elemwise_binary_op*, broadcast_reduce_op*,
matrix_op*, indexing_op.*, dot-inl.h, init_op.*, ordering_op*) — ~80k LoC
of C++/CUDA in the reference, re-emitted here as XLA HLO through jnp/lax.
Each pure function below is an HLO emitter; XLA fuses elementwise chains
into matmul epilogues on the MXU automatically, which is why this file is
two orders of magnitude smaller than its reference counterpart.

MXNet semantics notes: ``elemwise_*`` requires equal shapes while
``broadcast_*`` broadcasts; both map to the same jnp emitter (XLA
handles both).  Reductions keep MXNet's axis/keepdims conventions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# Elementwise binary (ref: elemwise_binary_op_basic.cc, broadcast ops)

def _k_add(lhs, rhs): return jnp.add(lhs, rhs)
def _k_sub(lhs, rhs): return jnp.subtract(lhs, rhs)
def _k_mul(lhs, rhs): return jnp.multiply(lhs, rhs)
def _k_div(lhs, rhs): return jnp.divide(lhs, rhs)
def _k_mod(lhs, rhs): return jnp.mod(lhs, rhs)
def _k_pow(lhs, rhs): return jnp.power(lhs, rhs)
def _k_maximum(lhs, rhs): return jnp.maximum(lhs, rhs)
def _k_minimum(lhs, rhs): return jnp.minimum(lhs, rhs)
def _k_hypot(lhs, rhs): return jnp.hypot(lhs, rhs)

_BIN = [("add", _k_add, ("plus",)), ("sub", _k_sub, ("minus",)),
        ("mul", _k_mul, ()), ("div", _k_div, ()), ("mod", _k_mod, ()),
        ("power", _k_pow, ()), ("maximum", _k_maximum, ()),
        ("minimum", _k_minimum, ()), ("hypot", _k_hypot, ())]

for _name, _fn, _extra in _BIN:
    register(f"broadcast_{_name}", _fn, arg_names=("lhs", "rhs"),
             aliases=tuple(f"broadcast_{e}" for e in _extra)
             + ((f"elemwise_{_name}", f"_{_name}") if _name in
                ("add", "sub", "mul", "div") else ()))

register("_maximum", _k_maximum, arg_names=("lhs", "rhs"))
register("_minimum", _k_minimum, arg_names=("lhs", "rhs"))


def _k_equal(lhs, rhs): return (lhs == rhs).astype(lhs.dtype)
def _k_not_equal(lhs, rhs): return (lhs != rhs).astype(lhs.dtype)
def _k_greater(lhs, rhs): return (lhs > rhs).astype(lhs.dtype)
def _k_greater_equal(lhs, rhs): return (lhs >= rhs).astype(lhs.dtype)
def _k_lesser(lhs, rhs): return (lhs < rhs).astype(lhs.dtype)
def _k_lesser_equal(lhs, rhs): return (lhs <= rhs).astype(lhs.dtype)
def _k_logical_and(lhs, rhs):
    return jnp.logical_and(lhs != 0, rhs != 0).astype(lhs.dtype)
def _k_logical_or(lhs, rhs):
    return jnp.logical_or(lhs != 0, rhs != 0).astype(lhs.dtype)
def _k_logical_xor(lhs, rhs):
    return jnp.logical_xor(lhs != 0, rhs != 0).astype(lhs.dtype)

for _name, _fn in [("equal", _k_equal), ("not_equal", _k_not_equal),
                   ("greater", _k_greater), ("greater_equal", _k_greater_equal),
                   ("lesser", _k_lesser), ("lesser_equal", _k_lesser_equal),
                   ("logical_and", _k_logical_and),
                   ("logical_or", _k_logical_or),
                   ("logical_xor", _k_logical_xor)]:
    register(f"broadcast_{_name}", _fn, arg_names=("lhs", "rhs"), nondiff=True)

# ---------------------------------------------------------------------------
# Elementwise unary (ref: elemwise_unary_op_basic.cc, trig/pow families)

_UNARY = {
    "exp": jnp.exp, "log": jnp.log, "log2": jnp.log2, "log10": jnp.log10,
    "log1p": jnp.log1p, "expm1": jnp.expm1, "sqrt": jnp.sqrt,
    "cbrt": jnp.cbrt, "square": jnp.square, "abs": jnp.abs,
    "sign": jnp.sign, "floor": jnp.floor, "ceil": jnp.ceil,
    "round": jnp.round, "rint": jnp.rint, "trunc": jnp.trunc,
    "negative": jnp.negative, "reciprocal": lambda x: 1.0 / x,
    "rsqrt": lax.rsqrt, "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "gammaln": jax.scipy.special.gammaln,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "digamma": jax.scipy.special.digamma,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "isnan": lambda x: jnp.isnan(x).astype(jnp.float32),
    "isinf": lambda x: jnp.isinf(x).astype(jnp.float32),
}

def _make_unary(fn):
    def _k(data):
        return fn(data)
    return _k

for _name, _impl in _UNARY.items():
    register(_name, _make_unary(_impl),
             nondiff=_name in ("sign", "floor", "ceil", "round", "rint",
                               "trunc", "logical_not", "isnan", "isinf"))


def _k_sigmoid(data): return jax.nn.sigmoid(data)
def _k_relu(data): return jax.nn.relu(data)
def _k_softsign(data): return jax.nn.soft_sign(data)
def _k_softrelu(data): return jax.nn.softplus(data)
def _k_hard_sigmoid(data, *, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)

register("sigmoid", _k_sigmoid)
register("relu", _k_relu)
register("softsign", _k_softsign)
register("softrelu", _k_softrelu, aliases=("softplus",))
register("hard_sigmoid", _k_hard_sigmoid)


def _k_clip(data, *, a_min, a_max):
    return jnp.clip(data, a_min, a_max)

register("clip", _k_clip)


def _k_smooth_l1(data, *, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(data) < 1.0 / s2,
                     0.5 * s2 * data * data,
                     jnp.abs(data) - 0.5 / s2)

register("smooth_l1", _k_smooth_l1)

# ---------------------------------------------------------------------------
# Reductions (ref: broadcast_reduce_op_value.cc)

def _norm_axis(axis):
    if isinstance(axis, list):
        return tuple(axis)
    return axis


def _k_sum(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.sum(data, axis=_excl(data, axis, exclude), keepdims=keepdims)
def _k_mean(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.mean(data, axis=_excl(data, axis, exclude), keepdims=keepdims)
def _k_prod(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.prod(data, axis=_excl(data, axis, exclude), keepdims=keepdims)
def _k_max(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.max(data, axis=_excl(data, axis, exclude), keepdims=keepdims)
def _k_min(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.min(data, axis=_excl(data, axis, exclude), keepdims=keepdims)
def _k_nansum(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.nansum(data, axis=_excl(data, axis, exclude), keepdims=keepdims)
def _k_nanprod(data, *, axis=None, keepdims=False, exclude=False):
    return jnp.nanprod(data, axis=_excl(data, axis, exclude), keepdims=keepdims)


def _excl(data, axis, exclude):
    axis = _norm_axis(axis)
    if not exclude:
        return axis
    if axis is None:
        return ()
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return tuple(i for i in range(data.ndim) if i not in axis)


register("sum", _k_sum, aliases=("sum_axis",))
register("mean", _k_mean)
register("prod", _k_prod)
register("max", _k_max, aliases=("max_axis",))
register("min", _k_min, aliases=("min_axis",))
register("nansum", _k_nansum)
register("nanprod", _k_nanprod)


def _k_norm(data, *, ord=2, axis=None, keepdims=False):
    axis = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))

register("norm", _k_norm)


def _k_argmax(data, *, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    # float32 output (MXNet parity); under the INT64_TENSOR_SIZE tier
    # result_type(float) widens to f64, which holds indices past 2^24
    # exactly (f32 cannot — the large-tensor suite caught this)
    return out.astype(jnp.result_type(float))
def _k_argmin(data, *, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out.astype(jnp.result_type(float))

register("argmax", _k_argmax, nondiff=True)
register("argmin", _k_argmin, nondiff=True)


def _k_argmax_channel(data):
    return jnp.argmax(data, axis=1).astype(jnp.float32)

register("argmax_channel", _k_argmax_channel, nondiff=True)

# ---------------------------------------------------------------------------
# Matrix ops (ref: dot-inl.h, la_op.cc). MXU-bound: keep operands bf16-able
# and batched; XLA tiles dot_general onto the systolic array.

def _k_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    return jnp.dot(a, b)

register("dot", _k_dot, arg_names=("lhs", "rhs"))


def _k_batch_dot(lhs, rhs, *, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)

register("batch_dot", _k_batch_dot, arg_names=("lhs", "rhs"),
         aliases=("linalg_gemm2",))


def _k_khatri_rao(*args):
    out = args[0]
    for m in args[1:]:
        out = jnp.einsum("i...,j...->ij...", out, m).reshape(
            out.shape[0] * m.shape[0], *out.shape[1:])
    return out

register("khatri_rao", _k_khatri_rao, variadic=True)

# ---------------------------------------------------------------------------
# Shape manipulation (ref: matrix_op.cc)

def mx_reshape_target(in_shape, spec, reverse=False):
    """Resolve MXNet reshape magic codes to a concrete shape (ref:
    matrix_op-inl.h InferReshapeShape): 0 copy input dim, -1 infer one,
    -2 copy all remaining, -3 merge next two, -4 split one dim into the
    following two entries; reverse applies the spec right-to-left."""
    ins = list(in_shape)
    spec = [int(s) for s in spec]
    if reverse:
        if -4 in spec:
            raise ValueError("reshape: reverse=True with -4 split is "
                             "not supported")
        ins, spec = ins[::-1], spec[::-1]
    out, i, j = [], 0, 0
    while j < len(spec):
        s = spec[j]
        if s > 0:
            out.append(s)
            i += 1
        elif s == 0:
            if i >= len(ins):
                raise ValueError(f"reshape 0 at output pos {j} has no "
                                 f"matching input dim for {tuple(in_shape)}")
            out.append(ins[i])
            i += 1
        elif s == -1:
            out.append(-1)
            i += 1
        elif s == -2:
            out.extend(ins[i:])
            i = len(ins)
        elif s == -3:
            if i + 1 >= len(ins):
                raise ValueError("reshape -3 needs two input dims")
            out.append(ins[i] * ins[i + 1])
            i += 2
        elif s == -4:
            if j + 2 >= len(spec):
                raise ValueError("reshape -4 needs two following entries")
            d1, d2 = spec[j + 1], spec[j + 2]
            cur = ins[i]
            if d1 == -1 and d2 == -1:
                raise ValueError("reshape -4 cannot infer both factors")
            if d1 == 0 or d2 == 0 or d1 < -1 or d2 < -1:
                raise ValueError(
                    f"reshape -4 factors must be positive or -1, got "
                    f"({d1}, {d2})")
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            if d1 * d2 != cur:
                raise ValueError(
                    f"reshape -4 split ({spec[j + 1]}, {spec[j + 2]}) "
                    f"does not factor input dim {cur}")
            out.extend([d1, d2])
            i += 1
            j += 2
        else:
            raise ValueError(f"invalid reshape code {s}")
        j += 1
    if reverse:
        out = out[::-1]
    # resolve a single -1 from the total size
    if out.count(-1) > 1:
        raise ValueError(f"reshape can infer at most one dim, got {spec}")
    total = 1
    for d in in_shape:
        total *= d
    if -1 in out:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        if known <= 0 or total % known != 0:
            raise ValueError(
                f"reshape cannot infer -1: input size {total} is not "
                f"divisible by the known dims of {tuple(spec)}")
        out[out.index(-1)] = total // known
    return tuple(out)


def _k_reshape(data, *, shape, reverse=False):
    if any(s <= 0 for s in shape):
        shape = mx_reshape_target(data.shape, shape, reverse)
    return jnp.reshape(data, shape)

register("reshape", _k_reshape, aliases=("Reshape",))


def _k_flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))

register("flatten", _k_flatten, aliases=("Flatten",))


def _k_transpose(data, *, axes=()):
    return jnp.transpose(data, axes if axes else None)

register("transpose", _k_transpose)


def _k_expand_dims(data, *, axis):
    return jnp.expand_dims(data, axis)

register("expand_dims", _k_expand_dims)


def _k_squeeze(data, *, axis=None):
    return jnp.squeeze(data, axis=axis)

register("squeeze", _k_squeeze)


def _k_stack(*args, axis=0):
    return jnp.stack(args, axis=axis)

register("stack", _k_stack, variadic=True)


def _k_concat(*args, dim=1):
    return jnp.concatenate(args, axis=dim)

register("concat", _k_concat, variadic=True, aliases=("Concat",))


def _k_split(data, *, num_outputs, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)

register("split", _k_split, num_outputs=-1,
         aliases=("SliceChannel", "split_v2"))


def _k_add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out

register("add_n", _k_add_n, variadic=True,
         aliases=("ElementWiseSum", "elemwise_sum"))


def _k_broadcast_axis(data, *, axis, size):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    sizes = (size,) if isinstance(size, int) else tuple(size)
    shape = list(data.shape)
    for ax, s in zip(axes, sizes):
        shape[ax] = s
    return jnp.broadcast_to(data, shape)

register("broadcast_axis", _k_broadcast_axis, aliases=("broadcast_axes",))


def _k_broadcast_to(data, *, shape):
    tgt = [d if s == 0 else s for s, d in zip(shape, data.shape)]
    return jnp.broadcast_to(data, tgt)

register("broadcast_to", _k_broadcast_to)


def _k_broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)

register("broadcast_like", _k_broadcast_like, arg_names=("lhs", "rhs"))


def _k_tile(data, *, reps):
    return jnp.tile(data, reps)

register("tile", _k_tile)


def _k_repeat(data, *, repeats, axis=None):
    return jnp.repeat(data, repeats, axis=axis)

register("repeat", _k_repeat)


def _k_flip(data, *, axis):
    return jnp.flip(data, axis)

register("flip", _k_flip, aliases=("reverse",))


def _k_pad(data, *, mode="constant", pad_width=(), constant_value=0.0):
    pw = list(zip(pad_width[::2], pad_width[1::2]))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pw, mode=jmode, constant_values=constant_value)
    return jnp.pad(data, pw, mode=jmode)

register("pad", _k_pad, aliases=("Pad",))


def _k_swapaxes(data, *, dim1=0, dim2=1):
    return jnp.swapaxes(data, dim1, dim2)

register("swapaxes", _k_swapaxes, aliases=("SwapAxis",))


def _k_depth_to_space(data, *, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)

register("depth_to_space", _k_depth_to_space)


def _k_space_to_depth(data, *, block_size):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)

register("space_to_depth", _k_space_to_depth)

# ---------------------------------------------------------------------------
# Slicing & indexing (ref: matrix_op.cc slice*, indexing_op.cc)

def _k_slice(data, *, begin, end, step=()):
    step = step or tuple(1 for _ in begin)
    idx = tuple(slice(b, e, s) for b, e, s in zip(begin, end, step))
    return data[idx]

register("slice", _k_slice)


def _k_slice_axis(data, *, axis, begin, end):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]

register("slice_axis", _k_slice_axis)


def _k_slice_like(data, shape_like, *, axes=()):
    idx = [slice(None)] * data.ndim
    sel = axes if axes else range(min(data.ndim, shape_like.ndim))
    for ax in sel:
        idx[ax] = slice(0, shape_like.shape[ax])
    return data[tuple(idx)]

register("slice_like", _k_slice_like, arg_names=("data", "shape_like"))


def _k_take(a, indices, *, axis=0, mode="clip"):
    m = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    if not jnp.issubdtype(indices.dtype, jnp.integer):
        # float indices (MXNet semantics: truncate) — cast at the
        # default int width so int64 survives the INT64_TENSOR_SIZE
        # tier (a hard int32 cast truncated >2^31 indices)
        indices = indices.astype(jnp.result_type(int))
    return jnp.take(a, indices, axis=axis, mode=m)


def _take_validator(arrays, attrs):
    # mode='raise' cannot raise data-dependently inside jit; do the bounds
    # check host-side (costs a sync, like the reference's CPU take path —
    # its GPU path silently clips, ref: indexing_op.cc)
    if attrs.get("mode") == "raise" and len(arrays) > 1:
        import numpy as _np

        from ..base import MXNetError

        idx = _np.asarray(arrays[1].asnumpy())
        dim = arrays[0].shape[attrs.get("axis", 0)]
        if idx.size and ((idx < -dim).any() or (idx >= dim).any()):
            raise MXNetError(
                f"take: index out of range for axis of size {dim}")


register("take", _k_take, arg_names=("a", "indices"),
         validator=_take_validator)


def _k_pick(data, index, *, axis=-1, keepdims=False, mode="clip"):
    if mode not in ("clip", "wrap"):
        from ..base import MXNetError

        raise MXNetError(f"pick: mode must be 'clip' or 'wrap', "
                         f"got {mode!r}")
    idx = index.astype(jnp.int32)
    dim = data.shape[axis]
    if mode == "wrap":
        idx = idx % dim
    else:  # "clip" (reference default)
        idx = jnp.clip(idx, 0, dim - 1)
    idx = jnp.expand_dims(idx, axis if axis >= 0 else data.ndim + axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out

register("pick", _k_pick, arg_names=("data", "index"),
         aliases=("choose_element_0d",))  # legacy name (ref: mshadow op)


def _k_gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]

register("gather_nd", _k_gather_nd, arg_names=("data", "indices"))


def _k_scatter_nd(data, indices, *, shape):
    out = jnp.zeros(shape, data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].add(data)

register("scatter_nd", _k_scatter_nd, arg_names=("data", "indices"))


def _k_one_hot(indices, *, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth, dtype=jnp.dtype(dtype))
    return oh * (on_value - off_value) + off_value

register("one_hot", _k_one_hot, arg_names=("indices",), nondiff=True)


def _k_where(condition, x, y):
    return jnp.where(condition != 0, x, y)

register("where", _k_where, arg_names=("condition", "x", "y"))


def _k_boolean_mask(data, index, *, axis=0):
    # dynamic output shape: eager-only op (jit_compile=False)
    import numpy as _np

    mask = _np.asarray(index) != 0
    return jnp.compress(mask, data, axis=axis)

register("boolean_mask", _k_boolean_mask, arg_names=("data", "index"),
         jit_compile=False, nondiff=True)

# ---------------------------------------------------------------------------
# Ordering (ref: ordering_op.cc)

def _k_sort(data, *, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)

register("sort", _k_sort)


def _k_argsort(data, *, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.dtype(dtype))

register("argsort", _k_argsort, nondiff=True)


def _k_topk(data, *, axis=-1, k=1, ret_typ="indices", is_ascend=False,
            dtype="float32"):
    if axis != -1 and axis != data.ndim - 1:
        src_m = jnp.moveaxis(data, axis, -1)
    else:
        src_m = data
    # lax.top_k returns the k largest; negate for ascending order
    vals, idxs = lax.top_k(-src_m if is_ascend else src_m, k)
    if is_ascend:
        vals = -vals
    moved = axis != -1 and axis != data.ndim - 1
    if ret_typ == "mask":
        # 1 where the element is among the top-k of its axis slice
        # (ref: ordering_op topk ret_typ=mask); built in the moved
        # layout (k on the last axis), then restored
        onehot = jax.nn.one_hot(idxs, src_m.shape[-1], dtype=data.dtype)
        mask_m = onehot.sum(axis=-2)  # merge the k picks
        return jnp.moveaxis(mask_m, -1, axis) if moved else mask_m
    if moved:
        vals = jnp.moveaxis(vals, -1, axis)
        idxs = jnp.moveaxis(idxs, -1, axis)
    idxs = idxs.astype(jnp.dtype(dtype))
    if ret_typ == "indices":
        return idxs
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idxs
    raise ValueError(ret_typ)

register("topk", _k_topk, nondiff=True, num_outputs=-1)

# ---------------------------------------------------------------------------
# Init-like & casts (ref: init_op.cc, elemwise cast)

def _k_zeros_like(data): return jnp.zeros_like(data)
def _k_ones_like(data): return jnp.ones_like(data)

register("zeros_like", _k_zeros_like, nondiff=True)
register("ones_like", _k_ones_like, nondiff=True)


def _k_cast(data, *, dtype):
    return data.astype(jnp.dtype(dtype))

register("cast", _k_cast, aliases=("Cast",))


def _k_shape_array(data):
    return jnp.array(data.shape, dtype=jnp.int64)

register("shape_array", _k_shape_array, nondiff=True, jit_compile=False)


def _k_size_array(data):
    return jnp.array([data.size], dtype=jnp.int64)

register("size_array", _k_size_array, nondiff=True, jit_compile=False)


def _k_identity(data):
    return data

register("identity", _k_identity, aliases=("_copy",))


def _k_stop_gradient(data):
    return lax.stop_gradient(data)

register("stop_gradient", _k_stop_gradient, aliases=("BlockGrad",))


def _k_make_loss(data):
    return data

register("make_loss", _k_make_loss, aliases=("MakeLoss",))


def _k_diag(data, *, k=0):
    if data.ndim == 1:
        return jnp.diag(data, k)
    return jnp.diagonal(data, offset=k, axis1=-2, axis2=-1)

register("diag", _k_diag)


def _k_embedding(data, weight, *, input_dim, output_dim, dtype="float32",
                 sparse_grad=False):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)

register("Embedding", _k_embedding, arg_names=("data", "weight"),
         aliases=("embedding",))

# ---------------------------------------------------------------------------
# Sequence ops (ref: src/operator/sequence_*.cc — transformer/RNN era
# building blocks)

def _seq_mask(data, sequence_length, *, use_sequence_length, value):
    if not use_sequence_length:
        return data
    # data: (seq, batch, ...)
    steps = jnp.arange(data.shape[0])
    mask = steps[:, None] < sequence_length.astype(jnp.int32)[None, :]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


def _k_sequence_mask(data, sequence_length=None, *, use_sequence_length=False,
                     value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    if axis == 1:
        data = jnp.swapaxes(data, 0, 1)
    out = _seq_mask(data, sequence_length, use_sequence_length=True,
                    value=value)
    if axis == 1:
        out = jnp.swapaxes(out, 0, 1)
    return out

register("SequenceMask", _k_sequence_mask,
         arg_names=("data", "sequence_length"), aliases=("sequence_mask",))


def _k_sequence_last(data, sequence_length=None, *, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    if axis == 1:
        data = jnp.swapaxes(data, 0, 1)
    last = (sequence_length.astype(jnp.int32) - 1)
    out = jnp.take_along_axis(
        data, last.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0)[0]
    return out

register("SequenceLast", _k_sequence_last,
         arg_names=("data", "sequence_length"), aliases=("sequence_last",))


def _k_sequence_reverse(data, sequence_length=None, *,
                        use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    rev_idx = jnp.where(steps < L, L - 1 - steps, steps)
    return jnp.take_along_axis(
        data, rev_idx.reshape(rev_idx.shape + (1,) * (data.ndim - 2)), axis=0)

register("SequenceReverse", _k_sequence_reverse,
         arg_names=("data", "sequence_length"), aliases=("sequence_reverse",))


def _k_div_sqrt_dim(data):
    return data / jnp.sqrt(jnp.asarray(data.shape[-1], data.dtype))

register("_contrib_div_sqrt_dim", _k_div_sqrt_dim)


# ---------------------------------------------------------------------------
# long-tail parity ops (round 2 audit vs src/operator/tensor/)


def _k_cumsum(a, *, axis=None, dtype=None):
    """Cumulative sum (ref: np_cumsum / mx.nd.cumsum)."""
    x = a if dtype is None else a.astype(dtype)
    if axis is None:
        return jnp.cumsum(x.reshape(-1))
    return jnp.cumsum(x, axis=axis)

register("cumsum", _k_cumsum, arg_names=("a",))


def _k_fix(data):
    """Round toward zero (ref: fix op)."""
    return jnp.trunc(data)

register("fix", _k_fix)


def _k_batch_take(a, indices):
    """a[i, indices[i]] per batch row (ref: batch_take)."""
    idx = indices.astype(jnp.int32).reshape(-1)
    return a[jnp.arange(a.shape[0]), idx]

register("batch_take", _k_batch_take, arg_names=("a", "indices"))


def _row_major_strides(shape):
    """Integer row-major strides for a dim tuple (shared by ravel/
    unravel) — float stride math corrupts indices past the mantissa
    (2^24 for the default float32).  jnp's widest int (int32 unless
    jax_enable_x64) covers tensors to 2^31 elements."""
    idt = jnp.asarray(0).dtype  # int32, or int64 under x64
    dims = jnp.asarray(shape, idt)
    return dims, jnp.concatenate(
        [jnp.cumprod(dims[::-1])[::-1][1:], jnp.ones((1,), idt)])


def _k_ravel_multi_index(data, *, shape):
    """N-d coords -> flat indices (ref: _ravel_multi_index).
    data: (ndim, n) array, shape: target dims.  Output is integer:
    a float32 result would corrupt indices past the 2^24 mantissa."""
    _, strides = _row_major_strides(shape)
    flat = (data.astype(strides.dtype) * strides[:, None]).sum(axis=0)
    if jnp.issubdtype(data.dtype, jnp.integer):
        return flat.astype(data.dtype)
    return flat.astype(jnp.int32)

register("_ravel_multi_index", _k_ravel_multi_index,
         aliases=("ravel_multi_index",), nondiff=True)


def _k_unravel_index(data, *, shape):
    """Flat indices -> N-d coords, output (ndim,) + data.shape
    (ref: _unravel_index)."""
    dims, strides = _row_major_strides(shape)
    flat = data.astype(strides.dtype).reshape(-1)
    coords = (flat[None, :] // strides[:, None]) % dims[:, None]
    out = coords.reshape((len(shape),) + data.shape)
    if jnp.issubdtype(data.dtype, jnp.integer):
        return out.astype(data.dtype)
    return out.astype(jnp.int32)

register("_unravel_index", _k_unravel_index,
         aliases=("unravel_index",), nondiff=True)


def _k_crop(data, *, offset=(0, 0), h_w=(0, 0), center_crop=False):
    """Legacy Crop op on NCHW (ref: src/operator/crop.cc)."""
    H, W = data.shape[2], data.shape[3]
    ch, cw = int(h_w[0]) or H, int(h_w[1]) or W
    if center_crop:
        y0, x0 = (H - ch) // 2, (W - cw) // 2
    else:
        y0, x0 = int(offset[0]), int(offset[1])
    # ref crop.cc CHECKs bounds; silent truncation/wraparound would
    # surface as a confusing shape mismatch far downstream
    if y0 < 0 or x0 < 0 or y0 + ch > H or x0 + cw > W:
        raise ValueError(
            f"Crop out of bounds: offset=({y0},{x0}) h_w=({ch},{cw}) "
            f"on input {H}x{W}")
    return data[:, :, y0:y0 + ch, x0:x0 + cw]

register("Crop", _k_crop, aliases=("crop_legacy",))


def _k_reshape_like(lhs, rhs, *, lhs_begin=None, lhs_end=None,
                    rhs_begin=None, rhs_end=None):
    """Reshape lhs to rhs's shape; the *_begin/*_end attrs reshape only
    the [lhs_begin, lhs_end) axes of lhs onto the [rhs_begin, rhs_end)
    axes of rhs (ref matrix_op reshape_like)."""
    if lhs_begin is None and lhs_end is None and rhs_begin is None \
            and rhs_end is None:
        return jnp.reshape(lhs, rhs.shape)
    lb = 0 if lhs_begin is None else int(lhs_begin) % (lhs.ndim + 1)
    le = lhs.ndim if lhs_end is None else int(lhs_end) % (lhs.ndim + 1)
    rb = 0 if rhs_begin is None else int(rhs_begin) % (rhs.ndim + 1)
    re_ = rhs.ndim if rhs_end is None else int(rhs_end) % (rhs.ndim + 1)
    new_shape = lhs.shape[:lb] + rhs.shape[rb:re_] + lhs.shape[le:]
    return jnp.reshape(lhs, new_shape)

register("reshape_like", _k_reshape_like, arg_names=("lhs", "rhs"),
         doc=_k_reshape_like.__doc__)


@jax.custom_vjp
def _kl_sparse_core(data, opts_dummy):
    return data


def _kl_fwd(data, opts_dummy):
    return data, (data, opts_dummy)


def _kl_bwd(res, g):
    data, opts = res
    target, scale = opts[0], opts[1]
    # ref identity_attach_KL_sparse_reg-inl.h: the input IS the sigmoid
    # activation; rho = batch mean, penalty gradient added directly
    rho = jnp.clip(jnp.mean(data, axis=0), 1e-6, 1 - 1e-6)
    dkl = (-target / rho + (1 - target) / (1 - rho)) * scale
    reg = jnp.broadcast_to(dkl, data.shape).astype(g.dtype)
    return g + reg, jnp.zeros_like(opts)


_kl_sparse_core.defvjp(_kl_fwd, _kl_bwd)


def _k_identity_attach_kl_sparse_reg(data, *, sparseness_target=0.1,
                                     penalty=0.001, momentum=0.9):
    """Identity forward; backward adds the KL-sparseness penalty
    gradient pushing the batch-mean of the (already-sigmoid) input
    toward sparseness_target (ref:
    identity_attach_KL_sparse_reg-inl.h; the reference's moving-average
    rho estimate is not kept — rho is the current batch mean)."""
    opts = jnp.asarray([sparseness_target, penalty], jnp.float32)
    return _kl_sparse_core(data, opts)


register("IdentityAttachKLSparseReg", _k_identity_attach_kl_sparse_reg,
         arg_names=("data",), jit_compile=False,
         doc=_k_identity_attach_kl_sparse_reg.__doc__)
