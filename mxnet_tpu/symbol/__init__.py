"""Symbolic frontend (ref: python/mxnet/symbol/)."""
from .symbol import (Symbol, Executor, var, Variable, load, fromjson,  # noqa: F401
                     Group, AttrScope)
from . import symbol as _symbol_mod
from . import export  # noqa: F401
from ..ndarray import (_ContribNamespace, _PrefixNamespace,
                       _RandomNamespace)

contrib = _ContribNamespace(_symbol_mod)
random = _RandomNamespace(_symbol_mod)
linalg = _PrefixNamespace(_symbol_mod, "_linalg_", "linalg")


def one_hot(indices, depth=None, **kwargs):
    """Positional-depth shim matching mx.nd.one_hot (see ndarray)."""
    if depth is None:
        raise TypeError("one_hot requires depth")
    return _symbol_mod.one_hot(indices, depth=int(depth), **kwargs)


def __getattr__(name):
    return getattr(_symbol_mod, name)
