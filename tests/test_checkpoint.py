"""mxnet_tpu.checkpoint — atomic, async, resumable checkpoints.

Covers the subsystem's contract: a training loop can be killed mid-run
and resumed via restore(latest()) with bit-identical parameters,
optimizer states, RNG stream, and step counter, in both ThreadedEngine
and NaiveEngine modes; an interrupted (uncommitted) save is never
selected by latest(); an async save on the d2h lane does not block
concurrently pushed compute.
"""
import os
import pickle
import signal
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd
from mxnet_tpu.gluon import nn


@pytest.fixture
def engine_mode():
    """Restore the engine type a test switches."""
    prev = mx.engine.engine_type()
    yield mx.engine.set_engine_type
    mx.engine.set_engine_type(prev)


def _train(net, trainer, steps, x):
    out = []
    for _ in range(steps):
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        trainer.step(1)
        out.append(float(loss.asnumpy()))
    return out


def _fresh(seed):
    mx.random.seed(seed)
    net = nn.Dense(4, in_units=3)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    return net, trainer


@pytest.mark.parametrize("mode", ["ThreadedEngine", "NaiveEngine"])
def test_save_kill_restore_roundtrip(tmp_path, engine_mode, mode):
    """Acceptance: save → "kill" (fresh process stand-ins) → restore is
    bit-identical for params, optimizer states, RNG, and step."""
    engine_mode(mode)
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    net, trainer = _fresh(7)
    _train(net, trainer, 3, x)
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=3)
    mgr.save(3, params=net, trainer=trainer, epoch=1, extra={"lr": 0.1})
    mgr.wait_until_finished()

    # the uninterrupted run continues: more steps + an RNG draw
    w_saved = net.weight.data().asnumpy().copy()
    cont_losses = _train(net, trainer, 2, x)
    cont_draw = mx.random.uniform(shape=(3,)).asnumpy()

    # "killed" run resumes in a fresh trainer with different init
    net2, trainer2 = _fresh(999)
    meta = mgr.restore(params=net2, trainer=trainer2)
    assert meta["step"] == 3 and meta["epoch"] == 1
    assert meta["extra"] == {"lr": 0.1}
    assert np.array_equal(net2.weight.data().asnumpy(), w_saved)
    assert trainer2._optimizer.num_update == 3
    ctx = net2.weight.list_ctx()[0]
    st = trainer2._states[0][ctx].asnumpy()
    # momentum buffer restored bit-identically → identical trajectory
    resumed_losses = _train(net2, trainer2, 2, x)
    np.testing.assert_array_equal(resumed_losses, cont_losses)
    resumed_draw = mx.random.uniform(shape=(3,)).asnumpy()
    np.testing.assert_array_equal(resumed_draw, cont_draw)
    assert st.shape == net2.weight.shape


def test_uncommitted_save_never_latest(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=5)
    mgr.save(4, params={"w": nd.ones((2, 2))}, sync=True)
    # interrupted saves: a temp dir and a renamed dir missing its manifest
    os.makedirs(str(tmp_path / "ckpt-00000009.tmp"))
    os.makedirs(str(tmp_path / "ckpt-00000010"))
    assert mgr.latest() == 4
    assert mgr.steps() == [4]
    with pytest.raises(mx.MXNetError, match="missing or uncommitted"):
        mgr.restore(step=10)
    assert checkpoint.latest(str(tmp_path)) == 4
    assert checkpoint.latest(str(tmp_path / "nope")) is None


def test_resave_same_step_never_loses_committed_copy(tmp_path):
    """Re-saving an existing step parks the committed copy aside until
    the new commit lands (no rmtree-before-rename window), and a kill
    inside the two-rename window is healed by _recover."""
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=3)
    mgr.save(5, params={"w": nd.ones((2,))}, sync=True)
    mgr.save(5, params={"w": nd.ones((2,)) * 2}, sync=True)  # re-save
    tgt = {"w": nd.zeros((2,))}
    mgr.restore(step=5, params=tgt)
    assert np.allclose(tgt["w"].asnumpy(), 2.0)
    assert not os.path.exists(str(tmp_path / "ckpt-00000005.old"))
    # simulate the crash window: final renamed aside, commit never done
    os.rename(str(tmp_path / "ckpt-00000005"),
              str(tmp_path / "ckpt-00000005.old"))
    assert checkpoint.CheckpointManager(str(tmp_path)).latest() == 5


def test_restore_without_any_checkpoint_raises(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    with pytest.raises(mx.MXNetError, match="no committed checkpoint"):
        mgr.restore()


def test_keep_n_retention_and_tmp_gc(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=2)
    stale = tmp_path / "ckpt-00000001.tmp"  # a crashed save's leftovers
    os.makedirs(str(stale))
    for s in range(1, 6):
        mgr.save(s, params={"w": nd.ones((2,)) * s}, sync=True)
    assert mgr.steps() == [4, 5]
    assert not stale.exists(), "stale temp dir must be garbage-collected"
    tgt = {"w": nd.zeros((2,))}
    mgr.restore(params=tgt)
    assert np.allclose(tgt["w"].asnumpy(), 5.0)


def test_async_save_does_not_block_compute(tmp_path, engine_mode):
    """Satellite: a CheckpointManager.save parked on the d2h stream must
    not stall a concurrently pushed compute op (the whole point of the
    d2h lane).  A gate blocks the d2h lane; compute completes and the
    save future is still pending until the gate opens."""
    engine_mode("ThreadedEngine")
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=2)
    gate = threading.Event()
    mgr._stream.push(gate.wait)  # head-of-line blocker on the d2h lane
    try:
        fut = mgr.save(1, params={"w": nd.ones((16, 16))})
        assert not fut.done()
        # compute proceeds while the checkpoint drains behind the gate
        val = float((nd.ones((32, 32)) * 3).sum().asnumpy())
        assert val == 32 * 32 * 3
        assert not fut.done(), "save must still be parked on the d2h lane"
    finally:
        gate.set()
    mgr.wait_until_finished()
    assert mgr.latest() == 1


def test_async_save_error_surfaces_at_barrier(tmp_path):
    """Errors from the async write surface at wait_until_finished (or
    the next save), never silently; a failed save never commits."""
    class Boom:
        def __array__(self, *a, **k):
            raise RuntimeError("boom: disk-side serialization failure")

    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=2)
    mgr.save(1, params={"w": Boom()})  # fails during the async write
    with pytest.raises(RuntimeError, match="boom"):
        mgr.wait_until_finished()
    assert mgr.latest() is None
    # the barrier drained the failure: the next save succeeds
    mgr.save(2, params={"w": nd.ones((2,))}, sync=True)
    assert mgr.latest() == 2


def test_sigterm_hook_final_save_and_chain(tmp_path):
    """Preemption: SIGTERM triggers a final synchronous save, then the
    previous handler still runs."""
    chained = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    try:
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=2)
        mgr.install_sigterm_hook(
            lambda: {"step": 3, "params": {"w": nd.ones((2,))}})
        # re-install replaces the state provider WITHOUT re-chaining
        # (a handler chained to itself would recurse on delivery)
        mgr.install_sigterm_hook(
            lambda: {"step": 11, "params": {"w": nd.ones((2,))}})
        os.kill(os.getpid(), signal.SIGTERM)
        assert mgr.latest() == 11, "final save must be committed"
        assert chained == [signal.SIGTERM]
        mgr.uninstall_sigterm_hook()
        # uninstalled: the old handler is back
        os.kill(os.getpid(), signal.SIGTERM)
        assert chained == [signal.SIGTERM, signal.SIGTERM]
        assert mgr.latest() == 11
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_trainer_states_blob_is_versioned(tmp_path):
    x = nd.ones((2, 3))
    net, trainer = _fresh(3)
    _train(net, trainer, 1, x)
    f = str(tmp_path / "t.states")
    trainer.save_states(f)
    with open(f, "rb") as fh:
        blob = pickle.load(fh)
    assert blob["version"] == gluon.Trainer.STATES_FORMAT_VERSION
    # the write commits atomically: no temp droppings, and re-saving
    # replaces the published name in one rename
    trainer.save_states(f)
    assert [n for n in os.listdir(str(tmp_path)) if ".tmp" in n] == []
    trainer.load_states(f)


def test_trainer_states_version_mismatch_rejected(tmp_path):
    x = nd.ones((2, 3))
    net, trainer = _fresh(3)
    _train(net, trainer, 1, x)
    legacy = str(tmp_path / "legacy.states")
    with open(legacy, "wb") as f:  # round-0 layout: bare dict, no version
        pickle.dump({"states": {}, "num_update": 7,
                     "index_update_count": {}}, f)
    trainer.load_states(legacy)  # identical to v1 minus the key: loads
    assert trainer._optimizer.num_update == 7
    bogus = str(tmp_path / "bogus.states")
    with open(bogus, "wb") as f:  # unversioned AND unrecognized layout
        pickle.dump({"weights": []}, f)
    with pytest.raises(mx.MXNetError, match="unversioned"):
        trainer.load_states(bogus)
    newer = str(tmp_path / "newer.states")
    with open(newer, "wb") as f:
        pickle.dump({"version": 99, "states": {}}, f)
    with pytest.raises(mx.MXNetError, match="v99"):
        trainer.load_states(newer)


def test_rng_state_roundtrip():
    mx.random.seed(42)
    mx.random.uniform(shape=(2,))  # advance the counter
    snap = mx.random.get_state()
    a = mx.random.uniform(shape=(4,)).asnumpy()
    a_np = mx.random.np_rng().rand(3)
    mx.random.seed(1)  # trash the stream
    mx.random.set_state(snap)
    b = mx.random.uniform(shape=(4,)).asnumpy()
    b_np = mx.random.np_rng().rand(3)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a_np, b_np)


def test_do_checkpoint_routes_through_manager(tmp_path):
    """do_checkpoint accepts a CheckpointManager: epoch-end saves commit
    through the atomic layout; the legacy prefix shim keeps writing the
    reference's -symbol.json/-NNNN.params files."""
    from mxnet_tpu import symbol as sym_mod

    s = sym_mod.Variable("data") * 2
    arg = {"w": nd.ones((2, 2))}
    aux = {"m": nd.zeros((2,))}

    mgr = checkpoint.CheckpointManager(str(tmp_path / "mgr"), keep_n=3)
    cb = mx.callback.do_checkpoint(mgr, period=2)
    cb(0, s, arg, aux)          # epoch 1: not a period boundary
    mgr.wait_until_finished()
    assert mgr.latest() is None
    cb(1, s, arg, aux)          # epoch 2: commits
    mgr.wait_until_finished()
    assert mgr.latest() == 2
    meta = mgr.restore()
    assert set(meta["params"]) == {"arg:w", "aux:m"}
    assert "symbol" in meta["extra"]

    prefix = str(tmp_path / "legacy" / "model")
    os.makedirs(str(tmp_path / "legacy"))
    cb2 = mx.callback.do_checkpoint(prefix, period=1)
    cb2(0, s, arg, aux)
    assert os.path.exists(f"{prefix}-symbol.json")
    assert os.path.exists(f"{prefix}-0001.params")
    back = nd.load(f"{prefix}-0001.params")
    assert set(back) == {"arg:w", "aux:m"}


def test_module_save_checkpoint_atomic(tmp_path):
    """module.save_checkpoint commits via the atomic writer: loadable
    output, no temp droppings under the published names."""
    from mxnet_tpu.module.module import load_checkpoint, save_checkpoint
    from mxnet_tpu import symbol as sym_mod

    s = sym_mod.Variable("data") * 2
    prefix = str(tmp_path / "m")
    save_checkpoint(prefix, 3, s, {"w": nd.ones((2, 2))},
                    {"m": nd.zeros((2,))})
    leftovers = [n for n in os.listdir(str(tmp_path)) if ".tmp" in n]
    assert leftovers == []
    sym2, arg2, aux2 = load_checkpoint(prefix, 3)
    assert np.allclose(arg2["w"].asnumpy(), 1.0)
    assert np.allclose(aux2["m"].asnumpy(), 0.0)


def test_serialization_version_embedded_and_future_rejected(tmp_path):
    import json
    import struct

    from mxnet_tpu.utils import serialization

    f = str(tmp_path / "x.params")
    serialization.save_ndarrays(f, {"a": nd.ones((2,))})
    with open(f, "rb") as fh:
        fh.read(len(serialization._MAGIC))
        (mlen,) = struct.unpack("<Q", fh.read(8))
        manifest = json.loads(fh.read(mlen).decode())
    assert manifest["version"] == serialization.FORMAT_VERSION

    # a file from a future format version is rejected, not misparsed
    fut = str(tmp_path / "future.params")
    m = json.dumps({"version": 99, "names": None, "tensors": []}).encode()
    with open(fut, "wb") as fh:
        fh.write(serialization._MAGIC)
        fh.write(struct.pack("<Q", len(m)))
        fh.write(m)
    with pytest.raises(mx.MXNetError, match="v99"):
        serialization.load_ndarrays(fut)


def test_checkpoint_save_restore_profiled(tmp_path):
    """Save/restore are bracketed as profiler op scopes (cat=checkpoint)."""
    import json

    from mxnet_tpu import profiler

    profiler.reset()
    profiler.start()
    try:
        mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=2)
        mgr.save(1, params={"w": nd.ones((2,))}, sync=True)
        mgr.restore()
    finally:
        profiler.stop()
    events = json.loads(profiler.dumps(reset=True))["traceEvents"]
    names = {e["name"] for e in events if e.get("cat") == "checkpoint"}
    assert {"checkpoint.save.capture", "checkpoint.save.readback",
            "checkpoint.save.commit", "checkpoint.restore"} <= names
