"""Automatic naming of symbols (ref: python/mxnet/name.py).

`NameManager.current()` hands out `hint0, hint1, ...` names for
anonymous symbols; `with Prefix("foo_"):` scopes a prefix onto every
auto-generated name. The symbol builder consults the active manager,
so naming is thread-local and context-scoped exactly like the
reference's `NameManager`/`Prefix` pair.
"""
from __future__ import annotations

import threading

_state = threading.local()


class NameManager:
    """Scoped counter-based namer (ref: mx.name.NameManager)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        """Return `name` if given, else a fresh `hint{i}` name."""
        if name:
            return name
        i = self._counter.get(hint, 0)
        self._counter[hint] = i + 1
        return f"{hint}{i}"

    def __enter__(self):
        _stack().append(self)
        return self

    def __exit__(self, *args):
        _stack().pop()

    @staticmethod
    def current():
        return _stack()[-1]


class Prefix(NameManager):
    """NameManager that prepends a fixed prefix (ref: mx.name.Prefix)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        if name:
            return name
        return self._prefix + super().get(None, hint)


def _stack():
    if not hasattr(_state, "stack"):
        _state.stack = [NameManager()]
    return _state.stack
