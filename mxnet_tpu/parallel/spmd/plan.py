"""ShardingPlan — param/activation PartitionSpecs for a multi-axis mesh.

GSPMD semantics (arXiv 2112.01075): a ``PartitionSpec`` is a LAYOUT
declaration, never a math change — XLA inserts the
allgather/reduce-scatter/allreduce collectives the declared layouts
imply, and the program computes the same global values whatever the
specs say.  That property shapes this API: the plan's auto-rules are
free to shard liberally (a bad choice costs bandwidth, not
correctness), and a user override per block path is a one-liner, not a
model rewrite.

Auto-rules (the Megatron-ish defaults, applied by param NAME + shape):

- 2-D weights (``(units, in_units)`` Dense/linear layout): shard dim 0
  — the output-features/attention-heads dim — over ``'mp'`` when
  divisible (column parallel), else dim 1 (row parallel), else
  replicate.  Names matching an output-projection pattern
  (``*out_proj*``, ``*o_proj*``) prefer dim 1 first, pairing the
  row-split with the preceding column-split so the boundary needs one
  reduce instead of two reshards.
- 4-D conv kernels: shard dim 0 (output channels) when divisible.
- 1-D vectors (bias/gamma/beta): shard dim 0 when divisible — they
  follow a column-split weight's output dim.
- Everything else: replicate.

Optimizer state follows the param spec, PLUS — when ZeRO-1 is on
(``Trainer(zero_shard=True)``) — ``'dp'`` on the first still-free
divisible dim: params shard over 'mp' while their Adam/momentum state
shards over 'mp' × 'dp', the ZeRO composition ROADMAP item 1 names.
The whole-step executable pins these as jit out_shardings, so the
state physically occupies 1/(dp·mp) of its full bytes per device.
"""
from __future__ import annotations

from fnmatch import fnmatchcase

from ...base import MXNetError

# name patterns whose 2-D weights prefer a ROW split (dim 1): the
# output projection following a heads-split attention/MLP block
_ROW_FIRST = ("*out_proj*", "*o_proj*", "*outproj*", "*proj_out*")


class ShardingPlan:
    """Per-parameter ``PartitionSpec`` assignment for one mesh.

    ``override(pattern, spec)`` pins every param whose full name
    matches the glob ``pattern`` (first match wins, registration
    order); unmatched params take the auto-rules above.  Specs may
    name only axes the mesh has — an unknown axis raises immediately,
    not at trace time."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._overrides = []  # [(pattern, PartitionSpec)]

    # -- declaration --------------------------------------------------------

    def override(self, pattern, spec):
        """Pin params matching glob ``pattern`` to ``spec`` (a
        ``PartitionSpec`` or a tuple of axis names/None per dim).
        Returns self for chaining."""
        from jax.sharding import PartitionSpec

        if not isinstance(spec, PartitionSpec):
            spec = PartitionSpec(*spec)
        for axis in spec:
            for a in (axis if isinstance(axis, tuple) else (axis,)):
                if a is not None and a not in self.mesh.axis_names:
                    raise MXNetError(
                        f"ShardingPlan override {pattern!r} names mesh "
                        f"axis {a!r} but the mesh axes are "
                        f"{tuple(self.mesh.axis_names)}")
        self._overrides.append((str(pattern), spec))
        return self

    # -- resolution ---------------------------------------------------------

    def param_spec(self, name, shape):
        """The ``PartitionSpec`` for param ``name`` of ``shape``."""
        from jax.sharding import PartitionSpec

        shape = tuple(int(d) for d in shape)
        for pattern, spec in self._overrides:
            if fnmatchcase(name, pattern):
                if len(spec) > len(shape):
                    raise MXNetError(
                        f"ShardingPlan override {pattern!r} has "
                        f"{len(spec)} dims but param {name!r} has "
                        f"shape {shape}")
                return spec
        mp = self.mesh.shape.get("mp", 1)
        if mp <= 1:
            return PartitionSpec()
        if len(shape) == 2:
            order = (1, 0) if any(fnmatchcase(name, p)
                                  for p in _ROW_FIRST) else (0, 1)
            for d in order:
                if shape[d] % mp == 0 and shape[d] >= mp:
                    dims = [None, None]
                    dims[d] = "mp"
                    return PartitionSpec(*dims)
            return PartitionSpec()
        if len(shape) == 4 and shape[0] % mp == 0 and shape[0] >= mp:
            return PartitionSpec("mp")
        if len(shape) == 1 and shape[0] % mp == 0 and shape[0] >= mp:
            return PartitionSpec("mp")
        return PartitionSpec()

    def state_spec(self, name, shape, zero=False):
        """The optimizer-state spec for param ``name``: the param spec,
        plus — under ZeRO — ``'dp'`` on the first unsharded dim the dp
        size divides (state arrays are param-shaped, so the composition
        is purely additive)."""
        from jax.sharding import PartitionSpec

        shape = tuple(int(d) for d in shape)
        pspec = self.param_spec(name, shape)
        if not zero:
            return pspec
        dp = self.mesh.shape.get("dp", 1)
        if dp <= 1:
            return pspec
        dims = list(pspec) + [None] * (len(shape) - len(pspec))
        for i, d in enumerate(dims):
            if d is None and shape[i] % dp == 0 and shape[i] >= dp:
                dims[i] = "dp"
                break
        return PartitionSpec(*dims)

    def batch_spec(self):
        """Dim-0 spec for batch inputs: the data axes present on the
        mesh (hierarchical ('dcn','dp') when both exist)."""
        from jax.sharding import PartitionSpec

        from .. import mesh as _mesh_mod

        axes = _mesh_mod.data_axes(self.mesh)
        if not axes:
            return PartitionSpec()
        return PartitionSpec(axes if len(axes) > 1 else axes[0])

    # -- shardings ----------------------------------------------------------

    def param_sharding(self, name, shape):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.param_spec(name, shape))

    def state_sharding(self, name, shape, zero=False):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh,
                             self.state_spec(name, shape, zero=zero))

    def batch_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self.batch_spec())

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec

        return NamedSharding(self.mesh, PartitionSpec())

    def constrain(self, x, *spec):
        """``with_sharding_constraint`` under this plan's mesh — for
        HybridBlocks that want to pin an ACTIVATION layout mid-forward
        (e.g. re-sharding at a stage boundary).  Accepts NDArray or raw
        jax arrays; a no-op outside a trace on a different mesh."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from ...ndarray.ndarray import NDArray, _wrap

        sh = NamedSharding(self.mesh, PartitionSpec(*spec))
        if isinstance(x, NDArray):
            return _wrap(jax.lax.with_sharding_constraint(x._data, sh))
        return jax.lax.with_sharding_constraint(x, sh)
