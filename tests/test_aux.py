"""Aux subsystem tests: profiler, test_utils, image, amp, monitor
(ref: test_profiler.py, test_image.py)."""
import json

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def test_profiler_op_trace(tmp_path):
    from mxnet_tpu import profiler

    f = str(tmp_path / "trace.json")
    profiler.set_config(profile_all=True, filename=f, sync=True)
    profiler.start()
    x = nd.ones((32, 32))
    y = nd.dot(x, x)
    y = nd.relu(y)
    y.wait_to_read()
    profiler.stop()
    profiler.dump()
    data = json.loads(open(f).read())
    names = [e["name"] for e in data["traceEvents"]]
    assert any("dot" in n for n in names), names
    assert any("relu" in n for n in names), names
    profiler.reset()


def test_profiler_memory_events():
    """profile_memory=True records 'C' (counter) memory events with pool
    occupancy and exposes running peaks (ref: profiler.cc DeviceStats
    memory-pool events; VERDICT r4 #7)."""
    from mxnet_tpu import profiler

    profiler.reset()
    profiler.set_config(profile_memory=True, aggregate_stats=True,
                        sync=True)
    try:
        profiler.start()
        nd.dot(nd.ones((16, 16)), nd.ones((16, 16))).wait_to_read()
        profiler.stop()
        data = json.loads(profiler.dumps())
        mem_events = [e for e in data["traceEvents"]
                      if e.get("cat") == "memory"]
        assert mem_events, "no memory counter events recorded"
        assert mem_events[0]["ph"] == "C"
        assert "pool_used_bytes" in mem_events[0]["args"]
        assert "memoryPeaks" in data
        table = profiler.dumps(format="table")
        assert "Memory Statistics" in table
        assert "pool_used_bytes" in table
    finally:
        profiler.set_config(profile_memory=False, aggregate_stats=False,
                            sync=False)
        profiler.reset()


def test_profiler_pause_resume():
    from mxnet_tpu import profiler

    profiler.reset()
    profiler.start()
    profiler.pause()
    nd.relu(nd.ones((2, 2))).wait_to_read()
    profiler.resume()
    nd.sigmoid(nd.ones((2, 2))).wait_to_read()
    profiler.stop()
    names = [e["name"] for e in
             json.loads(profiler.dumps(reset=True))["traceEvents"]]
    assert not any("relu" in n for n in names)
    assert any("sigmoid" in n for n in names)


def test_check_numeric_gradient():
    from mxnet_tpu.test_utils import check_numeric_gradient

    check_numeric_gradient(lambda x: (x * x).sum() * 0.5 + x.sum(),
                           [np.random.rand(3, 3).astype(np.float32)])


def test_check_consistency_cpu_vs_xla():
    from mxnet_tpu.test_utils import check_consistency

    check_consistency(lambda x: nd.softmax(nd.dot(x, x.T)),
                      [np.random.rand(4, 4).astype(np.float32)])


def test_with_seed_decorator():
    from mxnet_tpu.test_utils import with_seed

    vals = []

    @with_seed(42)
    def sample():
        vals.append(nd.random.uniform(shape=(3,)).asnumpy())

    sample()
    sample()
    assert np.allclose(vals[0], vals[1])


def test_assert_almost_equal_raises():
    from mxnet_tpu.test_utils import assert_almost_equal

    assert_almost_equal(nd.ones((2,)), np.ones(2))
    with pytest.raises(AssertionError):
        assert_almost_equal(nd.ones((2,)), np.zeros(2))


def test_image_utils():
    from mxnet_tpu import image

    img = nd.array((np.random.rand(40, 50, 3) * 255).astype(np.uint8),
                   dtype=np.uint8)
    r = image.imresize(img, 32, 24)
    assert r.shape == (24, 32, 3)
    rs = image.resize_short(img, 20)
    assert min(rs.shape[:2]) == 20
    cc, rect = image.center_crop(img, (16, 16))
    assert cc.shape == (16, 16, 3)
    rc, _ = image.random_crop(img, (16, 16))
    assert rc.shape == (16, 16, 3)
    normed = image.color_normalize(cc.astype("float32"),
                                   nd.array([127.0, 127.0, 127.0]))
    assert normed.asnumpy().max() <= 128.5
    augs = image.CreateAugmenter((3, 24, 24), rand_mirror=True,
                                 mean=[0, 0, 0], std=[1, 1, 1])
    out = img
    for a in augs:
        out = a(out)
    assert out.shape == (24, 24, 3)


def test_imdecode_roundtrip(tmp_path):
    import io as _io

    from PIL import Image

    from mxnet_tpu import image

    arr = (np.random.rand(20, 20, 3) * 255).astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    out = image.imdecode(buf.getvalue())
    assert np.array_equal(out.asnumpy(), arr)


def test_amp_convert_model():
    from mxnet_tpu import amp

    amp.init()
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.BatchNorm(), nn.Dense(2))
    net.initialize()
    net(nd.ones((2, 4)))
    amp.convert_model(net)
    assert net[0].weight.data().dtype == np.dtype("bfloat16")
    # norm params stay fp32
    assert net[1].gamma.data().dtype == np.float32
    out = net(nd.ones((2, 4)))
    assert out.dtype == np.dtype("bfloat16")


def test_loss_scaler_dynamics():
    from mxnet_tpu import amp

    s = amp.LossScaler(init_scale=8.0, scale_factor=2.0, scale_window=3)
    assert float(s.scale(nd.array([2.0])).asnumpy()[0]) == 16.0
    # overflow halves and requests a skip
    assert s.update(overflow=True) is True
    assert s.loss_scale == 4.0
    # scale_window clean steps double it back
    for _ in range(3):
        assert s.update(overflow=False) is False
    assert s.loss_scale == 8.0
    assert s.has_overflow([nd.array([1.0, float("inf")])])
    assert not s.has_overflow([nd.array([1.0, 2.0])])
    g = s.unscale([nd.array([8.0])])[0]
    assert float(g.asnumpy()[0]) == 1.0


def test_scale_loss_trainer_integration():
    """fp16-style dynamic scaling: scaled loss backward, grads rescaled
    by the optimizer, overflow skips the update and shrinks the scale."""
    from mxnet_tpu import amp, autograd, gluon

    amp._target_dtype = "float16"  # force a real (non-1) scale
    try:
        net = nn.Dense(1, use_bias=False)
        net.initialize(mx.init.Constant(2.0))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 1.0})
        x = nd.ones((1, 1))
        with autograd.record():
            out = net(x)
            loss = out.sum()
            with amp.scale_loss(loss, tr) as scaled:
                pass
        scaled.backward()
        scale = tr._amp_loss_scaler.loss_scale
        assert scale > 1.0
        g = net.weight.grad().asnumpy()
        assert g[0, 0] == scale  # grad carries the loss scale
        w_before = net.weight.data().asnumpy().copy()
        tr.step(1)
        w_after = net.weight.data().asnumpy()
        # optimizer divided the scale back out: dw = lr * 1.0
        np.testing.assert_allclose(w_before - w_after, 1.0, rtol=1e-6)

        # now force an overflow: update must be skipped, scale halved
        with autograd.record():
            loss = (net(x) * float("inf")).sum()
            with amp.scale_loss(loss, tr) as scaled:
                pass
        scaled.backward()
        w_before = net.weight.data().asnumpy().copy()
        s_before = tr._amp_loss_scaler.loss_scale
        tr.step(1)
        assert np.array_equal(net.weight.data().asnumpy(), w_before)
        assert tr._amp_loss_scaler.loss_scale == s_before / 2.0
    finally:
        amp._target_dtype = "bfloat16"


def test_monitor_hooks():
    from mxnet_tpu.monitor import Monitor

    net = nn.HybridSequential()
    net.add(nn.Dense(4, activation="relu"), nn.Dense(2))
    net.initialize()
    mon = Monitor(interval=1).install(net)
    mon.tic()
    net(nd.ones((2, 3)))
    stats = mon.toc()
    assert len(stats) >= 2
    assert all(np.isfinite(v) for _, _, v in stats)


def test_resource_manager():
    """Ref: include/mxnet/resource.h — temp space + RNG resources."""
    from mxnet_tpu import resource

    r = resource.request(resource.ResourceRequest.kTempSpace)
    buf = r.get_space((16, 4), np.float32)
    buf[:] = 3.0
    assert buf.shape == (16, 4) and buf.dtype == np.float32
    assert (buf == 3.0).all()
    r.release()

    rr = resource.request(resource.ResourceRequest.kRandom)
    k = rr.get_key()
    assert k is not None
    pr = resource.request(resource.ResourceRequest.kParallelRandom)
    keys = pr.get_parallel_keys(4)
    assert len(keys) == 4
    import jax

    vals = [float(jax.random.uniform(k)) for k in keys]
    assert len(set(vals)) == 4  # independent streams

    import pytest as _pytest

    with _pytest.raises(mx.MXNetError):
        rr.get_space((2,))
    with _pytest.raises(mx.MXNetError):
        r.get_key()
    with _pytest.raises(mx.MXNetError):
        resource.request("bogus")


def test_runtime_features():
    """Ref: mx.runtime.Features — live capability probing."""
    f = mx.runtime.Features()
    assert f.is_enabled("CPU")
    assert "NATIVE_ENGINE" in f and "PALLAS" in f
    assert repr(f["CPU"]).startswith("[")
    with pytest.raises(Exception):
        f.is_enabled("WARP_DRIVE")
    assert len(mx.runtime.feature_list()) == len(f)


def test_library_plugin_load(tmp_path):
    """Ref: mx.library.load — plugin ops land on the nd front."""
    p = tmp_path / "plugops.py"
    p.write_text(
        "import jax.numpy as jnp\n"
        "from mxnet_tpu.ops.registry import register\n"
        "def _k_triple(a):\n"
        "    return 3 * a\n"
        "register('triple_test_op', _k_triple)\n")
    mx.library.load(str(p), verbose=False)
    out = nd.triple_test_op(nd.ones((3,)))
    assert np.allclose(out.asnumpy(), 3.0)
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        mx.library.load(str(tmp_path / "missing.py"))
    so = tmp_path / "x.so"
    so.write_bytes(b"\x7fELF")
    with pytest.raises(MXNetError, match="python plugin"):
        mx.library.load(str(so))


def test_generic_registry():
    """Ref: mx.registry register/create machinery."""

    class Base:
        pass

    reg = mx.registry.get_register_func(Base, "widget")
    alias = mx.registry.get_alias_func(Base, "widget")
    create = mx.registry.get_create_func(Base, "widget")

    @alias("frob")
    @reg
    class Foo(Base):
        def __init__(self, v=1):
            self.v = v

    assert create("foo", v=7).v == 7
    assert create("frob").v == 1
    inst = Foo(3)
    assert create(inst) is inst
    from mxnet_tpu.base import MXNetError

    with pytest.raises(MXNetError):
        create("nope")
    assert mx.attribute.AttrScope is mx.AttrScope


def test_progress_bar_and_rand_shapes():
    import contextlib
    import io as _io

    from mxnet_tpu.callback import BatchEndParam, ProgressBar

    pb = ProgressBar(total=4, length=10)
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        # Module.fit emits 0-based nbatch (enumerate)
        for i in range(4):
            pb(BatchEndParam(epoch=0, nbatch=i, eval_metric=None,
                             locals=None))
    out = buf.getvalue()
    assert "1/4" in out and "4/4" in out and "#" * 10 in out
    assert len(mx.test_utils.rand_shape_2d()) == 2
    assert len(mx.test_utils.rand_shape_3d()) == 3
    assert hasattr(mx.kvstore_server, "main")


def test_profiler_aggregate_stats_table():
    """set_config(aggregate_stats=True) must make dumps(format='table')
    return a per-op summary (VERDICT r2 weak #8: the accepted flag
    silently did nothing).  Ref: src/profiler/aggregate_stats.cc."""
    import pytest as _pytest

    from mxnet_tpu import profiler

    profiler.reset()
    profiler.set_config(aggregate_stats=False)
    with _pytest.raises(RuntimeError, match="aggregate"):
        profiler.dumps(format="table")
    profiler.set_config(profile_all=True, aggregate_stats=True, sync=True)
    profiler.start()
    x = nd.ones((16, 16))
    for _ in range(3):
        x = nd.relu(x)
    nd.dot(x, x).wait_to_read()
    profiler.stop()
    table = profiler.dumps(format="table")
    assert "Profile Statistics" in table and "Total Count" in table
    relu_rows = [ln for ln in table.splitlines() if "relu" in ln]
    assert relu_rows, table
    # count column shows the 3 relu calls aggregated into one row
    assert any(int(r.split()[1]) >= 3 for r in relu_rows), relu_rows
    # json path still works and reset clears
    json.loads(profiler.dumps())
    profiler.reset()
    profiler.set_config(aggregate_stats=False, profile_all=False,
                        sync=False)
    assert profiler.dumps(format="json")
