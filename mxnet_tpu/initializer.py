"""Weight initializers (ref: python/mxnet/initializer.py)."""
from __future__ import annotations

import math

import numpy as np

from .base import Registry

_registry = Registry("initializer")
register = _registry.register


class InitDesc(str):
    """Parameter name + attr hints handed to initializers
    (ref: mxnet.init.InitDesc).  Layout-dependent layers attach
    ``__init_fan__`` so fan-based initializers (Xavier/MSRAPrelu) stay
    correct for channel-last OHWI conv weights, whose shape alone is
    ambiguous (e.g. (256,3,3,256))."""

    def __new__(cls, name, attrs=None):
        s = super().__new__(cls, name)
        s.attrs = dict(attrs or {})
        return s


class Initializer:
    """Base initializer (ref: mx.init.Initializer)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr=None):
        # legacy call convention: init(name, arr)
        if arr is None:
            name, arr = "", name
        self.init_array(name if isinstance(name, str) else str(name), arr)

    def init_array(self, name, arr):
        if name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta"):
            self._init_zero(arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    def _init_zero(self, arr):
        arr[:] = 0.0

    def _init_one(self, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _rand(self, arr):
        from . import random as _random

        return _random

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


def _np_rng():
    from .random import np_rng

    return np_rng()


def _fill(arr, np_values):
    from .ndarray.ndarray import NDArray

    vals = np_values.astype(np.dtype(arr.dtype))
    if isinstance(arr, NDArray):
        arr[:] = vals
    else:
        arr[...] = vals


@register("zeros")
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


@register("ones")
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


@register()
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value


@register()
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        _fill(arr, _np_rng().uniform(-self.scale, self.scale, arr.shape))


@register()
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        _fill(arr, _np_rng().normal(0, self.sigma, arr.shape))


@register()
class TruncNorm(Initializer):
    """Truncated normal in [mean - 2*stdev, mean + 2*stdev]
    (ref: python/mxnet/initializer.py TruncNorm; the BERT init)."""

    def __init__(self, mean=0.0, stdev=0.01):
        super().__init__(mean=mean, stdev=stdev)
        self.mean = mean
        self.stdev = stdev

    def _init_weight(self, name, arr):
        lo, hi = -2.0, 2.0
        vals = _np_rng().normal(0, 1, arr.shape)
        bad = (vals < lo) | (vals > hi)
        while bad.any():  # resample the tails (truncation, not clipping)
            vals[bad] = _np_rng().normal(0, 1, int(bad.sum()))
            bad = (vals < lo) | (vals > hi)
        _fill(arr, self.mean + self.stdev * vals)


@register()
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1
        if self.rand_type == "uniform":
            tmp = _np_rng().uniform(-1, 1, (nout, nin))
        else:
            tmp = _np_rng().normal(0, 1, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        _fill(arr, self.scale * q.reshape(arr.shape))


@register()
class Xavier(Initializer):
    """Ref: mx.init.Xavier (magnitude/factor_type/rnd_type)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hints = getattr(name, "attrs", {})
        if "__init_fan__" in hints:
            # layout-aware layers supply exact fans (OHWI weights would
            # otherwise be misread as OI*k)
            fan_in, fan_out = hints["__init_fan__"]
        else:
            hw_scale = 1.0
            if len(shape) < 2:
                raise ValueError(
                    f"Xavier initializer needs >=2D weight, got {shape} "
                    f"for {name}")
            if len(shape) > 2:
                hw_scale = np.prod(shape[2:])
            fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0,
                  "in": fan_in, "out": fan_out}[self.factor_type]
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            _fill(arr, _np_rng().uniform(-scale, scale, shape))
        else:
            _fill(arr, _np_rng().normal(0, scale, shape))


@register()
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register()
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        shape = arr.shape
        weight = np.zeros(np.prod(shape), dtype="float32")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        _fill(arr, weight.reshape(shape))


@register()
class LSTMBias(Initializer):
    """Forget-gate bias = 1 (gate order i,f,g,o — see ops/rnn.py)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        arr[:] = 0.0
        n = arr.shape[0] // 4
        arr[n:2 * n] = self.forget_bias


@register()
class Mixed(Initializer):
    """Per-parameter-pattern dispatch (ref: mx.init.Mixed): each name
    is initialized by the FIRST regex in `patterns` that matches —
    order patterns specific-first, with '.*' as the catch-all."""

    def __init__(self, patterns, initializers):
        import re

        super().__init__(patterns=patterns)
        if len(patterns) != len(initializers):
            raise ValueError(
                "patterns and initializers must pair up, got "
                f"{len(patterns)} vs {len(initializers)}")
        self._map = [(re.compile(p), init)
                     for p, init in zip(patterns, initializers)]

    def init_array(self, name, arr):
        # dispatch on the FULL name (no bias/gamma convention layer:
        # the matched initializer owns the decision, as the ref does)
        for pat, init in self._map:
            if pat.match(name):
                init(name, arr)
                return
        raise ValueError(
            f"parameter {name!r} matched none of the Mixed patterns; "
            "add a '.*' catch-all as the last pattern")


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    return _registry.get(name)(**kwargs)


# aliases matching mx.init
zero = Zero
one = One
