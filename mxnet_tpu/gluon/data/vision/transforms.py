"""Vision transforms (ref: python/mxnet/gluon/data/vision/transforms.py).

Transforms are HybridBlocks operating on HWC uint8/float NDArrays
(MXNet convention) — ToTensor converts to CHW float32 in [0,1].
"""
from __future__ import annotations

import numpy as np

from ....ndarray import ndarray as _nd
from ....ndarray.ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn.basic_layers import Sequential, HybridSequential


class Compose(Sequential):
    """Ref: transforms.Compose."""

    def __init__(self, transforms):
        super().__init__()
        self.add(*transforms)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (ref: ToTensor)."""

    def hybrid_forward(self, F, x):
        if x.ndim == 4:
            return F.transpose(F.cast(x, dtype="float32"),
                               axes=(0, 3, 1, 2)) / 255.0
        return F.transpose(F.cast(x, dtype="float32"), axes=(2, 0, 1)) / 255.0


class Normalize(HybridBlock):
    """Channel-wise (x - mean)/std on CHW input (ref: Normalize)."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def hybrid_forward(self, F, x):
        mean = _nd.array(self._mean)
        std = _nd.array(self._std)
        return (x - mean) / std


class Resize(Block):
    """Resize HWC image (ref: Resize)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._keep = keep_ratio

    def forward(self, x):
        from PIL import Image

        arr = x.asnumpy().astype(np.uint8)
        squeeze = arr.shape[-1] == 1
        pil = Image.fromarray(arr[..., 0] if squeeze else arr)
        w, h = self._size
        if self._keep:
            scale = max(w / pil.size[0], h / pil.size[1])
            pil = pil.resize((int(round(pil.size[0] * scale)),
                              int(round(pil.size[1] * scale))))
        else:
            pil = pil.resize((w, h))
        out = np.asarray(pil)
        if squeeze:
            out = out[..., None]
        return _nd.array(out, dtype=np.uint8)


class CenterCrop(Block):
    def __init__(self, size):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)

    def forward(self, x):
        w, h = self._size
        ih, iw = x.shape[0], x.shape[1]
        y0, x0 = max((ih - h) // 2, 0), max((iw - w) // 2, 0)
        return x[y0:y0 + h, x0:x0 + w]


class RandomCrop(Block):
    """Random-position crop, optionally zero-padding first
    (ref: transforms.RandomCrop)."""

    def __init__(self, size, pad=None):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size,
                                                                   size)
        self._pad = pad

    def forward(self, x):
        if self._pad:
            p = self._pad
            x = _nd.array(np.pad(x.asnumpy(),
                                 ((p, p), (p, p), (0, 0))))
        w, h = self._size
        ih, iw = x.shape[0], x.shape[1]
        if ih < h or iw < w:
            from ....base import MXNetError

            raise MXNetError(
                f"RandomCrop: image ({ih}x{iw}) smaller than crop "
                f"({h}x{w}); use pad= or resize first")
        y0 = np.random.randint(0, ih - h + 1)
        x0 = np.random.randint(0, iw - w + 1)
        return x[y0:y0 + h, x0:x0 + w]


class RandomGray(Block):
    """Randomly convert to 3-channel gray (ref: transforms.RandomGray)."""

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, x):
        if np.random.rand() < self._p:
            from ....image.image import RandomGrayAug

            # keep the input dtype: the gray matmul promotes to float32,
            # and a stochastic dtype change breaks dtype-sensitive
            # consumers downstream
            return RandomGrayAug(1.0)(x).astype(x.dtype)
        return x


class RandomResizedCrop(Block):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size, size)
        self._scale = scale
        self._ratio = ratio

    def forward(self, x):
        from PIL import Image

        arr = x.asnumpy().astype(np.uint8)
        squeeze = arr.shape[-1] == 1
        pil = Image.fromarray(arr[..., 0] if squeeze else arr)
        iw, ih = pil.size
        area = iw * ih
        for _ in range(10):
            target = area * np.random.uniform(*self._scale)
            ar = np.random.uniform(*self._ratio)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if w <= iw and h <= ih:
                x0 = np.random.randint(0, iw - w + 1)
                y0 = np.random.randint(0, ih - h + 1)
                pil = pil.crop((x0, y0, x0 + w, y0 + h))
                break
        pil = pil.resize(self._size)
        out = np.asarray(pil)
        if squeeze:
            out = out[..., None]
        return _nd.array(out, dtype=np.uint8)


class RandomFlipLeftRight(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return x.flip(axis=1)
        return x


class RandomFlipTopBottom(Block):
    def forward(self, x):
        if np.random.rand() < 0.5:
            return x.flip(axis=0)
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._b = brightness

    def forward(self, x):
        f = 1.0 + np.random.uniform(-self._b, self._b)
        return (x.astype("float32") * f).clip(0, 255)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._c = contrast

    def forward(self, x):
        f = 1.0 + np.random.uniform(-self._c, self._c)
        xf = x.astype("float32")
        mean = xf.mean()
        return ((xf - mean) * f + mean).clip(0, 255)


class RandomSaturation(Block):
    _gray_w = np.array([0.299, 0.587, 0.114], np.float32)

    def __init__(self, saturation):
        super().__init__()
        self._s = saturation
        self._gw = _nd.array(self._gray_w)

    def forward(self, x):
        f = 1.0 + np.random.uniform(-self._s, self._s)
        xf = x.astype("float32")
        gray = (xf * self._gw).sum(axis=-1, keepdims=True)
        return (xf * f + gray * (1 - f)).clip(0, 255)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._h = hue

    # the reference's YIQ transform matrices (image_random-inl.h)
    _t_yiq = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], np.float32)
    _t_rgb = np.array([[1.0, 0.956, 0.621],
                       [1.0, -0.272, -0.647],
                       [1.0, -1.107, 1.705]], np.float32)

    def forward(self, x):
        theta = np.random.uniform(-self._h, self._h) * np.pi
        cs, sn = np.cos(theta), np.sin(theta)
        rot = np.array([[1, 0, 0], [0, cs, -sn], [0, sn, cs]],
                       np.float32)
        m = self._t_rgb @ rot @ self._t_yiq
        xf = x.astype("float32")
        return (xf.reshape((-1, 3)).dot(_nd.array(m.T))
                .reshape(xf.shape)).clip(0, 255)


class RandomColorJitter(Block):
    """brightness -> contrast -> saturation -> hue, each optional
    (ref: transforms.RandomColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._stages = []
        if brightness:
            self._stages.append(RandomBrightness(brightness))
        if contrast:
            self._stages.append(RandomContrast(contrast))
        if saturation:
            self._stages.append(RandomSaturation(saturation))
        if hue:
            self._stages.append(RandomHue(hue))

    def forward(self, x):
        for s in self._stages:
            x = s(x)
        return x


class RandomLighting(Block):
    """AlexNet-style PCA noise (ref: transforms.RandomLighting)."""

    _eigval = np.array([55.46, 4.794, 1.148], np.float32)
    _eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                        [-0.5808, -0.0045, -0.8140],
                        [-0.5836, -0.6948, 0.4203]], np.float32)

    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        a = np.random.normal(0, self._alpha, 3).astype(np.float32)
        noise = (self._eigvec * a * self._eigval).sum(axis=1)
        return (x.astype("float32") + _nd.array(noise)).clip(0, 255)


class CropResize(Block):
    """Crop (x, y, w, h) then optionally resize (ref:
    transforms.CropResize)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._box = (x, y, width, height)
        self._size = size
        self._interp = interpolation

    def forward(self, data):
        from .... import image as _image

        x, y, w, h = self._box
        s = None
        if self._size:
            s = self._size if isinstance(self._size, (tuple, list)) \
                else (self._size, self._size)
        return _image.fixed_crop(data, x, y, w, h, size=s,
                                 interp=self._interp)
