"""Monitor: tap layer outputs/params for debugging
(ref: python/mxnet/monitor.py — executor output callback; here Gluon
forward hooks)."""
from __future__ import annotations

import re

import numpy as np

from .ndarray.ndarray import NDArray


def _default_stat(x):
    return np.abs(x).mean()


class Monitor:
    """Ref: mx.mon.Monitor(interval, stat_func, pattern)."""

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval = interval
        self.stat_func = stat_func or _default_stat
        self.pattern = re.compile(pattern)
        self.sort = sort
        self.step = 0
        self.activated = False
        self.queue = []
        self._handles = []

    def install(self, block):
        """Attach to a Gluon block tree (the executor-callback analogue)."""

        def make_hook(name):
            def hook(blk, inputs, output):
                if not self.activated:
                    return
                outs = output if isinstance(output, (list, tuple)) \
                    else [output]
                for i, o in enumerate(outs):
                    if isinstance(o, NDArray) and self.pattern.match(name):
                        self.queue.append(
                            (self.step, f"{name}_output{i}",
                             self.stat_func(o.asnumpy())))

            return hook

        def walk(blk, prefix):
            for cname, child in blk._children.items():
                full = f"{prefix}{cname}"
                self._handles.append(
                    child.register_forward_hook(make_hook(full)))
                walk(child, full + ".")

        walk(block, "")
        return self

    def tic(self):
        if self.step % self.interval == 0:
            self.activated = True
            self.queue = []

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = sorted(self.queue) if self.sort else list(self.queue)
        self.step += 1
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            print(f"Batch {step:>7d} {name:<40s} {value:g}")
