#!/usr/bin/env python
"""Pack an image folder or .lst file into RecordIO (ref: tools/im2rec.py).

Usage:
  python tools/im2rec.py PREFIX ROOT [--list] [--recursive]
  python tools/im2rec.py PREFIX ROOT --num-thread 8 --quality 95

Two phases like the reference: `--list` generates PREFIX.lst
(idx\\tlabel\\trelpath); without it, packs PREFIX.lst into PREFIX.rec +
PREFIX.idx (JPEG-encoded, readable by ImageRecordIter incl. the native
C++ pipeline).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.io import recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def make_list(prefix, root, recursive=False, train_ratio=1.0):
    items = []
    if recursive:
        label = 0
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            for fn in sorted(os.listdir(path)):
                if fn.lower().endswith(EXTS):
                    items.append((os.path.join(folder, fn), label))
            label += 1
    else:
        for fn in sorted(os.listdir(root)):
            if fn.lower().endswith(EXTS):
                items.append((fn, 0))
    with open(prefix + ".lst", "w") as f:
        for i, (rel, label) in enumerate(items):
            f.write(f"{i}\t{label}\t{rel}\n")
    print(f"wrote {len(items)} entries to {prefix}.lst")


def pack(prefix, root, quality=95, resize=0, color=1, pack_label=False,
         native=False):
    import numpy as np
    from PIL import Image

    if native:
        # record/index writing through src/recordio.cc (the im2rec.cc
        # role); JPEG encode stays in Python — the bytes are identical
        rec = recordio.NativeIndexedRecordIO(prefix + ".idx",
                                             prefix + ".rec", "w")
    else:
        rec = recordio.MXIndexedRecordIO(prefix + ".idx",
                                         prefix + ".rec", "w")
    n = 0
    with open(prefix + ".lst") as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx, rel = int(parts[0]), parts[-1]
            if pack_label:
                # full float label vector (detection et al.; ref:
                # im2rec.py --pack-label)
                label = np.array([float(v) for v in parts[1:-1]],
                                 np.float32)
            else:
                label = float(parts[1])
            img = Image.open(os.path.join(root, rel))
            img = img.convert("RGB" if color else "L")
            if resize:
                short = min(img.size)
                scale = resize / short
                img = img.resize((int(img.size[0] * scale),
                                  int(img.size[1] * scale)))
            rec.write_idx(idx, recordio.pack_img(
                recordio.IRHeader(0, label, idx, 0), np.asarray(img),
                quality=quality, img_fmt=".jpg"))
            n += 1
    rec.close()
    print(f"packed {n} images into {prefix}.rec")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prefix")
    ap.add_argument("root")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--recursive", action="store_true")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--resize", type=int, default=0)
    ap.add_argument("--color", type=int, default=1)
    ap.add_argument("--pack-label", action="store_true",
                    help="pack every .lst field between idx and path as "
                         "a float label vector (detection labels)")
    ap.add_argument("--native", action="store_true",
                    help="write records through the native C++ recordio "
                         "writer (ref: tools/im2rec.cc)")
    args = ap.parse_args()
    if args.list:
        make_list(args.prefix, args.root, args.recursive)
    else:
        if not os.path.exists(args.prefix + ".lst"):
            make_list(args.prefix, args.root, recursive=True)
        pack(args.prefix, args.root, args.quality, args.resize, args.color,
             pack_label=args.pack_label, native=args.native)


if __name__ == "__main__":
    main()
