"""PS transport reliability: resend, dedup, heartbeat failure detection.

Ref: ps-lite Van resend (PS_RESEND) + Postoffice heartbeats — the
reference's thin failure-detection tier (SURVEY §5 "failure
detection/elastic recovery").
"""
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.parallel.ps import PSClient, PSServer


def _start_server():
    srv = PSServer(0)  # ephemeral port
    srv.start()
    return srv


def test_push_dedup_by_worker_seq():
    srv = _start_server()
    try:
        srv._handle(("init", "w", np.zeros(3, np.float32)))
        g = np.ones(3, np.float32)
        assert srv._handle(("push", "w", g, 7, 1)) == ("ok",)
        # resend of the same (worker, seq): acknowledged, NOT re-applied
        assert srv._handle(("push", "w", g, 7, 1)) == ("ok", "dup")
        np.testing.assert_array_equal(
            srv._handle(("pull", "w"))[1], np.ones(3, np.float32))
        # next seq applies
        assert srv._handle(("push", "w", g, 7, 2)) == ("ok",)
        np.testing.assert_array_equal(
            srv._handle(("pull", "w"))[1], 2 * np.ones(3, np.float32))
        # other workers have independent seq spaces
        assert srv._handle(("push", "w", g, 8, 1)) == ("ok",)
        np.testing.assert_array_equal(
            srv._handle(("pull", "w"))[1], 3 * np.ones(3, np.float32))
    finally:
        srv.stop()


def test_client_reconnects_after_server_restart():
    srv = _start_server()
    port = srv.port
    cli = PSClient([("127.0.0.1", port)], timeout=5, retries=4,
                   worker_id=1)
    try:
        cli.init("k", np.arange(4, dtype=np.float32))
        assert cli.pull("k")[2] == 2.0
        # kill the server under the client, then bring a fresh one up on
        # the same port — the client must resend on a new connection
        srv.stop()
        time.sleep(0.1)
        srv = PSServer(port).start()
        srv._handle(("init", "k", np.arange(4, dtype=np.float32) * 10))
        out = cli.pull("k")
        assert out[2] == 20.0
        # pushes survive the retry path without double-apply
        cli.push("k", np.ones(4, np.float32))
        np.testing.assert_array_equal(
            cli.pull("k"), np.arange(4, dtype=np.float32) * 10 + 1)
    finally:
        cli.close()
        srv.stop()


def test_heartbeat_marks_dead_server():
    srv = _start_server()
    deaths = []
    cli = PSClient([("127.0.0.1", srv.port)], timeout=2, retries=0,
                   worker_id=2, heartbeat_interval=0.05, dead_after=2,
                   on_server_death=lambda i, ep, why: deaths.append(
                       (i, ep, why)))
    try:
        cli.init("k", np.zeros(2, np.float32))
        assert cli.alive() == [("127.0.0.1", srv.port)]
        srv.stop()
        deadline = time.time() + 5
        while cli.alive() and time.time() < deadline:
            time.sleep(0.05)
        assert cli.alive() == []
        assert deaths and deaths[0][0] == 0
        # subsequent calls fail FAST with the failure cause
        t0 = time.time()
        with pytest.raises(mx.MXNetError, match="dead"):
            cli.pull("k")
        assert time.time() - t0 < 1.0
    finally:
        cli.close()


def test_unreachable_server_raises_diagnosable_error():
    srv = _start_server()
    cli = PSClient([("127.0.0.1", srv.port)], timeout=2, retries=1,
                   worker_id=3)
    srv.stop()
    time.sleep(0.1)
    with pytest.raises(mx.MXNetError, match="unreachable|dead"):
        for _ in range(3):  # first calls may drain buffered replies
            cli.pull("k")
    cli.close()
