"""Health-monitor gate for `make verify` (see docs/observability.md,
"Health monitor").

A supervised, pipeline-fed training run under an armed HealthMonitor
must produce decision-grade health facts:

1. goodput DEBITS injected recovery time: a transient fault forces a
   supervisor restart, and the window's lost_ms/goodput reflect it;
2. MFU is reported for the whole-step path (FLOPs from the compiled
   executable's jax cost analysis, not a guess);
3. a deliberately input-starved phase fires the input_starvation SLO
   rule, `/healthz` flips to `degraded` while it fires and back to
   `ok` after recovery;
4. an injected dist.allreduce DELAY fault on one virtual rank is named
   — rank AND collective phase — within K ticks;
5. `/metrics` scrapes of `mxtpu_health_*` agree with
   `profiler.sections()["health"]`;
6. the armed monitor introduces ZERO post-warmup compiles, and the
   disarmed hook is the module no-op at ~tracer cost.

Runs on the CPU backend so the gate is deterministic and fast anywhere.
"""
import json
import os
import shutil
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import checkpoint, gluon, pipeline  # noqa: E402
from mxnet_tpu import profiler, resilience, telemetry  # noqa: E402
from mxnet_tpu.gluon import nn  # noqa: E402
from mxnet_tpu.gluon import trainer as trainer_mod  # noqa: E402
from mxnet_tpu.telemetry import health  # noqa: E402
from mxnet_tpu.telemetry.health import (HealthMonitor,  # noqa: E402
                                        SLORule)

FEAT, BS, N = 4, 4, 32
K_TICKS = 2


def build_model(whole_step=False, kvstore=None):
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, in_units=FEAT, activation="relu"),
            nn.Dense(1, in_units=16))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    kwargs = {}
    if kvstore is not None:
        kwargs = dict(kvstore=kvstore, update_on_kvstore=False)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05},
                            whole_step=whole_step, **kwargs)
    return net, trainer


def loss_fn(out, y):
    return (out - y.reshape((-1, 1))) ** 2


def make_data():
    rng = np.random.RandomState(0)
    return [(rng.rand(FEAT).astype(np.float32), np.float32(i % 2))
            for i in range(N)]


def get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read().decode()) if path.startswith(
            "/healthz") else r.read().decode()


def eager_steps(net, trainer, n):
    from mxnet_tpu import autograd

    x = mx.nd.array(np.random.rand(BS, FEAT).astype(np.float32))
    y = mx.nd.array(np.random.rand(BS).astype(np.float32))
    for _ in range(n):
        with autograd.record():
            loss = ((net(x) - y.reshape((-1, 1))) ** 2).sum()
        loss.backward()
        trainer.step(BS)


def main():
    # -- 6a: disarmed identity + overhead budget (before anything arms)
    assert health.scope_end is health._noop
    fire = health.scope_end
    t0 = time.perf_counter()
    for _ in range(200_000):
        fire("trainer.step", "trainer", 0.0, 1.0)
    disarmed = time.perf_counter() - t0
    assert disarmed < 2.0, \
        f"disarmed health hook cost {disarmed:.3f}s / 200k fires"
    assert health.health_stats() is None, \
        "health section must be absent before any monitor arms"

    srv = telemetry.start_metrics_server(port=0)
    mon = HealthMonitor(
        tick_sec=0, straggler_ratio=1.5, straggler_ticks=K_TICKS,
        rules=[SLORule("input_starvation", "input_starvation",
                       above=0.4)],
        flight_on_breach=False).arm()

    # -- 1+2: supervised whole-step run with an injected transient ----------
    ckdir = tempfile.mkdtemp(prefix="health-smoke-")
    try:
        plan = resilience.FaultPlan([
            {"site": "train.step", "action": "raise", "on_hit": 3},
        ], seed=0)
        resilience.install_plan(plan)
        try:
            mgr = checkpoint.CheckpointManager(ckdir, keep_n=2)
            sup = resilience.Supervisor(
                mgr, on_preemption="resume", max_restarts=3,
                retry=resilience.RetryPolicy(max_retries=3,
                                             base_delay=0.05))
            data = make_data()

            def train(ctx):
                net, trainer = build_model(whole_step=True)
                pipe = pipeline.Pipeline(data).batch(
                    BS, last_batch="discard")
                start = 0
                if ctx.manager.latest() is not None:
                    meta = ctx.manager.restore(
                        params=net, trainer=trainer, pipeline=pipe)
                    start = meta["step"] + 1
                step = start
                for x, y in pipe:
                    trainer.whole_step(net, loss_fn, x, y)
                    ctx.step_done(step, save=dict(
                        params=net, trainer=trainer, pipeline=pipe,
                        sync=True))
                    step += 1
                return step

            mon.tick()                       # open a fresh window
            steps_run = sup.run(train)
        finally:
            resilience.clear_plan()

        fired = [(f["site"], f["action"]) for f in plan.fired()]
        assert ("train.step", "raise") in fired, fired
        w = mon.tick()
        res = json.loads(profiler.dumps())["resilience"]
        assert res["retries"].get("transient") == 1, res
        assert w["steps"] >= steps_run, w["steps"]
        # goodput debits the injected restart: the booked recovery
        # time shows in lost_ms and eats the productive fraction
        assert w["lost_ms"] >= 40.0, w["lost_ms"]
        assert w["goodput"] is not None and w["goodput"] < 1.0, w
        # MFU for the whole-step path, from the executable's REAL cost
        assert w["flops_per_step"] > 0, w
        assert w["flops_source"] == "cost_analysis", w["flops_source"]
        assert w["mfu"] is not None and w["mfu"] > 0, w
        goodput, mfu = w["goodput"], w["mfu"]
        lost_ms = w["lost_ms"]
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)

    # -- 6b: the armed monitor introduces zero post-warmup compiles ---------
    net, trainer = build_model(whole_step=True)
    x = mx.nd.array(np.random.rand(BS, FEAT).astype(np.float32))
    y = mx.nd.array(np.random.rand(BS).astype(np.float32))
    for _ in range(4):                        # warmup (compiles here)
        trainer.whole_step(net, loss_fn, x, y)
    before = trainer_mod.trainer_step_stats()["whole_step_compiles"]
    for _ in range(15):                       # monitored steady state
        trainer.whole_step(net, loss_fn, x, y)
        mon.tick()
    after = trainer_mod.trainer_step_stats()["whole_step_compiles"]
    assert after == before, \
        f"monitored steady steps compiled: {before} -> {after}"

    # -- 3: input-starved phase fires the rule, /healthz flips --------------
    hz = get(srv.port, "/healthz")
    assert hz["status"] == "ok", hz
    net2, trainer2 = build_model()

    def slow_fetch(sample):
        time.sleep(0.01)                      # remote-storage latency
        return sample

    pipe = pipeline.Pipeline(make_data()).map(
        slow_fetch, inflight=1).batch(BS, last_batch="discard")
    from mxnet_tpu import autograd

    for x2, y2 in pipe:
        with autograd.record():
            loss = ((net2(x2) - y2.reshape((-1, 1))) ** 2).sum()
        loss.backward()
        trainer2.step(BS)
    w = mon.tick()
    assert w["input_starvation"] is not None and \
        w["input_starvation"] > 0.4, w["input_starvation"]
    assert "input_starvation" in w["firing"], w["firing"]
    starvation = w["input_starvation"]
    hz = get(srv.port, "/healthz")
    assert hz["status"] == "degraded", hz
    assert "input_starvation" in hz["rules"], hz
    # recovery: a fast, compute-bound window clears the rule
    eager_steps(net2, trainer2, 6)
    w = mon.tick()
    assert w["status"] == "ok", w["firing"]
    hz = get(srv.port, "/healthz")
    assert hz["status"] == "ok", hz

    # -- 4: injected straggler named (rank + phase) within K ticks ----------
    n_ranks, straggler = 4, 2
    rank_nets = [build_model(kvstore="dist_sync")
                 for _ in range(n_ranks)]
    totals = [{} for _ in range(n_ranks)]
    windows = []
    for _w in range(K_TICKS + 1):
        for r in range(n_ranks):
            netr, trainerr = rank_nets[r]
            before_h = dict(profiler.sections()["health"])
            if r == straggler:
                resilience.install_plan(resilience.FaultPlan([
                    {"site": "dist.allreduce", "action": "delay",
                     "delay_s": 0.03, "times": None}], seed=0))
            try:
                eager_steps(netr, trainerr, 2)
            finally:
                if r == straggler:
                    resilience.clear_plan()
            after_h = profiler.sections()["health"]
            for k, v in after_h.items():
                if isinstance(v, (int, float)):
                    totals[r][k] = totals[r].get(k, 0) + max(
                        v - before_h.get(k, 0), 0)
        windows.append([{"health": dict(t), "dataPipeline": {}}
                        for t in totals])
    feed = {"i": 0}
    mon._aggregate_fn = lambda: {
        "world_size": n_ranks, "rank": 0,
        "ranks": windows[min(feed["i"], len(windows) - 1)]}
    named_at = None
    for i in range(len(windows)):
        feed["i"] = i
        w = mon.tick()
        if w["stragglers"]:
            named_at = i + 1
            break
    mon._aggregate_fn = None
    assert named_at is not None and named_at <= K_TICKS + 1, \
        f"straggler not named within K={K_TICKS} ticks"
    s = w["stragglers"][0]
    assert s["rank"] == straggler, s
    assert s["phase"] == "collective", s
    state, names = mon.status()
    assert state == "degraded" and f"rank {straggler}" in names[-1]
    mon.tick()                                # pool data stops: clears

    # -- 5: scrape-vs-dumps agreement for mxtpu_health_* --------------------
    scrape = get(srv.port, "/metrics")
    sec = profiler.sections()["health"]
    seen = 0
    scraped = {}
    for line in scrape.splitlines():
        if line.startswith("mxtpu_health_") and " " in line:
            name, val = line.rsplit(" ", 1)
            scraped[name] = float(val)
    for key, val in sec.items():
        name = "mxtpu_health_" + "".join(
            "_" + c.lower() if c.isupper() else c for c in key)
        assert name in scraped, f"{name} missing from the scrape"
        assert abs(scraped[name] - float(val)) < 1e-6, \
            f"{name}: scrape {scraped[name]} != dumps {val}"
        seen += 1
    assert seen >= 15, f"only {seen} health gauges compared"

    mon.disarm()
    telemetry.stop_metrics_server()
    assert health.scope_end is health._noop
    alerts = sec["alerts"]

    print(f"HEALTH_SMOKE_OK steps={sec['steps']} "
          f"goodput={goodput:.3f} lost_ms={lost_ms:.0f} "
          f"mfu={mfu:.2e} flops_per_step={sec['flops_per_step']:.0f} "
          f"starvation={starvation:.2f} alerts={alerts} "
          f"straggler=rank{s['rank']}/{s['phase']}@{s['ratio']}x "
          f"named_in={named_at}_ticks "
          f"health_gauges_scraped={seen} "
          f"post_warmup_compiles=0 "
          f"disarmed_overhead_ns={disarmed / 200_000 * 1e9:.0f}")


if __name__ == "__main__":
    main()
