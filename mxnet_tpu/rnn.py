"""Legacy mx.rnn module (ref: python/mxnet/rnn/ — io.py, rnn_cell.py).

The reference's symbol-level RNN cells are subsumed by the gluon cells
(one registry, see gluon/rnn/) which are re-exported here under their
legacy names; what this module adds is the bucketed data path used with
``BucketingModule`` — the reference's sequence-length-scaling mechanism
(SURVEY §5: one executor per bucket; here one compiled XLA program per
bucket, same idea).
"""
from __future__ import annotations

import random as _pyrandom

import numpy as np

from .base import MXNetError
from .io.io import DataBatch, DataDesc, DataIter

# legacy cell names (ref: mx.rnn.LSTMCell etc.)
from .gluon.rnn import (RNNCell, LSTMCell, GRUCell,  # noqa: F401
                        SequentialRNNCell, DropoutCell, ResidualCell,
                        ModifierCell, ZoneoutCell)


class BucketSentenceIter(DataIter):
    """Bucketed iterator over variable-length id sequences
    (ref: python/mxnet/rnn/io.py BucketSentenceIter).

    Sentences are assigned to the smallest bucket that fits, padded to
    the bucket length, and batches are drawn bucket-by-bucket; each
    DataBatch carries ``bucket_key`` + per-bucket provide_data/label so
    BucketingModule (or the shape-bucketed executable cache) compiles
    one program per bucket."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size)
        if not buckets:
            lens = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(lens)
                       if n >= batch_size]
            if not buckets:
                buckets = [max(len(s) for s in sentences)]
        buckets = sorted(buckets)
        self.buckets = buckets
        self.data_name, self.label_name = data_name, label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.layout = layout
        if layout not in ("NT", "TN"):
            raise MXNetError(f"unsupported layout {layout!r}")

        self.data = [[] for _ in buckets]
        ndiscard = 0
        for s in sentences:
            buck = np.searchsorted(buckets, len(s))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(s)] = s
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype) for x in self.data]
        if ndiscard:
            import logging

            logging.warning("discarded %d sentences longer than the "
                            "largest bucket", ndiscard)
        self.major_axis = layout.find("N")
        self.reset()

    @property
    def provide_data(self):
        # largest bucket (ref: default_bucket_key binds the biggest shape)
        return [DataDesc(self.data_name, self._shape(max(self.buckets)),
                         layout=self.layout)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, self._shape(max(self.buckets)),
                         layout=self.layout)]

    @property
    def default_bucket_key(self):
        return max(self.buckets)

    def _shape(self, seq_len):
        return ((self.batch_size, seq_len) if self.major_axis == 0
                else (seq_len, self.batch_size))

    def reset(self):
        self.curr_idx = 0
        self._plan = []
        for i, buck in enumerate(self.data):
            if len(buck) == 0:
                continue
            idx = list(range(len(buck)))
            _pyrandom.shuffle(idx)
            for start in range(0, len(idx) - self.batch_size + 1,
                               self.batch_size):
                self._plan.append((i, idx[start:start + self.batch_size]))
        _pyrandom.shuffle(self._plan)

    def next(self):
        from .ndarray.ndarray import array

        if self.curr_idx >= len(self._plan):
            raise StopIteration
        bucket_i, rows = self._plan[self.curr_idx]
        self.curr_idx += 1
        buck = self.data[bucket_i][rows]
        # label = data shifted left by one step (next-token prediction)
        label = np.full_like(buck, self.invalid_label)
        label[:, :-1] = buck[:, 1:]
        if self.major_axis == 1:
            buck, label = buck.T, label.T
        key = self.buckets[bucket_i]
        return DataBatch(
            data=[array(buck)], label=[array(label)], pad=0,
            bucket_key=key,
            provide_data=[DataDesc(self.data_name, self._shape(key),
                                   layout=self.layout)],
            provide_label=[DataDesc(self.label_name, self._shape(key),
                                    layout=self.layout)])
