"""mxnet_tpu.serve.decode — continuous batching over a slot arena.

Covers the decode tier's contract: continuously-batched decode is
bit-identical to sequential whole-batch decode of the same prompts
(slot reuse and co-resident churn never leak across rows); a warmed
server takes a staggered mixed stream with ZERO new XLA compilations
and exact dispatch accounting (one per token step, one per prefill
group, one per admission); deadlines expire mid-decode and free the
slot immediately; drain leaves zero live slots; hot reload swaps
weights mid-stream without a recompile; and the concurrent stress run
holds under the runtime lock-order checker.
"""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import _imperative, checkpoint, serve

VOCAB = 64


def _make_model(seed=3, vocab=VOCAB, embed=16):
    mx.random.seed(seed)
    model = serve.TinyDecoder(vocab=vocab, embed=embed)
    model.initialize(mx.init.Xavier())
    return model


def _spec(batches=(1, 2, 4), lengths=(4, 8)):
    return serve.BucketSpec(batch_sizes=batches, example_shape=(None,),
                            lengths=lengths, dtype="int32")


def _prompts(n, rng, max_len=8):
    return [rng.randint(0, VOCAB, size=int(rng.randint(2, max_len + 1)))
            .astype(np.int32) for _ in range(n)]


def _server(model, **kwargs):
    kwargs.setdefault("max_slots", 4)
    kwargs.setdefault("max_len", 32)
    return serve.DecodeServer(model, kwargs.pop("spec", _spec()), **kwargs)


# ---------------------------------------------------------------------------
# parity: the acceptance gate


def test_parity_continuous_vs_whole_batch_decode():
    """Continuously-batched outputs are bit-identical to sequential
    whole-batch decode of the same prompts: staggered admission, slot
    reuse, and different co-residents never change any sequence."""
    model = _make_model()
    rng = np.random.RandomState(1)
    prompts = _prompts(14, rng)
    budgets = [int(rng.randint(2, 12)) for _ in prompts]

    def run(admission, stagger=0.0):
        srv = _server(model, admission=admission)
        srv.start()
        handles = []
        for p, m in zip(prompts, budgets):
            handles.append(srv.submit(p, max_new_tokens=m))
            if stagger:
                time.sleep(stagger)
        seqs = [h.result(timeout=120) for h in handles]
        srv.drain()
        return seqs, srv.stats()

    cont, s_cont = run("continuous", stagger=0.002)
    whole, s_whole = run("batch")
    for a, b in zip(cont, whole):
        np.testing.assert_array_equal(a, b)
    assert all(len(seq) == m for seq, m in zip(cont, budgets))
    # (the scheduling win itself — fewer step dispatches per token —
    # is asserted under saturated load in
    # test_staggered_admission_zero_compiles_exact_dispatches and
    # A/B-measured by `bench.py serve_decode`; at this trickle rate the
    # arena runs far below capacity and step counts are arrival-bound)
    assert s_cont["graph"]["post_warmup_compiles"] == 0
    assert s_whole["graph"]["post_warmup_compiles"] == 0


# ---------------------------------------------------------------------------
# closed compile surface + honest dispatch accounting


def test_staggered_admission_zero_compiles_exact_dispatches():
    model = _make_model()
    srv = _server(model, max_queue=128)
    srv.start()
    execs_before = _imperative.compiled_executable_count()
    d0 = _imperative.device_dispatch_count()
    rng = np.random.RandomState(2)
    handles = []
    for i, p in enumerate(_prompts(24, rng)):
        handles.append(srv.submit(p, max_new_tokens=int(rng.randint(1, 9))))
        if i % 4 == 0:
            time.sleep(0.002)
    for h in handles:
        h.result(timeout=120)
    srv.drain()
    d1 = _imperative.device_dispatch_count()
    s = srv.stats()
    assert s["served"] == 24
    assert s["graph"]["post_warmup_compiles"] == 0
    assert _imperative.compiled_executable_count() == execs_before
    # the honest counter: one dispatch per token step, one per fused
    # prefill+write admission group — nothing eager leaks into the loop
    assert d1 - d0 == s["decode_steps"] + s["batches"]
    assert s["admitted"] == 24
    # iteration-level scheduling: many tokens ride each step dispatch
    assert s["tokens"] > s["decode_steps"]


def test_single_sequence_one_dispatch_per_token():
    """Steady state with one live sequence: exactly 1 device dispatch
    per generated token (after the admission prefill+write)."""
    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(3)
    h = srv.submit(_prompts(1, rng)[0], max_new_tokens=9)
    seq = h.result(timeout=120)
    srv.drain()
    s = srv.stats()
    assert len(seq) == 9
    # first token comes from prefill; each later token is ONE step
    assert s["decode_steps"] == 8
    assert s["batches"] == 1
    assert s["graph"]["post_warmup_compiles"] == 0


def test_eos_terminates_early_and_frees_slot():
    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(12)
    prompt = _prompts(1, rng)[0]
    ref = srv.generate(prompt, max_new_tokens=10, timeout=120)
    srv.drain()
    # pick a token the greedy sequence provably emits; a server with
    # that eos_id must stop at its first occurrence
    eos = int(ref[3])
    first_idx = int(np.argmax(ref == eos))
    srv2 = _server(model, eos_id=eos)
    srv2.start()
    seq = srv2.generate(prompt, max_new_tokens=10, timeout=120)
    srv2.drain()
    np.testing.assert_array_equal(seq, ref[:first_idx + 1])
    s = srv2.stats()
    assert s["served"] == 1 and s["slots"]["live"] == 0


# ---------------------------------------------------------------------------
# streaming


def test_stream_iterator_matches_future():
    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(4)
    h = srv.submit(_prompts(1, rng)[0], max_new_tokens=7)
    streamed = list(h)
    assert streamed == list(h.result(timeout=120))
    assert len(streamed) == 7
    # a second pass over the handle terminates (sentinel stays put)
    assert list(h) == []
    srv.drain()


# ---------------------------------------------------------------------------
# deadlines / cancellation free slots mid-decode


def test_mid_decode_deadline_frees_slot():
    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(5)
    # a generous budget that cannot finish inside the deadline: the
    # deadline check at a token boundary must fail it and free the slot
    doomed = srv.submit(_prompts(1, rng)[0], max_new_tokens=24,
                        deadline_ms=1)
    time.sleep(0.05)
    with pytest.raises(serve.DeadlineExceededError):
        doomed.result(timeout=120)
    # the freed slot keeps serving new traffic
    ok = srv.submit(_prompts(1, rng)[0], max_new_tokens=4)
    assert len(ok.result(timeout=120)) == 4
    srv.drain()
    s = srv.stats()
    assert s["expired_deadline"] == 1 and s["served"] == 1
    assert s["slots"]["live"] == 0
    assert s["submitted"] == s["served"] + s["expired_deadline"]
    # the stream carries the same terminal error
    with pytest.raises(serve.DeadlineExceededError):
        list(doomed)


def test_cancel_frees_slot_and_voids_queued():
    model = _make_model()
    srv = _server(model, max_slots=1, max_len=2048)
    srv.start()
    rng = np.random.RandomState(6)
    live = srv.submit(_prompts(1, rng)[0], max_new_tokens=2000)
    queued = srv.submit(_prompts(1, rng)[0], max_new_tokens=2000)
    time.sleep(0.02)          # let the first admit and start decoding
    live.cancel()
    queued.cancel()
    srv.drain()
    s = srv.stats()
    assert s["cancelled"] == 2 and s["served"] == 0
    assert s["slots"]["live"] == 0 and s["queue_depth"] == 0


# ---------------------------------------------------------------------------
# drain / restart


def test_drain_leaves_zero_live_slots_and_restarts_warm():
    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(7)
    handles = [srv.submit(p, max_new_tokens=5)
               for p in _prompts(10, rng)]
    srv.drain()
    assert all(h.future.done() for h in handles)
    s = srv.stats()
    assert s["served"] == s["submitted"] == 10
    assert s["queue_depth"] == 0 and s["slots"]["live"] == 0
    with pytest.raises(serve.ServerClosedError):
        srv.submit(_prompts(1, rng)[0])
    # restart reuses every warmed executable: zero new compiles
    srv.start()
    assert len(srv.generate(_prompts(1, rng)[0], max_new_tokens=3,
                            timeout=120)) == 3
    srv.drain()
    assert srv.stats()["graph"]["post_warmup_compiles"] == 0


def test_overload_rejection_and_backpressure():
    model = _make_model()
    srv = _server(model, max_slots=1, max_queue=2)
    srv.start()
    rng = np.random.RandomState(8)
    handles, rejected = [], 0
    for p in _prompts(12, rng):
        try:
            handles.append(srv.submit(p, max_new_tokens=12))
        except serve.ServerOverloadedError:
            rejected += 1
    assert rejected > 0       # the bounded admission queue sheds load
    for h in handles:
        h.result(timeout=300)
    srv.drain()
    s = srv.stats()
    assert s["rejected_overload"] == rejected
    assert s["served"] == s["submitted"] == 12 - rejected


# ---------------------------------------------------------------------------
# hot reload mid-stream


def test_hot_reload_mid_stream(tmp_path):
    trained = _make_model(seed=11)
    mgr = checkpoint.CheckpointManager(str(tmp_path))
    mgr.save(7, params=trained, sync=True)
    mgr.wait_until_finished()

    serving = _make_model(seed=99)    # same arch, different weights
    srv = _server(serving, checkpoint=str(tmp_path))
    srv.start()
    rng = np.random.RandomState(9)
    prompt = _prompts(1, rng)[0]
    before = srv.generate(prompt, max_new_tokens=6, timeout=120)
    # reload between token boundaries of a LIVE stream: the sequence
    # finishes (on swapped weights), nothing drops, nothing recompiles
    mid = srv.submit(prompt, max_new_tokens=20)
    meta = srv.reload_weights()
    assert len(mid.result(timeout=120)) == 20
    after = srv.generate(prompt, max_new_tokens=6, timeout=120)
    srv.drain()
    assert meta["step"] == 7
    s = srv.stats()
    assert s["reloads"] == 1
    assert s["graph"]["post_warmup_compiles"] == 0
    # post-reload output equals a server built on the trained weights
    ref_srv = _server(trained)
    ref_srv.start()
    ref = ref_srv.generate(prompt, max_new_tokens=6, timeout=120)
    ref_srv.drain()
    np.testing.assert_array_equal(after, ref)
    assert before.shape == after.shape


# ---------------------------------------------------------------------------
# failure injection: the loop survives, the arena resets


def test_injected_step_fault_fails_live_and_keeps_serving():
    from mxnet_tpu.resilience import faults

    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(10)
    plan = faults.FaultPlan([{"site": "serve.decode", "action": "raise",
                              "on_hit": 2}])
    with faults.armed(plan):
        doomed = srv.submit(_prompts(1, rng)[0], max_new_tokens=24)
        with pytest.raises(faults.TransientFault):
            doomed.result(timeout=120)
    # the loop thread survived: fresh traffic decodes normally
    assert len(srv.generate(_prompts(1, rng)[0], max_new_tokens=5,
                            timeout=120)) == 5
    srv.drain()
    s = srv.stats()
    assert s["failed"] == 1 and s["served"] == 1
    assert s["slots"]["live"] == 0


# ---------------------------------------------------------------------------
# profiler section + request spans


def test_decode_serve_section_and_request_spans(tmp_path):
    import json

    from mxnet_tpu import profiler, telemetry
    from mxnet_tpu.serve import decode as decode_mod

    decode_mod.reset_decode_serve_stats()
    model = _make_model()
    srv = _server(model)
    srv.start()
    rng = np.random.RandomState(11)
    trace_path = str(tmp_path / "decode.trace.json")
    with telemetry.trace(trace_path):
        handles = [srv.submit(p, max_new_tokens=4)
                   for p in _prompts(6, rng)]
        for h in handles:
            h.result(timeout=120)
    srv.drain()

    section = json.loads(profiler.dumps(reset=True))["decodeServe"]
    assert section["admitted"] == section["finished"] == 6
    assert section["tokens"] == 24
    assert section["steps"] >= 3
    assert 0 < section["slot_occupancy"] <= 1
    # window-scoped: the reset dump rewound the section
    fresh = json.loads(profiler.dumps())["decodeServe"]
    assert fresh["tokens"] == fresh["admitted"] == 0

    events = json.load(open(trace_path))["traceEvents"]
    begins = [e for e in events if e["ph"] == "b"
              and e["name"] == "serve.decode.request"]
    ends = [e for e in events if e["ph"] == "e"
            and e["name"] == "serve.decode.request"]
    assert len(begins) == len(ends) == 6
    assert {e["id"] for e in begins} == {e["id"] for e in ends}
    assert all("prompt_len" in e["args"] for e in begins)
    for e in ends:
        assert e["args"]["outcome"] == "served"
        assert e["args"]["tokens"] == 4
        assert e["args"]["queue_ms"] >= 0
        assert e["args"]["decode_ms"] >= 0
    firsts = [e for e in events if e["ph"] == "n"
              and e["name"] == "serve.decode.first_token"]
    assert len(firsts) == 6 and all(e["args"]["ttft_ms"] > 0
                                    for e in firsts)
    names = {e["name"] for e in events}
    assert {"serve.prefill", "serve.decode.admit",
            "serve.decode.step"} <= names


# ---------------------------------------------------------------------------
# concurrent stress under the runtime lock checker


@pytest.mark.slow
def test_decode_stress_concurrent_submitters():
    """Many concurrent submitters + a mid-stream hot reload against the
    decode loop: every accepted request resolves with its full budget,
    the accounting invariant holds, the compile surface stays closed,
    and the lock-order checker observes zero inversions across the
    batcher/stats/exec-lock nest."""
    from mxnet_tpu.analysis import runtime as lock_order

    lock_order.reset()
    assert lock_order.enable(raise_on_inversion=False), \
        "lock-order checker was already on"
    lock_order.wrap_existing()
    try:
        _decode_stress_body()
    finally:
        lock_order.disable()
        lock_order.unwrap_existing()
    assert lock_order.inversions() == []
    assert lock_order.stats()["acquires"] > 0


def _decode_stress_body():
    model = _make_model()
    srv = _server(model, max_slots=8, max_queue=512)
    srv.start()
    n_threads, per_thread = 6, 25
    results, errors = [], []
    lock = threading.Lock()

    def submitter(seed):
        rng = np.random.RandomState(seed)
        handles = [srv.submit(p, max_new_tokens=int(rng.randint(1, 9)))
                   for p in _prompts(per_thread, rng)]
        for h in handles:
            try:
                r = h.result(timeout=600)
                with lock:
                    results.append(r)
            except Exception as e:  # noqa: BLE001 — collected for assert
                with lock:
                    errors.append(e)

    threads = [threading.Thread(target=submitter, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.drain()
    s = srv.stats()
    assert not errors
    assert len(results) == n_threads * per_thread
    assert s["served"] == s["submitted"] == n_threads * per_thread
    assert s["slots"]["live"] == 0 and s["queue_depth"] == 0
    assert s["graph"]["post_warmup_compiles"] == 0
    assert s["tokens"] > s["decode_steps"]  # real continuous batching
