#!/usr/bin/env python
"""Measure collective bandwidth over the device mesh
(ref: tools/bandwidth/measure.py — kvstore all-reduce bandwidth tool,
re-pointed at ICI collectives)."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=64.0)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="force N virtual CPU devices")
    args = ap.parse_args()

    import jax

    if args.cpu_devices:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", args.cpu_devices)
        except AttributeError:  # older jax: flag-based device count
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") +
                f" --xla_force_host_platform_device_count="
                f"{args.cpu_devices}").strip()
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    devs = jax.devices()
    n = len(devs)
    elems = int(args.size_mb * 1e6 / 4)
    elems -= elems % max(n, 1)
    import numpy as np

    mesh = Mesh(np.array(devs), ("dp",))
    x = jnp.ones((elems,), jnp.float32)

    @jax.jit
    def allreduce(x):
        f = shard_map(lambda v: jax.lax.psum(v, "dp"), mesh=mesh,
                      in_specs=PartitionSpec("dp"),
                      out_specs=PartitionSpec())
        return f(x)

    allreduce(x).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(args.iters):
        out = allreduce(x)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / args.iters
    # ring all-reduce moves 2*(n-1)/n of the data per device
    algbw = args.size_mb / 1e3 / dt
    busbw = algbw * 2 * (n - 1) / max(n, 1)
    print(f"devices={n} size={args.size_mb}MB time={dt*1e3:.2f}ms "
          f"algbw={algbw:.2f}GB/s busbw={busbw:.2f}GB/s")


if __name__ == "__main__":
    main()
