"""mx.nd — the imperative NDArray API (ref: python/mxnet/ndarray/)."""
from .ndarray import (NDArray, array, zeros, ones, full, arange, empty,  # noqa: F401
                      zeros_like, ones_like, eye, linspace, histogram,
                      concatenate,
                      waitall, save, load, from_jax, moveaxis)
from .ops import *  # noqa: F401,F403  (generated op namespace)
from . import ops as _gen_ops
from .. import random  # noqa: F401  (mx.nd.random.* sampling namespace)
from . import sparse  # noqa: F401  (mx.nd.sparse storage types)
from .sparse import cast_storage, sparse_retain  # noqa: F401

# creation helpers must win over same-named registered ops: the helper
# versions preserve the source array's device context
from .ndarray import zeros_like, ones_like  # noqa: F401,E402


class _ContribNamespace:
    """mx.nd.contrib.X → the op registered as `_contrib_X`, plus the
    python-level control-flow operators (foreach/while_loop/cond take
    callables, so they bypass the array-op registry — same split as
    python/mxnet/ndarray/contrib.py)."""

    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        if name in ("foreach", "while_loop", "cond"):
            from . import control_flow

            return getattr(control_flow, name)
        try:
            return getattr(self._mod, "_contrib_" + name)
        except AttributeError:
            raise AttributeError(
                f"contrib namespace has no operator '{name}'") from None


contrib = _ContribNamespace(_gen_ops)


class _PrefixNamespace:
    """mx.nd.linalg.X → the op registered as `_linalg_X` (ref:
    python/mxnet/ndarray/linalg.py strips the same prefix)."""

    def __init__(self, mod, prefix, label):
        self._mod = mod
        self._prefix = prefix
        self._label = label

    def __getattr__(self, name):
        # the registry exposes both `linalg_X` (primary) and the
        # MXNet-internal `_linalg_X` alias for most but not all ops —
        # accept either spelling
        for pre in (self._prefix, self._prefix.lstrip("_")):
            try:
                return getattr(self._mod, pre + name)
            except AttributeError:
                continue
        raise AttributeError(
            f"{self._label} namespace has no operator '{name}'")


linalg = _PrefixNamespace(_gen_ops, "_linalg_", "linalg")


class _ImageNamespace:
    """mx.nd.image.X (ref: python/mxnet/ndarray/image.py — the
    image_random.cc op family): thin functional forms over the same
    primitives the gluon vision transforms use."""

    @staticmethod
    def to_tensor(src):
        from .ndarray import _wrap

        x = src._data.astype("float32") / 255.0
        if x.ndim == 3:
            return _wrap(x.transpose(2, 0, 1))
        return _wrap(x.transpose(0, 3, 1, 2))

    @staticmethod
    def normalize(src, mean=0.0, std=1.0):
        import jax.numpy as jnp

        from .ndarray import _wrap

        mean = jnp.asarray(mean, src.dtype)
        std = jnp.asarray(std, src.dtype)
        if mean.ndim == 1:  # per-channel; src is CHW or NCHW
            shape = (1,) * (src._data.ndim - 3) + (-1, 1, 1)
            mean = mean.reshape(shape)
            std = std.reshape(shape)
        return _wrap((src._data - mean) / std)

    @staticmethod
    def resize(src, size, keep_ratio=False, interp=1):
        from ..image.image import imresize, resize_short

        if isinstance(size, int):
            if keep_ratio:
                return resize_short(src, size, interp)
            size = (size, size)
        return imresize(src, size[0], size[1], interp)

    @staticmethod
    def crop(src, x, y, width, height):
        from ..image.image import fixed_crop

        return fixed_crop(src, x, y, width, height)

    @staticmethod
    def random_flip_left_right(src):
        from .. import random as _random

        from .ndarray import _wrap
        import jax.numpy as jnp

        flip = float(_random.uniform(0, 1, shape=(1,)).asnumpy()[0]) < 0.5
        return _wrap(jnp.flip(src._data, axis=-2)) if flip else src


image = _ImageNamespace()

# module-level binary helpers accepting scalar or NDArray operands
# (ref: python/mxnet/ndarray/ndarray.py maximum/minimum/power/hypot)
maximum = _gen_ops.broadcast_maximum
minimum = _gen_ops.broadcast_minimum
power = _gen_ops.broadcast_power
hypot = _gen_ops.broadcast_hypot

# legacy flat sampling names (ref: python/mxnet/ndarray/random.py keeps
# mx.nd.random_normal etc. as deprecated aliases of mx.nd.random.*)
random_normal = random.normal
random_uniform = random.uniform
random_randint = random.randint


def one_hot(indices, depth=None, on_value=1.0, off_value=0.0,
            dtype="float32"):
    """mx.nd.one_hot(indices, depth, ...) — depth is positional in the
    reference signature (indexing_op.cc OneHotParam), but the generated
    wrapper treats extra positionals as array inputs; this shim keeps
    the reference calling convention."""
    if depth is None:
        raise TypeError("one_hot requires depth")
    return _gen_ops.one_hot(indices, depth=int(depth),
                            on_value=on_value, off_value=off_value,
                            dtype=dtype)


def __getattr__(name):
    # fall through to generated ops for aliases added later
    return getattr(_gen_ops, name)


class _RandomNamespace:
    """mx.sym.random-style access by registry name: `random.X` → the op
    registered as `_random_X` (ref: python/mxnet/{ndarray,symbol}/
    random.py generated wrappers).  Names whose ops live under other
    registry spellings (multinomial → sample_multinomial, shuffle →
    _shuffle) are mapped so eager code keeps working when hybridized."""

    _OP_ALIASES = {"multinomial": "sample_multinomial",
                   "shuffle": "_shuffle",
                   "randint": "_random_randint"}

    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        if name == "randn":
            normal = getattr(self._mod, "_random_normal")

            def randn(*shape, loc=0.0, scale=1.0, **kw):
                return normal(loc=loc, scale=scale, shape=shape, **kw)

            return randn
        target = self._OP_ALIASES.get(name, "_random_" + name)
        try:
            return getattr(self._mod, target)
        except AttributeError:
            raise AttributeError(
                f"random namespace has no operator '{name}'") from None
