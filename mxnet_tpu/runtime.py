"""Runtime feature detection (ref: python/mxnet/runtime.py —
mx.runtime.Features() / feature_list()).

The reference reports compile-time flags (CUDA, MKLDNN, OPENCV...);
here features are probed live: backend platforms, native C++
libraries, Pallas availability.
"""
from __future__ import annotations


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = bool(enabled)

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _probe():
    feats = {}
    try:
        import jax

        platforms = {d.platform for d in jax.devices()}
    except Exception:
        platforms = set()
    feats["TPU"] = "tpu" in platforms
    feats["CPU"] = True
    try:
        from .utils import native

        feats["NATIVE_IO"] = native.load() is not None
    except Exception:
        feats["NATIVE_IO"] = False
    try:
        from .utils import native_engine

        feats["NATIVE_ENGINE"] = native_engine.load() is not None
    except Exception:
        feats["NATIVE_ENGINE"] = False
    try:
        from .storage import Storage

        feats["NATIVE_STORAGE"] = Storage.get().native is not None
    except Exception:
        feats["NATIVE_STORAGE"] = False
    import os

    feats["CAPI"] = os.path.exists(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "lib", "libmxtpu_capi.so"))
    try:
        import jax.experimental.pallas  # noqa: F401

        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    feats["BF16"] = True
    feats["INT8_QUANTIZATION"] = True
    feats["DIST_KVSTORE"] = True
    # ref: USE_INT64_TENSOR_SIZE build flag -> runtime toggle here
    try:
        from .util import large_tensor_enabled

        feats["INT64_TENSOR_SIZE"] = large_tensor_enabled()
    except Exception:
        feats["INT64_TENSOR_SIZE"] = False
    # r4 surface: workload data pipelines and the trainable C ABI tier
    try:
        from . import data  # noqa: F401

        feats["DATA_PIPELINES"] = True
    except Exception:
        feats["DATA_PIPELINES"] = False
    # probe an actual trainable-tier symbol: a stale pre-r4 .so exists
    # but lacks it, and existence alone would misreport trainability
    feats["CAPI_TRAINABLE"] = False
    if feats["CAPI"]:
        try:
            import ctypes

            lib = ctypes.CDLL(os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(
                    __file__))), "lib", "libmxtpu_capi.so"))
            feats["CAPI_TRAINABLE"] = hasattr(lib, "MXTPUCreateCachedOp")
        except Exception:
            pass
    return feats


class Features(dict):
    """Mapping name -> Feature (ref: runtime.Features)."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _probe().items()})

    def is_enabled(self, name):
        key = name.upper()
        if key not in self:
            raise RuntimeError(f"unknown feature {name!r}; "
                               f"known: {sorted(self)}")
        return self[key].enabled


def feature_list():
    return list(Features().values())
