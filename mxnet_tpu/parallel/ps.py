"""Parameter-server transport for dist_async (ref: 3rdparty/ps-lite
Van/KVWorker/KVServer + src/kvstore/kvstore_dist_server.h).

The reference's dist_async semantics: each worker's push triggers a
server-side merge/update IMMEDIATELY (no barrier, no waiting for the
other workers); pulls return whatever the server holds right now.
Synchronous collectives cannot express that, so — like the reference —
async rides a real transport: a threaded TCP KV server. dist_sync stays
on the in-graph DCN collective path (parallel/dist.py), which is the
right shape for TPU pods; this server is the DCN-async escape hatch and
runs anywhere (the nightly tests drive it multi-process on CPU).

Protocol: length-prefixed pickled tuples, trusted-cluster only (same
trust model as ps-lite's raw ZMQ). Ops:
  ("init", key, array)      -> set-if-absent (idempotent)
  ("push", key, array)      -> merge: optimizer(key, grad, weight) if a
                               server-side optimizer is set (the
                               update_on_kvstore semantic), else +=
  ("pull", key)             -> current value
  ("set_optimizer", bytes)  -> install pickled optimizer (worker 0)
  ("stop",)                 -> shut down
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading

import numpy as np

from ..base import MXNetError


def _send_frame(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


class PSServer:
    """The KVServer role (ref: KVStoreDistServer::Run DataHandleEx)."""

    def __init__(self, port, host="0.0.0.0"):
        self._store = {}           # key -> np.ndarray (weights)
        self._updater = None       # server-side optimizer updater
        self._lock = threading.Lock()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        msg = _recv_frame(self.request)
                        reply = outer._handle(msg)
                        _send_frame(self.request, reply)
                        if msg[0] == "stop":
                            # shutdown() from this handler thread is safe
                            # (serve_forever runs in its own thread) and
                            # unblocks run_server's join
                            threading.Thread(target=outer.stop,
                                             daemon=True).start()
                            return
                except (ConnectionError, OSError):
                    return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    def _handle(self, msg):
        op = msg[0]
        with self._lock:
            if op == "init":
                _, key, arr = msg
                self._store.setdefault(key, np.array(arr, copy=True))
                return ("ok",)
            if op == "push":
                _, key, grad = msg
                if key not in self._store:
                    return ("err", f"key {key} not initialized")
                if self._updater is not None:
                    # per-push server-side optimizer: THE async semantic
                    # (ref: kvstore_dist_server.h DataHandleDefault,
                    # sync_mode_=false branch)
                    from ..ndarray import ndarray as _nd

                    w = _nd.array(self._store[key])
                    self._updater(_ps_key_index(key), _nd.array(grad), w)
                    self._store[key] = np.asarray(w.asnumpy())
                else:
                    self._store[key] = self._store[key] + np.asarray(grad)
                return ("ok",)
            if op == "pull":
                _, key = msg
                if key not in self._store:
                    return ("err", f"key {key} not initialized")
                return ("ok", self._store[key])
            if op == "set_optimizer":
                from .. import optimizer as _opt

                self._updater = _opt.get_updater(pickle.loads(msg[1]))
                return ("ok",)
            if op == "stop":
                return ("ok",)
        return ("err", f"unknown op {op!r}")


def _ps_key_index(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


class PSClient:
    """The KVWorker role (ref: ps::KVWorker push/pull).

    Keys are sharded over the server group by hash (ref: ps-lite's
    key→server range partitioning); optimizer installs broadcast to
    every server."""

    def __init__(self, endpoints, timeout=60):
        if isinstance(endpoints, tuple) and isinstance(endpoints[0], str):
            endpoints = [endpoints]
        self._socks = [socket.create_connection((h, p), timeout=timeout)
                       for h, p in endpoints]
        self._locks = [threading.Lock() for _ in self._socks]

    def _server_of(self, key):
        import zlib

        return zlib.crc32(str(key).encode()) % len(self._socks)

    def _call_on(self, i, *msg):
        with self._locks[i]:
            _send_frame(self._socks[i], msg)
            reply = _recv_frame(self._socks[i])
        if reply[0] != "ok":
            raise MXNetError(f"ps server error: {reply[1:]}")
        return reply[1] if len(reply) > 1 else None

    def _call(self, op, key, *rest):
        return self._call_on(self._server_of(key), op, key, *rest)

    def init(self, key, arr):
        self._call("init", key, np.asarray(arr))

    def push(self, key, grad):
        self._call("push", key, np.asarray(grad))

    def pull(self, key):
        return self._call("pull", key)

    def set_optimizer(self, optimizer):
        blob = pickle.dumps(optimizer)
        for i in range(len(self._socks)):
            self._call_on(i, "set_optimizer", blob)

    def stop_server(self):
        for i in range(len(self._socks)):
            self._call_on(i, "stop")

    def close(self):
        for s in self._socks:
            s.close()


_server_singleton = None


def server_endpoints():
    """[(host, port), ...] of the PS group for this job.

    Dedicated server roles if tools/launch.py spawned them
    (DMLC_PS_SERVER_PORT base + DMLC_NUM_SERVER consecutive ports);
    otherwise worker 0 hosts one in-process server thread on
    root_port+1 — the local-launcher degenerate mode.
    """
    host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    base = int(os.environ.get(
        "DMLC_PS_SERVER_PORT",
        int(os.environ.get("DMLC_PS_ROOT_PORT", "9099")) + 1))
    n = max(1, int(os.environ.get("DMLC_NUM_SERVER", "0") or 0))
    if "DMLC_PS_SERVER_PORT" not in os.environ:
        n = 1  # embedded single-server mode
    return [(host, base + i) for i in range(n)]


def ensure_local_server():
    """Start the in-process server on worker 0 when no dedicated server
    role exists. Idempotent."""
    global _server_singleton
    if _server_singleton is None:
        (_, port), = server_endpoints()
        _server_singleton = PSServer(port).start()
    return _server_singleton


def run_server():
    """Blocking server loop for a dedicated DMLC_ROLE=server process
    (ref: MXKVStoreRunServer / kvstore_server.py).

    The PS is a host-side role: its optimizer updates run on XLA:CPU.
    Pinning the platform here also keeps the server off the TPU tunnel
    (a server process must come up even when the accelerator is wedged).
    """
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized by the embedding process
    host, base = server_endpoints()[0]
    my_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    srv = PSServer(base + my_id, host="0.0.0.0").start()
    srv._thread.join()
