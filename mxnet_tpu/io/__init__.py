"""IO subsystem (ref: src/io/ + python/mxnet/io/)."""
from .io import (DataBatch, DataDesc, DataIter, NDArrayIter, MNISTIter,  # noqa: F401
                 CSVIter, LibSVMIter, ImageRecordIter, PrefetchingIter,
                 ResizeIter)
from . import recordio  # noqa: F401


def ImageDetRecordIter(**kwargs):
    """Detection record iterator (ref: src/io/iter_image_det_recordio.cc,
    registered as io.ImageDetRecordIter). Alias onto
    `mx.image.ImageDetIter`; label layout and kwargs are shared."""
    from ..image.detection import ImageDetIter

    return ImageDetIter(**kwargs)
