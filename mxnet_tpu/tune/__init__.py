"""Closed-loop autotuner: measured trials + cost-model search over the
live knob surface.

The sensor layer (health windows, profiler sections, ``/metrics``)
already reports how fast the system runs; this package moves the knobs
itself.  ``knobs`` is the typed registry of everything tunable,
``trials`` the seeded measured-window protocol, ``cost_model`` the
cheap ranking filter in front of expensive real trials, ``geometry``
the traffic-derived serving shapes, and ``tuner`` the coordinate
descent that ties them into ``Tuner.recommend()`` — a config plus the
evidence trail that earned it.  See docs/tuning.md.
"""
from .knobs import (Knob, KnobRegistry, default_registry,
                    RESTART_CLASSES)
from .trials import (TrialRunner, default_objective, tune_stats,
                     reset_tune_stats)
from .cost_model import CostModel
from .geometry import (parse_grid, format_grid, padding_overhead,
                       derive_lengths, derive_batches,
                       derive_bucket_spec, derive_decode_geometry)
from .tuner import Tuner, Recommendation

__all__ = [
    "Knob", "KnobRegistry", "default_registry", "RESTART_CLASSES",
    "TrialRunner", "default_objective", "tune_stats",
    "reset_tune_stats", "CostModel", "parse_grid", "format_grid",
    "padding_overhead", "derive_lengths", "derive_batches",
    "derive_bucket_spec", "derive_decode_geometry", "Tuner",
    "Recommendation",
]
