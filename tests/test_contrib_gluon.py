"""gluon.contrib blocks + viz (ref: tests/python/unittest/test_gluon_contrib.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def test_hybrid_concurrent_and_identity():
    net = gluon.contrib.nn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(4), gluon.contrib.nn.Identity())
    net.initialize(mx.init.Xavier())
    x = nd.ones((2, 3))
    out = net(x)
    assert out.shape == (2, 7)
    net.hybridize()
    np.testing.assert_allclose(out.asnumpy(), net(x).asnumpy(), rtol=1e-6)


def test_sparse_embedding_grad_stype():
    se = gluon.contrib.nn.SparseEmbedding(10, 4)
    assert se.weight.grad_stype == "row_sparse"


def test_variational_dropout_same_mask_across_steps():
    cell = gluon.contrib.rnn.VariationalDropoutCell(
        gluon.rnn.RNNCell(6), drop_inputs=0.5)
    cell.base_cell.initialize(mx.init.One())
    mx.random.seed(7)
    x = nd.ones((2, 3))
    with autograd.record():
        cell(x, cell.begin_state(2))
        mask1 = cell._mask_in.asnumpy()
        cell(x, cell.begin_state(2))
        mask2 = cell._mask_in.asnumpy()
    np.testing.assert_allclose(mask1, mask2)  # cached until reset
    cell.reset()
    assert cell._mask_in is None


def test_variational_dropout_inference_identity():
    base = gluon.rnn.RNNCell(5)
    cell = gluon.contrib.rnn.VariationalDropoutCell(base, drop_inputs=0.9)
    base.initialize(mx.init.Xavier())
    x = nd.ones((2, 4))
    s = cell.begin_state(2)
    o1, _ = cell(x, s)
    o2, _ = base(x, s)
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(), rtol=1e-6)


def test_zoneout_cell_trains():
    zc = gluon.rnn.ZoneoutCell(gluon.rnn.GRUCell(5), zoneout_states=0.3)
    zc.base_cell.initialize(mx.init.Xavier())
    with autograd.record():
        o, s = zc(nd.ones((2, 3)))
        o2, _ = zc(nd.ones((2, 3)), s)
    assert o2.shape == (2, 5)
    # inference passes straight through
    o_inf, _ = zc(nd.ones((2, 3)))
    assert np.isfinite(o_inf.asnumpy()).all()


def test_modifier_cell_state_info():
    rc = gluon.rnn.ResidualCell(gluon.rnn.LSTMCell(4))
    assert rc.state_info(2) == rc.base_cell.state_info(2)
    assert rc.base_cell._modified


def test_viz_print_summary(capsys):
    import mxnet_tpu.symbol as sym

    data = sym.var("data")
    c1 = sym.Convolution(data, num_filter=8, kernel=(3, 3), name="conv1")
    a1 = sym.Activation(c1, act_type="relu", name="relu1")
    fc = sym.FullyConnected(a1, num_hidden=10, name="fc1")
    out = sym.SoftmaxOutput(fc, name="softmax")
    total = mx.viz.print_summary(out, shape={"data": (1, 1, 28, 28)})
    assert total == 8 * 9 + 8 + 10 * 8 * 26 * 26 + 10
    cap = capsys.readouterr().out
    assert "conv1 (Convolution)" in cap and "(1, 8, 26, 26)" in cap


def test_viz_plot_network_soft_dependency():
    import mxnet_tpu.symbol as sym

    out = sym.FullyConnected(sym.var("data"), num_hidden=4, name="fc")
    try:
        g = mx.viz.plot_network(out)
        assert g is not None
    except mx.MXNetError as e:
        assert "graphviz" in str(e)
