"""Multi-process distributed tests, launched the reference's way:
tools/launch.py -n N --launcher local (ref: tests/nightly/)."""
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(script, n, num_servers=0, timeout=240, env_extra=None,
            launcher="local"):
    """Run a tests/nightly worker script through tools/launch.py and
    return its combined output (asserting exit 0)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # scripts force cpu themselves
    if env_extra:
        env.update(env_extra)
    cmd = [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
           "-n", str(n)]
    if num_servers:
        cmd += ["-s", str(num_servers)]
    cmd += ["--launcher", launcher, sys.executable,
            os.path.join(_ROOT, "tests", "nightly", script)]
    res = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout, env=env, cwd=_ROOT)
    out = res.stdout + res.stderr
    assert res.returncode == 0, out[-3000:]
    return out


@pytest.mark.slow
@pytest.mark.parametrize("n,timeout", [(2, 240), (4, 360), (8, 600)])
def test_dist_sync_kvstore_n_workers(n, timeout):
    """In-graph DCN all-reduce at 2 (the reference nightly's base), 4
    (VERDICT r2 #5: scale past 2) and 8 workers (a v5p-16 host-group's
    process count — the largest local-launcher shape this box
    carries)."""
    out = _launch("dist_sync_kvstore.py", n, timeout=timeout)
    for r in range(n):
        assert f"worker {r}/{n}: dist_sync kvstore OK" in out


@pytest.mark.slow
@pytest.mark.parametrize("num_servers", [0, 1])
def test_dist_async_kvstore_two_workers(tmp_path, num_servers):
    """num_servers=0: worker 0 hosts the PS thread; =1: dedicated
    DMLC_ROLE=server process (ref: tools/launch.py -s)."""
    out = _launch("dist_async_kvstore.py", 2, num_servers=num_servers,
                  env_extra={"MXTPU_TEST_TMPDIR": str(tmp_path)})
    for r in (0, 1):
        assert f"worker {r}/2: dist_async kvstore OK" in out




@pytest.mark.slow
@pytest.mark.parametrize("num_servers", [0, 1])
def test_dist_async_conflict_three_workers(tmp_path, num_servers):
    """Conflicting + out-of-order pushes at n=3 with exact merge
    assertions (VERDICT r2 weak #5)."""
    out = _launch("dist_async_conflict.py", 3, num_servers=num_servers,
                  timeout=360,
                  env_extra={"MXTPU_TEST_TMPDIR": str(tmp_path)})
    for r in range(3):
        assert f"worker {r}/3: dist_async conflict OK" in out


@pytest.mark.slow
@pytest.mark.skipif(__import__("shutil").which("mpirun") is None,
                    reason="mpirun not installed")
def test_dist_sync_kvstore_two_workers_mpi():
    """VERDICT r3 #7: the mpi launcher transport (ref: dmlc_tracker/
    mpi.py) — mpirun fans out ranks, the shim derives worker ids from
    the MPI rank variable."""
    out = _launch("dist_sync_kvstore.py", 2, launcher="mpi")
    assert "worker 0/2: dist_sync kvstore OK" in out
    assert "worker 1/2: dist_sync kvstore OK" in out


def test_mpi_shim_translates_rank():
    """The --mpi-shim re-entry itself needs no mpirun: fake the OpenMPI
    rank variable and check the env protocol lands in the child."""
    env = dict(os.environ)
    env.update({"OMPI_COMM_WORLD_RANK": "3", "MXTPU_NUM_WORKER": "4",
                "DMLC_NUM_WORKER": "4"})
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "--mpi-shim", "--", sys.executable, "-c",
         "import os; print('wid', os.environ['MXTPU_WORKER_ID'],"
         " os.environ['DMLC_WORKER_ID'])"],
        capture_output=True, text=True, timeout=60, env=env, cwd=_ROOT)
    assert res.returncode == 0, res.stderr[-1000:]
    assert "wid 3 3" in res.stdout
    # no rank variable -> diagnosable failure, not a silent wrong id
    env2 = {k: v for k, v in os.environ.items()
            if "RANK" not in k and "PROCID" not in k}
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "--mpi-shim", "--", "true"],
        capture_output=True, text=True, timeout=60, env=env2, cwd=_ROOT)
    assert res.returncode == 2
    assert "no MPI rank variable" in res.stderr


def test_k8s_manifest_generator():
    """--launcher k8s renders an indexed-Job manifest carrying the DMLC
    env protocol (generator only; ref: dmlc_tracker yarn/k8s role)."""
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "launch.py"),
         "-n", "4", "--launcher", "k8s", "--image", "example/img:1",
         "--job-name", "trainjob", "python", "train.py"],
        capture_output=True, text=True, timeout=60, cwd=_ROOT)
    assert res.returncode == 0, res.stderr[-1000:]
    out = res.stdout
    import yaml

    service, job = list(yaml.safe_load_all(out))
    assert service["kind"] == "Service"
    # k8s headless-service sentinel is the literal string "None"
    assert service["spec"]["clusterIP"] == "None"
    assert job["kind"] == "Job"
    assert job["spec"]["completions"] == 4
    assert job["spec"]["completionMode"] == "Indexed"
    container = job["spec"]["template"]["spec"]["containers"][0]
    assert container["image"] == "example/img:1"
    assert container["command"] == ["python", "train.py"]
    envs = {e["name"]: e for e in container["env"]}
    assert envs["MXTPU_COORDINATOR"]["value"] == "trainjob-0.trainjob:9099"
    assert envs["MXTPU_NUM_WORKER"]["value"] == "4"
    assert "fieldRef" in envs["MXTPU_WORKER_ID"]["valueFrom"]


@pytest.mark.slow
def test_dist_gluon_trainer_matches_oracle(tmp_path):
    """gluon.Trainer(kvstore='dist_sync') — the reference's canonical
    user-facing dist loop — at 2 workers: per-step losses equal the
    single-process full-batch oracle and both workers end with
    identical params."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    rng = np.random.RandomState(0)
    X = rng.rand(16, 12).astype(np.float32)
    Y = rng.randint(0, 4, 16).astype(np.float32)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore="local")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(6):
        with autograd.record():
            loss = loss_fn(net(nd.array(X)), nd.array(Y)).sum()
        loss.backward()
        trainer.step(16)
        losses.append(float(loss.asscalar()) / 16)
    oracle_file = str(tmp_path / "gluon_oracle.npz")
    np.savez(oracle_file, losses=np.asarray(losses, np.float64))

    out = _launch("dist_gluon_trainer.py", 2, timeout=300,
                  env_extra={"MXTPU_ORACLE_FILE": oracle_file})
    for r in (0, 1):
        assert f"worker {r}/2: gluon dist_sync trainer OK" in out


@pytest.mark.slow
def test_dist_hierarchical_dcn_x_ici(tmp_path):
    """The pod shape (VERDICT r3 #5): 2 processes x 4 virtual devices
    each — DataParallelTrainer on a 2-level {'dcn': 2, 'dp': 4} mesh
    must reproduce the 8-device single-process losses exactly, and
    kvstore('dist_sync') composed with an in-process 4-device psum must
    reproduce the full-batch gradient (ref: ps-lite workers x
    multi-GPU per worker, SURVEY §3.4)."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import data_parallel
    from mxnet_tpu.parallel import mesh as mesh_mod

    # oracle: the same trainer, single process, flat 8-device dp mesh
    # (conftest provides the virtual 8-CPU mesh)
    rng = np.random.RandomState(0)
    X = rng.rand(16, 20).astype(np.float32)
    Y = rng.randint(0, 10, 16).astype(np.float32)
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = data_parallel.DataParallelTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1}, mesh=mesh_mod.make_mesh({"dp": 8}))
    losses = [float(trainer.step(X, Y).asnumpy()) for _ in range(5)]
    oracle_file = str(tmp_path / "hier_oracle.npz")
    np.savez(oracle_file, losses=np.asarray(losses, np.float64))

    out = _launch("dist_hier_dcn_ici.py", 2, timeout=420,
                  env_extra={"MXTPU_ORACLE_FILE": oracle_file})
    for r in (0, 1):
        assert f"worker {r}/2: hier dcn x ici OK" in out


@pytest.mark.slow
def test_dist_sync_worker_death_then_rejoin(tmp_path):
    """In-graph dist_sync failure semantics (VERDICT r4 #6): at n=4, a
    worker dying mid-step must surface a diagnosable MXNetError on every
    survivor within the MXTPU_BARRIER_TIMEOUT_S bound (not hang), and a
    relaunched group must rejoin from the surviving checkpoint and
    finish with oracle-exact losses."""
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    # single-process full-batch oracle for the complete 6-step run
    rng = np.random.RandomState(0)
    X = rng.rand(16, 12).astype(np.float32)
    Y = rng.randint(0, 4, 16).astype(np.float32)
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore="local")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(6):
        with autograd.record():
            loss = loss_fn(net(nd.array(X)), nd.array(Y)).sum()
        loss.backward()
        trainer.step(16)
        losses.append(float(loss.asscalar()) / 16)
    oracle_file = str(tmp_path / "failfast_oracle.npz")
    np.savez(oracle_file, losses=np.asarray(losses, np.float64))

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    base_env = {"MXTPU_FAILTEST_CKPT": str(ckpt),
                "MXTPU_ORACLE_FILE": oracle_file,
                "MXTPU_BARRIER_TIMEOUT_S": "20"}

    # phase 1: rank 1 of 4 dies abruptly at step 3. Two legitimate
    # bounded fail-fast outcomes race per survivor: (a) our watchdog/
    # transport path raises the diagnosable MXNetError ("peer failure
    # detected"), or (b) jax's coordination service notices the dead
    # task first and terminates the survivor with its own diagnosis
    # ("another task died").  Either way the job ends promptly with a
    # diagnosable cause — assert that, not which race winner.
    import subprocess as sp
    import time as _time

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(base_env)
    env["MXTPU_FAILTEST_MODE"] = "die"
    t0 = _time.monotonic()
    res = sp.run([sys.executable,
                  os.path.join(_ROOT, "tools", "launch.py"), "-n", "4",
                  "--launcher", "local", sys.executable,
                  os.path.join(_ROOT, "tests", "nightly",
                               "dist_sync_failfast.py")],
                 capture_output=True, text=True, timeout=300, env=env,
                 cwd=_ROOT)
    took = _time.monotonic() - t0
    out = res.stdout + res.stderr
    assert "worker 1/4: dying abruptly at step 3" in out, out[-2000:]
    detected = out.count("peer failure detected in")
    terminated = ("detected fatal errors" in out
                  or "task died" in out
                  or "heartbeat timeout" in out)
    assert detected > 0 or terminated, out[-3000:]
    # bounded: well inside watchdog bound + slack, nobody hung
    assert took < 120, f"fail-fast took {took:.0f}s"
    assert int(open(ckpt / "step.txt").read()) == 3

    # phase 2: fresh group (replacement worker included) rejoins from
    # the checkpoint and finishes steps 3..5 on the oracle trajectory
    out = _launch("dist_sync_failfast.py", 4, timeout=300,
                  env_extra=dict(base_env, MXTPU_FAILTEST_MODE="resume"))
    for r in range(4):
        assert f"worker {r}/4: rejoined from step 3 and finished OK" \
            in out, out[-2000:]


@pytest.mark.slow
@pytest.mark.parametrize("failure_mode", ["sigkill", "sigstop"])
def test_dist_async_server_death_fails_fast(tmp_path, failure_mode):
    """Kill the dedicated parameter-server PROCESS mid-run: the worker
    must surface a diagnosable MXNetError quickly — not hang (VERDICT
    r2 weak #5 'heartbeat marks dead -> then what?').

    Two failure shapes exercise two detection paths:
    - sigkill: the kernel closes the socket (RST) -> the connect/retry
      path reports the server unreachable immediately;
    - sigstop: the process freezes but its socket STAYS OPEN (the
      network-partition/power-loss shape, no RST) -> only the
      HEARTBEAT detector can mark it dead."""
    import random
    import signal
    import time

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu.parallel.ps import PSClient

    port = 19700 + (os.getpid() + random.randrange(500)) % 1000

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update({"DMLC_PS_SERVER_PORT": str(port), "DMLC_NUM_SERVER": "1",
                "DMLC_SERVER_ID": "0"})
    server = subprocess.Popen(
        [sys.executable, "-c",
         "from mxnet_tpu.parallel import ps; ps.run_server()"],
        env=env, cwd=_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        cli = None
        for _ in range(80):  # server cold start
            if server.poll() is not None:
                break  # died at startup: surface its stderr below
            try:
                cli = PSClient([("127.0.0.1", port)], timeout=2,
                               retries=1, worker_id=0,
                               heartbeat_interval=0.05, dead_after=4)
                break
            except OSError:
                time.sleep(0.25)
        if cli is None:
            server.kill()
            out, err = server.communicate(timeout=10)
            raise AssertionError(
                f"server never came up on port {port}; stderr:\n"
                f"{err[-2000:]}")
        cli.init("w", np.zeros(4, np.float32))
        cli.push("w", np.ones(4, np.float32))
        assert cli.pull("w")[0] == 1.0

        t0 = time.time()
        if failure_mode == "sigkill":
            server.send_signal(signal.SIGKILL)
            server.wait(timeout=10)
        else:
            server.send_signal(signal.SIGSTOP)  # frozen, socket open
            # the heartbeat thread must mark it dead on its own
            deadline = time.time() + 20
            while cli.alive() and time.time() < deadline:
                time.sleep(0.05)
            assert cli.alive() == [], (
                "heartbeat never marked the frozen server dead")
        with pytest.raises(mx.MXNetError,
                           match="dead" if failure_mode == "sigstop"
                                 else "dead|unreachable"):
            for _ in range(40):  # the kill path may need a few misses
                cli.push("w", np.ones(4, np.float32))
                time.sleep(0.1)
        # diagnosable AND prompt: well under a one-minute hang
        assert time.time() - t0 < 40, "fail-fast took too long"
        cli.close()
    finally:
        if server.poll() is None:
            try:
                server.send_signal(signal.SIGCONT)
            except Exception:
                pass
            server.kill()
