"""Parameter-server transport for dist_async (ref: 3rdparty/ps-lite
Van/KVWorker/KVServer + src/kvstore/kvstore_dist_server.h).

The reference's dist_async semantics: each worker's push triggers a
server-side merge/update IMMEDIATELY (no barrier, no waiting for the
other workers); pulls return whatever the server holds right now.
Synchronous collectives cannot express that, so — like the reference —
async rides a real transport: a threaded TCP KV server. dist_sync stays
on the in-graph DCN collective path (parallel/dist.py), which is the
right shape for TPU pods; this server is the DCN-async escape hatch and
runs anywhere (the nightly tests drive it multi-process on CPU).

Protocol: length-prefixed pickled tuples — TRUSTED-CLUSTER ONLY (same
trust model as ps-lite's raw ZMQ, but sharper: unpickling attacker
bytes is REMOTE CODE EXECUTION, not just data corruption — anyone who
can reach the port owns the process).  Servers therefore bind loopback
by default; a multi-host cluster must opt in by setting
DMLC_PS_BIND_HOST (e.g. 0.0.0.0) and is responsible for network
isolation of the PS ports.  Ops:
  ("init", key, array)      -> set-if-absent (idempotent)
  ("push", key, array[, wid, seq]) -> merge: optimizer(key, grad,
                               weight) if a server-side optimizer is
                               set (the update_on_kvstore semantic),
                               else +=.  (wid, seq) enables resend
                               dedup: a retried push that was already
                               applied is acknowledged, not re-applied.
  ("pull", key)             -> current value
  ("set_optimizer", bytes)  -> install pickled optimizer (worker 0)
  ("heartbeat",)            -> liveness probe (ref: ps-lite Postoffice
                               heartbeats / PS_HEARTBEAT_INTERVAL)
  ("stop",)                 -> shut down

Reliability (ref: ps-lite Van resend + node management, SURVEY §5
"failure detection"): clients retry dropped connections with
exponential backoff (MXTPU_PS_RESEND attempts, resending the exact
message — safe because pushes carry (worker, seq) dedup ids), and an
optional heartbeat thread marks servers dead after consecutive misses
so training fails fast with a diagnosable error instead of hanging.
"""
from __future__ import annotations

import itertools
import os
import pickle
import socket
import socketserver
import struct
import threading
import time

import numpy as np

from ..base import MXNetError, getenv


def _send_frame(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_frame(sock):
    (n,) = struct.unpack("<Q", _recv_exact(sock, 8))
    return pickle.loads(_recv_exact(sock, n))


class PSServer:
    """The KVServer role (ref: KVStoreDistServer::Run DataHandleEx)."""

    def __init__(self, port, host=None):
        if host is None:
            # loopback unless the cluster explicitly opts in: the pickle
            # protocol is RCE to anyone who can reach the port (see
            # module docstring)
            host = os.environ.get("DMLC_PS_BIND_HOST", "127.0.0.1")
        self._store = {}           # key -> np.ndarray (weights)
        self._updater = None       # server-side optimizer updater
        self._applied = {}         # (wid, key) -> last applied push seq
        self._lock = threading.Lock()
        self._conns = set()        # live handler sockets (closed on stop)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                with outer._lock:
                    outer._conns.add(self.request)
                try:
                    while True:
                        msg = _recv_frame(self.request)
                        reply = outer._handle(msg)
                        _send_frame(self.request, reply)
                        if msg[0] == "stop":
                            # shutdown() from this handler thread is safe
                            # (serve_forever runs in its own thread) and
                            # unblocks run_server's join
                            threading.Thread(target=outer.stop,
                                             daemon=True).start()
                            return
                except (ConnectionError, OSError):
                    return
                finally:
                    with outer._lock:
                        outer._conns.discard(self.request)

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        # sever live connections so clients observe the death (a real
        # process exit does this; shutdown() alone leaves handler
        # threads serving stale state over established sockets)
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _handle(self, msg):
        op = msg[0]
        with self._lock:
            if op == "init":
                _, key, arr = msg
                self._store.setdefault(key, np.array(arr, copy=True))
                return ("ok",)
            if op == "push":
                key, grad = msg[1], msg[2]
                wid, seq = (msg[3], msg[4]) if len(msg) >= 5 \
                    else (None, None)
                if key not in self._store:
                    return ("err", f"key {key} not initialized")
                if wid is not None:
                    # resend dedup (ref: ps-lite PS_RESEND message ids):
                    # a retried push whose original landed is ACKed, not
                    # re-applied — pushes are not idempotent
                    if self._applied.get((wid, key), -1) >= seq:
                        return ("ok", "dup")
                    self._applied[(wid, key)] = seq
                if self._updater is not None:
                    # per-push server-side optimizer: THE async semantic
                    # (ref: kvstore_dist_server.h DataHandleDefault,
                    # sync_mode_=false branch)
                    from ..ndarray import ndarray as _nd

                    w = _nd.array(self._store[key])
                    self._updater(_ps_key_index(key), _nd.array(grad), w)
                    self._store[key] = np.asarray(w.asnumpy())
                else:
                    self._store[key] = self._store[key] + np.asarray(grad)
                return ("ok",)
            if op == "pull":
                _, key = msg
                if key not in self._store:
                    return ("err", f"key {key} not initialized")
                return ("ok", self._store[key])
            if op == "set_optimizer":
                from .. import optimizer as _opt

                self._updater = _opt.get_updater(pickle.loads(msg[1]))
                return ("ok",)
            if op == "heartbeat":
                return ("ok", time.time())
            if op == "stop":
                return ("ok",)
        return ("err", f"unknown op {op!r}")


def _ps_key_index(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


class PSClient:
    """The KVWorker role (ref: ps::KVWorker push/pull).

    Keys are sharded over the server group by hash (ref: ps-lite's
    key→server range partitioning); optimizer installs broadcast to
    every server.

    Reliability: a dropped/timed-out request is resent on a fresh
    connection up to MXTPU_PS_RESEND times with exponential backoff
    (pushes carry (worker, seq) ids so a resend can never double-apply);
    an optional heartbeat thread (interval > 0) probes every server and
    marks one dead after `dead_after` consecutive misses — calls then
    fail fast with the failure cause instead of hanging (ref: ps-lite
    Van resend + Postoffice heartbeats).
    """

    def __init__(self, endpoints, timeout=60, retries=None, worker_id=None,
                 heartbeat_interval=None, dead_after=3,
                 on_server_death=None):
        if isinstance(endpoints, tuple) and isinstance(endpoints[0], str):
            endpoints = [endpoints]
        self._endpoints = list(endpoints)
        self._timeout = timeout
        self._retries = int(getenv("PS_RESEND", 3, int)) \
            if retries is None else int(retries)
        if worker_id is not None:
            self._worker_id = int(worker_id)
        elif "DMLC_WORKER_ID" in os.environ:
            self._worker_id = int(os.environ["DMLC_WORKER_ID"])
        else:
            # pid alone collides across hosts/containers (two "pid 1"
            # workers would share a dedup watermark and silently drop
            # each other's pushes) — fold in the hostname
            import zlib

            self._worker_id = (
                zlib.crc32(socket.gethostname().encode()) << 22
            ) | (os.getpid() & 0x3FFFFF)
        # seq base = µs since epoch: a restarted worker (same wid) must
        # start ABOVE the server's dedup watermark from its previous
        # incarnation, else its pushes are silently dropped as dups
        self._seq = itertools.count(int(time.time() * 1e6))
        self._socks = [socket.create_connection((h, p), timeout=timeout)
                       for h, p in self._endpoints]
        self._locks = [threading.Lock() for _ in self._socks]
        self._dead = [None] * len(self._socks)  # index -> failure reason
        self._misses = [0] * len(self._socks)
        self._on_server_death = on_server_death
        self._hb_stop = threading.Event()
        self._hb_thread = None
        interval = float(getenv("PS_HEARTBEAT", 0.0, float)) \
            if heartbeat_interval is None else float(heartbeat_interval)
        self._dead_after = int(dead_after)
        if interval > 0:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, args=(interval,), daemon=True)
            self._hb_thread.start()

    # -- transport with resend ----------------------------------------------

    def _reconnect(self, i):
        try:
            self._socks[i].close()
        except OSError:
            pass
        self._socks[i] = socket.create_connection(
            self._endpoints[i], timeout=self._timeout)

    def _call_on(self, i, *msg):
        if self._dead[i]:
            raise MXNetError(
                f"ps server {self._endpoints[i]} marked dead: "
                f"{self._dead[i]}")
        last = None
        for attempt in range(self._retries + 1):
            try:
                with self._locks[i]:
                    _send_frame(self._socks[i], msg)
                    reply = _recv_frame(self._socks[i])
                break
            except (ConnectionError, OSError) as e:
                last = e
                if attempt >= self._retries:
                    self._mark_dead(i, f"{type(e).__name__}: {e} after "
                                       f"{self._retries + 1} attempts")
                    raise MXNetError(
                        f"ps server {self._endpoints[i]} unreachable "
                        f"({last}); gave up after "
                        f"{self._retries + 1} attempts") from e
                time.sleep(min(0.1 * 2 ** attempt, 2.0))
                with self._locks[i]:
                    try:
                        self._reconnect(i)
                    except OSError as e2:
                        last = e2
        if reply[0] != "ok":
            raise MXNetError(f"ps server error: {reply[1:]}")
        return reply[1] if len(reply) > 1 else None

    def _call(self, op, key, *rest):
        return self._call_on(self._server_of(key), op, key, *rest)

    def _server_of(self, key):
        import zlib

        return zlib.crc32(str(key).encode()) % len(self._socks)

    # -- failure detection ---------------------------------------------------

    def _mark_dead(self, i, reason):
        if self._dead[i] is None:
            self._dead[i] = reason
            if self._on_server_death is not None:
                try:
                    self._on_server_death(i, self._endpoints[i], reason)
                except Exception:
                    pass

    def _heartbeat_loop(self, interval):
        while not self._hb_stop.wait(interval):
            for i in range(len(self._socks)):
                if self._dead[i]:
                    continue
                try:
                    with self._locks[i]:
                        _send_frame(self._socks[i], ("heartbeat",))
                        _recv_frame(self._socks[i])
                    self._misses[i] = 0
                except (ConnectionError, OSError) as e:
                    self._misses[i] += 1
                    try:
                        with self._locks[i]:
                            self._reconnect(i)
                    except OSError:
                        pass
                    if self._misses[i] >= self._dead_after:
                        self._mark_dead(
                            i, f"{self._misses[i]} consecutive heartbeat "
                               f"misses ({e})")

    def alive(self):
        """Endpoints still considered live (failure-detection view)."""
        return [ep for ep, d in zip(self._endpoints, self._dead) if not d]

    # -- kv api --------------------------------------------------------------

    def init(self, key, arr):
        self._call("init", key, np.asarray(arr))

    def push(self, key, grad):
        self._call("push", key, np.asarray(grad),
                   self._worker_id, next(self._seq))

    def pull(self, key):
        return self._call("pull", key)

    def set_optimizer(self, optimizer):
        blob = pickle.dumps(optimizer)
        for i in range(len(self._socks)):
            self._call_on(i, "set_optimizer", blob)

    def stop_server(self):
        for i in range(len(self._socks)):
            self._call_on(i, "stop")

    def close(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        for s in self._socks:
            s.close()


_server_singleton = None


def server_endpoints():
    """[(host, port), ...] of the PS group for this job.

    Dedicated server roles if tools/launch.py spawned them
    (DMLC_PS_SERVER_PORT base + DMLC_NUM_SERVER consecutive ports);
    otherwise worker 0 hosts one in-process server thread on
    root_port+1 — the local-launcher degenerate mode.
    """
    host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    base = int(os.environ.get(
        "DMLC_PS_SERVER_PORT",
        int(os.environ.get("DMLC_PS_ROOT_PORT", "9099")) + 1))
    n = max(1, int(os.environ.get("DMLC_NUM_SERVER", "0") or 0))
    if "DMLC_PS_SERVER_PORT" not in os.environ:
        n = 1  # embedded single-server mode
    return [(host, base + i) for i in range(n)]


def _check_bind_optin(root_host):
    """Multi-host cluster without an explicit bind opt-in: binding
    loopback would strand remote workers in retry loops, and binding
    wide open silently would expose the pickle transport (= RCE).
    Fail fast with the knob to turn."""
    if (root_host not in ("127.0.0.1", "localhost", "::1")
            and not os.environ.get("DMLC_PS_BIND_HOST")):
        raise MXNetError(
            f"dist server for cluster root {root_host!r} needs "
            "DMLC_PS_BIND_HOST set (e.g. 0.0.0.0). The PS pickle "
            "transport is remote-code-execution to anything that can "
            "reach the port, so non-loopback binding is opt-in; the "
            "launcher must network-isolate the PS ports.")


def ensure_local_server():
    """Start the in-process server on worker 0 when no dedicated server
    role exists. Idempotent.  Binds loopback unless DMLC_PS_BIND_HOST
    opts in — and fails fast (rather than stranding remote workers)
    when the cluster root is non-loopback and no opt-in is set."""
    global _server_singleton
    if _server_singleton is None:
        (host, port), = server_endpoints()
        _check_bind_optin(host)
        _server_singleton = PSServer(port).start()
    return _server_singleton


def run_server():
    """Blocking server loop for a dedicated DMLC_ROLE=server process
    (ref: MXKVStoreRunServer / kvstore_server.py).

    The PS is a host-side role: its optimizer updates run on XLA:CPU.
    Pinning the platform here also keeps the server off the TPU tunnel
    (a server process must come up even when the accelerator is wedged).
    """
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized by the embedding process
    host, base = server_endpoints()[0]
    my_id = int(os.environ.get("DMLC_SERVER_ID", "0"))
    _check_bind_optin(host)
    srv = PSServer(base + my_id).start()
    srv._thread.join()
