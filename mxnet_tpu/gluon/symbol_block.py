"""SymbolBlock: wrap a Symbol graph as a Gluon block.

Ref: python/mxnet/gluon/block.py SymbolBlock — the class that loads an
exported ``model-symbol.json`` + ``model-0000.params`` pair back into
Gluon (``SymbolBlock.imports``), or wraps any hand-built symbol as a
layer inside a larger net.  This is checkpoint mechanism 2 of SURVEY §5
closing the loop: export → imports round-trips through the on-disk
format, including across frontends.

TPU-native realization: forward feeds the parameter/input arrays into
the shared symbolic graph evaluator (``_eval_graph`` — the emit-HLO
pass), dispatched through the imperative ``invoke`` layer so the whole
graph runs as ONE jitted XLA computation with autograd tape support.
Because the evaluator is pure and traceable, a SymbolBlock nested in a
hybridized parent simply inlines into the parent's computation.
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _wrap
from .block import HybridBlock


def _as_name_list(inputs):
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    names = []
    for i in inputs:
        if isinstance(i, str):
            names.append(i)
        else:  # Symbol variable
            if i.list_arguments() != [getattr(i, "name", None)]:
                raise MXNetError(
                    "SymbolBlock inputs must be variable symbols "
                    f"(sym.var), got {i}")
            names.append(i.name)
    return names


class SymbolBlock(HybridBlock):
    """Construct a block from a symbol graph and its input variables.

    Ref: gluon.SymbolBlock(outputs, inputs, params=None).
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol import symbol as sym_ns

        if isinstance(outputs, (list, tuple)):
            outputs = (outputs[0] if len(outputs) == 1
                       else sym_ns.Group(list(outputs)))
        self._out_sym = outputs
        self._in_names = _as_name_list(inputs)
        arg_names = outputs.list_arguments()
        aux_names = outputs.list_auxiliary_states()
        for name in self._in_names:
            if name not in arg_names and name not in aux_names:
                raise MXNetError(
                    f"input {name!r} is not a variable of the symbol "
                    f"(arguments: {arg_names})")
        # every non-input variable becomes a Parameter of this block;
        # aux states (BN moving stats) are non-differentiable, matching
        # the reference's grad_req='null' treatment in SymbolBlock
        self._arg_params = [n for n in arg_names if n not in self._in_names]
        self._aux_params = [n for n in aux_names if n not in self._in_names]
        for name in self._arg_params:
            self.params.get(name, allow_deferred_init=True)
        for name in self._aux_params:
            self.params.get(name, grad_req="null",
                            allow_deferred_init=True,
                            differentiable=False)

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        """Load an exported model (ref: SymbolBlock.imports).

        ``symbol_file``/``param_file`` are the artifacts written by
        ``HybridBlock.export`` (model-symbol.json, model-0000.params).
        """
        from ..context import current_context
        from ..ndarray import ndarray as _nd
        from ..symbol import symbol as sym_ns

        out = sym_ns.load(symbol_file)
        block = SymbolBlock(out, [sym_ns.var(n) for n in
                                  ([input_names] if isinstance(input_names,
                                                               str)
                                   else list(input_names))])
        if param_file is not None:
            loaded = _nd.load(param_file)
            # strip the arg:/aux: prefixes of the export format
            flat = {}
            for k, v in loaded.items():
                flat[k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                     else k] = v
            ctx_list = [ctx] if ctx is not None and not isinstance(
                ctx, (list, tuple)) else (ctx or [current_context()])
            params = block.collect_params()
            for name, p in params.items():
                if name in flat:
                    v = flat[name]
                    p.shape = v.shape
                    p.initialize(ctx=ctx_list)
                    p.set_data(v)
                else:
                    raise MXNetError(
                        f"parameter {name!r} missing from {param_file}")
        return block

    # SymbolBlock has no hybrid_forward — forward evaluates the graph.
    def forward(self, *args):
        from ..symbol.symbol import Symbol

        if args and isinstance(args[0], Symbol):
            return self._compose_symbolic(args)
        if len(args) != len(self._in_names):
            raise MXNetError(
                f"SymbolBlock expects {len(self._in_names)} inputs "
                f"({self._in_names}), got {len(args)}")
        for a in args:
            if not isinstance(a, NDArray):
                raise MXNetError("SymbolBlock.forward expects NDArrays")
        return self._eval(args)

    def _eval(self, args):
        from .. import autograd
        from .. import random as _random
        from .._imperative import invoke
        from ..symbol.symbol import _graph_fn, _n_outputs
        from .block import is_tracing

        ctx = None if is_tracing() else args[0].context
        params = {}
        for name in self._arg_params + self._aux_params:
            p = self.params.get(name)
            try:
                params[name] = p.data(ctx) if ctx is not None else p.data()
            except MXNetError:
                params[name] = p.data()
        feed = dict(zip(self._in_names, args))
        feed.update(params)
        train = autograd.is_training()
        fn = _graph_fn(self._out_sym, train)
        names = tuple(sorted(feed))
        key_nd = _wrap(_random.next_key())
        res = invoke(fn, key_nd, *[feed[n] for n in names], _names=names)
        if not isinstance(res, tuple):
            res = (res,)
        n_out = _n_outputs(self._out_sym._node)
        outs, aux_new = res[:n_out], res[n_out:]
        # write back mutated aux states (BN moving stats), same contract
        # as CachedOp: only outside jit tracing (inside a parent's trace
        # the parent's own aux plumbing owns the write-back)
        for name, new in zip(self._out_sym.list_auxiliary_states(),
                             aux_new):
            if name in params and params[name]._data is not new._data:
                params[name]._data = new._data
        return outs[0] if n_out == 1 else list(outs)

    def _compose_symbolic(self, args):
        """Symbol inputs: splice this block's graph into the caller's
        (the reference composes via Symbol.__call__)."""
        from ..symbol.symbol import Symbol, _Node, _topo_order

        if len(args) != len(self._in_names):
            raise MXNetError(
                f"SymbolBlock expects {len(self._in_names)} inputs "
                f"({self._in_names}), got {len(args)}")
        for a in args:
            if not isinstance(a, Symbol):
                raise MXNetError(
                    "SymbolBlock symbolic compose expects all-Symbol "
                    f"inputs, got {type(a).__name__}")
        sub = dict(zip(self._in_names, [a._node for a in args]))
        memo = {}
        for n in _topo_order([self._out_sym._node]):
            if n.op is None and n.name in sub:
                memo[id(n)] = sub[n.name]
            elif n.op is None:
                memo[id(n)] = n  # shared parameter variable
            else:
                memo[id(n)] = _Node(
                    n.op, n.name, dict(n.attrs),
                    [(memo[id(s)], oi) for s, oi in n.inputs])
        return Symbol(memo[id(self._out_sym._node)], self._out_sym._index)

    def __repr__(self):
        return (f"SymbolBlock(inputs={self._in_names}, "
                f"outputs={self._out_sym.list_outputs()})")
