"""Operator numeric checks against NumPy oracles
(ref: tests/python/unittest/test_operator.py — numpy reference impls +
finite-difference gradient checking)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def _rand(*shape):
    return np.random.RandomState(42).rand(*shape).astype(np.float32)


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar-output f at x."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp, xm = x.copy(), x.copy()
        xp[idx] += eps
        xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def check_grad(op, x_np, analytic_tol=1e-2):
    x = nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = op(x).sum()
    y.backward()
    num = numeric_grad(lambda a: float(op(nd.array(a)).sum().asscalar()), x_np)
    assert np.allclose(x.grad.asnumpy(), num, atol=analytic_tol,
                       rtol=analytic_tol), (x.grad.asnumpy(), num)


@pytest.mark.parametrize("name,np_fn", [
    ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
    ("square", np.square), ("tanh", np.tanh), ("sigmoid",
                                               lambda x: 1 / (1 + np.exp(-x))),
    ("relu", lambda x: np.maximum(x, 0)),
])
def test_unary_forward(name, np_fn):
    x_np = _rand(3, 4) + 0.5
    y = getattr(nd, name)(nd.array(x_np))
    assert np.allclose(y.asnumpy(), np_fn(x_np), atol=1e-5)


@pytest.mark.parametrize("name", ["exp", "log", "sqrt", "square", "tanh",
                                  "sigmoid"])
def test_unary_grad(name):
    check_grad(getattr(nd, name), _rand(2, 3) + 0.5)


def test_fully_connected():
    x, w, b = _rand(4, 10), _rand(5, 10), _rand(5)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                            num_hidden=5)
    assert np.allclose(out.asnumpy(), x @ w.T + b, atol=1e-5)
    out2 = nd.FullyConnected(nd.array(x), nd.array(w), no_bias=True,
                             num_hidden=5)
    assert np.allclose(out2.asnumpy(), x @ w.T, atol=1e-5)


def test_fully_connected_flatten():
    x = _rand(2, 3, 4)
    w = _rand(6, 12)
    out = nd.FullyConnected(nd.array(x), nd.array(w), no_bias=True,
                            num_hidden=6)
    assert np.allclose(out.asnumpy(), x.reshape(2, 12) @ w.T, atol=1e-5)


def test_convolution_vs_naive():
    x = _rand(2, 3, 8, 8)
    w = _rand(4, 3, 3, 3)
    b = _rand(4)
    out = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=(3, 3), num_filter=4)
    # naive conv oracle
    ref = np.zeros((2, 4, 6, 6), np.float32)
    for n in range(2):
        for f in range(4):
            for i in range(6):
                for j in range(6):
                    ref[n, f, i, j] = (x[n, :, i:i + 3, j:j + 3] * w[f]).sum() + b[f]
    assert np.allclose(out.asnumpy(), ref, atol=1e-4)


def test_convolution_stride_pad_group():
    x = _rand(1, 4, 8, 8)
    w = _rand(8, 2, 3, 3)
    out = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=8, stride=(2, 2), pad=(1, 1),
                         num_group=2, no_bias=True)
    assert out.shape == (1, 8, 4, 4)


def test_conv_grad():
    x_np, w_np = _rand(1, 2, 5, 5), _rand(3, 2, 3, 3)
    w = nd.array(w_np)

    def op(x):
        return nd.Convolution(x, w, kernel=(3, 3), num_filter=3, no_bias=True)

    check_grad(op, x_np)


def test_pooling():
    x = _rand(1, 1, 4, 4)
    mx_max = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                        pool_type="max")
    ref = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    assert np.allclose(mx_max.asnumpy(), ref)
    mx_avg = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                        pool_type="avg")
    ref_avg = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
    assert np.allclose(mx_avg.asnumpy(), ref_avg, atol=1e-6)
    glob = nd.Pooling(nd.array(x), global_pool=True, pool_type="avg")
    assert glob.shape == (1, 1, 1, 1)
    assert np.isclose(glob.asscalar(), x.mean(), atol=1e-6)


def test_pooling_full_convention():
    x = _rand(1, 1, 5, 5)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max", pooling_convention="full")
    assert out.shape == (1, 1, 3, 3)


def test_batchnorm_train_eval():
    x = _rand(4, 3, 2, 2) * 5
    gamma, beta = np.ones(3, np.float32), np.zeros(3, np.float32)
    mm, mv = np.zeros(3, np.float32), np.ones(3, np.float32)
    g, b_, m, v = (nd.array(gamma), nd.array(beta), nd.array(mm), nd.array(mv))
    with autograd.record():
        y = nd.BatchNorm(nd.array(x), g, b_, m, v, fix_gamma=False,
                         momentum=0.9, eps=1e-5)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    ref = (x - mean[None, :, None, None]) / np.sqrt(var[None, :, None, None]
                                                    + 1e-5)
    assert np.allclose(y.asnumpy(), ref, atol=1e-4)
    # moving stats updated in place
    assert np.allclose(m.asnumpy(), 0.1 * mean, atol=1e-5)
    assert np.allclose(v.asnumpy(), 0.9 + 0.1 * var, atol=1e-5)
    # eval mode uses moving stats
    y2 = nd.BatchNorm(nd.array(x), g, b_, m, v, fix_gamma=False, eps=1e-5)
    ref2 = (x - m.asnumpy()[None, :, None, None]) / np.sqrt(
        v.asnumpy()[None, :, None, None] + 1e-5)
    assert np.allclose(y2.asnumpy(), ref2, atol=1e-4)


def test_layernorm():
    x = _rand(2, 5)
    g, b = np.ones(5, np.float32), np.zeros(5, np.float32)
    y = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b))
    ref = (x - x.mean(1, keepdims=True)) / np.sqrt(x.var(1, keepdims=True)
                                                   + 1e-5)
    assert np.allclose(y.asnumpy(), ref, atol=1e-5)


def test_softmax_ops():
    x = _rand(3, 4)
    s = nd.softmax(nd.array(x), axis=-1)
    e = np.exp(x - x.max(-1, keepdims=True))
    assert np.allclose(s.asnumpy(), e / e.sum(-1, keepdims=True), atol=1e-6)
    ls = nd.log_softmax(nd.array(x), axis=-1)
    assert np.allclose(ls.asnumpy(), np.log(e / e.sum(-1, keepdims=True)),
                       atol=1e-5)


def test_activation_op():
    x = _rand(2, 3) - 0.5
    for act, fn in [("relu", lambda v: np.maximum(v, 0)),
                    ("tanh", np.tanh),
                    ("sigmoid", lambda v: 1 / (1 + np.exp(-v)))]:
        y = nd.Activation(nd.array(x), act_type=act)
        assert np.allclose(y.asnumpy(), fn(x), atol=1e-5)


def test_leaky_relu_variants():
    x = nd.array([-1.0, 1.0])
    y = nd.LeakyReLU(x, act_type="leaky", slope=0.1)
    assert np.allclose(y.asnumpy(), [-0.1, 1.0], atol=1e-6)
    e = nd.LeakyReLU(x, act_type="elu", slope=1.0)
    assert np.allclose(e.asnumpy(), [np.expm1(-1), 1.0], atol=1e-6)


def test_embedding():
    w = _rand(10, 4)
    idx = nd.array([1, 3, 1], dtype="int32")
    out = nd.Embedding(idx, nd.array(w), input_dim=10, output_dim=4)
    assert np.allclose(out.asnumpy(), w[[1, 3, 1]])


def test_batch_dot():
    a, b = _rand(2, 3, 4), _rand(2, 4, 5)
    out = nd.batch_dot(nd.array(a), nd.array(b))
    assert np.allclose(out.asnumpy(), a @ b, atol=1e-5)
    out_t = nd.batch_dot(nd.array(a), nd.array(_rand(2, 5, 4)),
                         transpose_b=True)
    assert out_t.shape == (2, 3, 5)


def test_topk_sort():
    x = nd.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]])
    idx = nd.topk(x, k=2)
    assert np.allclose(idx.asnumpy(), [[0, 2], [1, 2]])
    both = nd.topk(x, k=1, ret_typ="both")
    assert np.allclose(both[0].asnumpy(), [[3], [5]])
    s = nd.sort(x, axis=1)
    assert np.allclose(s.asnumpy(), [[1, 2, 3], [0, 4, 5]])


def test_sequence_ops():
    # (seq, batch, feat)
    x = nd.array(np.arange(24, dtype=np.float32).reshape(4, 2, 3))
    slen = nd.array([2, 4])
    m = nd.SequenceMask(x, slen, use_sequence_length=True, value=-1.0)
    out = m.asnumpy()
    assert np.allclose(out[2:, 0], -1)
    assert np.allclose(out[:, 1], x.asnumpy()[:, 1])
    last = nd.SequenceLast(x, slen, use_sequence_length=True)
    assert np.allclose(last.asnumpy()[0], x.asnumpy()[1, 0])
    assert np.allclose(last.asnumpy()[1], x.asnumpy()[3, 1])
    rev = nd.SequenceReverse(x, slen, use_sequence_length=True)
    assert np.allclose(rev.asnumpy()[0, 0], x.asnumpy()[1, 0])
    assert np.allclose(rev.asnumpy()[3, 0], x.asnumpy()[3, 0])


def test_rnn_op_lstm_shapes():
    from mxnet_tpu.ops.rnn import rnn_param_size

    T, N, I, H, L = 5, 3, 4, 6, 2
    psize = rnn_param_size(L, I, H, "lstm")
    params = nd.random.uniform(-0.1, 0.1, shape=(psize,))
    h0 = nd.zeros((L, N, H))
    c0 = nd.zeros((L, N, H))
    out, hn, cn = nd.RNN(nd.random.uniform(shape=(T, N, I)), params, h0, c0,
                         state_size=H, num_layers=L, mode="lstm")
    assert out.shape == (T, N, H)
    assert hn.shape == (L, N, H) and cn.shape == (L, N, H)


def test_rnn_op_gru_bidirectional():
    from mxnet_tpu.ops.rnn import rnn_param_size

    T, N, I, H = 4, 2, 3, 5
    psize = rnn_param_size(1, I, H, "gru", bidirectional=True)
    params = nd.random.uniform(-0.1, 0.1, shape=(psize,))
    h0 = nd.zeros((2, N, H))
    out, hn = nd.RNN(nd.random.uniform(shape=(T, N, I)), params, h0,
                     state_size=H, num_layers=1, mode="gru",
                     bidirectional=True)
    assert out.shape == (T, N, 2 * H)
    assert hn.shape == (2, N, H)


def test_lstm_matches_manual_cell():
    """Fused RNN vs hand-rolled LSTM steps (oracle test)."""
    from mxnet_tpu.ops.rnn import rnn_param_size

    rng = np.random.RandomState(0)
    T, N, I, H = 3, 2, 4, 5
    psize = rnn_param_size(1, I, H, "lstm")
    p = rng.uniform(-0.5, 0.5, psize).astype(np.float32)
    x = rng.uniform(-1, 1, (T, N, I)).astype(np.float32)
    out, hn, cn = nd.RNN(nd.array(x), nd.array(p), nd.zeros((1, N, H)),
                         nd.zeros((1, N, H)), state_size=H, num_layers=1,
                         mode="lstm")
    # manual oracle
    wi = p[: 4 * H * I].reshape(4 * H, I)
    wh = p[4 * H * I: 4 * H * I + 4 * H * H].reshape(4 * H, H)
    bi = p[4 * H * (I + H): 4 * H * (I + H) + 4 * H]
    bh = p[4 * H * (I + H) + 4 * H:]
    h = np.zeros((N, H), np.float32)
    c = np.zeros((N, H), np.float32)

    def sig(v):
        return 1 / (1 + np.exp(-v))

    for t in range(T):
        gates = x[t] @ wi.T + bi + h @ wh.T + bh
        i, f, g, o = np.split(gates, 4, axis=1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
    assert np.allclose(out.asnumpy()[-1], h, atol=1e-5)
    assert np.allclose(cn.asnumpy()[0], c, atol=1e-5)


def test_clip_where_pad():
    x = nd.array([[-2.0, 0.5, 3.0]])
    assert np.allclose(nd.clip(x, a_min=-1, a_max=1).asnumpy(),
                       [[-1, 0.5, 1]])
    p = nd.pad(nd.ones((1, 1, 2, 2)), mode="constant",
               pad_width=(0, 0, 0, 0, 1, 1, 1, 1), constant_value=9)
    assert p.shape == (1, 1, 4, 4)
    assert np.isclose(p.asnumpy()[0, 0, 0, 0], 9)


def test_gather_scatter_nd():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    idx = nd.array([[0, 1], [2, 0]], dtype="int32")  # (2 index dims, 2 pts)
    g = nd.gather_nd(x, idx)
    assert np.allclose(g.asnumpy(), [2.0, 3.0])
    s = nd.scatter_nd(nd.array([1.0, 5.0]), idx, shape=(2, 3))
    ref = np.zeros((2, 3))
    ref[0, 2], ref[1, 0] = 1, 5
    assert np.allclose(s.asnumpy(), ref)


def test_norm_ops():
    x = _rand(2, 8, 4, 4)
    il = nd.InstanceNorm(nd.array(x), nd.ones((8,)), nd.zeros((8,)))
    assert il.shape == x.shape
    l2 = nd.L2Normalization(nd.array(x))
    flat = x.reshape(2, -1)
    ref = x / np.sqrt((flat ** 2).sum(1) + 1e-10)[:, None, None, None]
    assert np.allclose(l2.asnumpy(), ref, atol=1e-5)


def test_random_ops_determinism():
    mx.random.seed(7)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    assert np.allclose(a, b)
    c = nd.random.normal(loc=2.0, scale=0.001, shape=(1000,)).asnumpy()
    assert abs(c.mean() - 2.0) < 0.01


def test_cast_stop_gradient():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = nd.stop_gradient(x * 2) + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [1.0])
    assert nd.cast(x, dtype="float16").dtype == np.float16


def test_reshape_magic_codes():
    """Ref matrix_op-inl.h InferReshapeShape: 0 copy, -1 infer, -2 rest,
    -3 merge, -4 split, reverse right-to-left (doc examples)."""
    x = nd.array(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert nd.reshape(x, shape=(0, 0, -1)).shape == (2, 3, 4)
    assert nd.reshape(x, shape=(-2,)).shape == (2, 3, 4)
    assert nd.reshape(x, shape=(0, -3)).shape == (2, 12)
    assert nd.reshape(x, shape=(-3, 4)).shape == (6, 4)
    assert nd.reshape(x, shape=(0, -4, 1, 3, 0)).shape == (2, 1, 3, 4)
    assert nd.reshape(x, shape=(0, -4, -1, 1, 0)).shape == (2, 3, 1, 4)
    # reverse doc example: (10, 5, 4) + (-1, 0) -> (50, 4)
    y = nd.zeros((10, 5, 4))
    assert y.reshape((-1, 0), reverse=True).shape == (50, 4)
    assert y.reshape((-1, 0)).shape == (40, 5)
    # values preserved, not just shapes
    out = nd.reshape(x, shape=(0, -3)).asnumpy()
    assert np.allclose(out, np.arange(24).reshape(2, 12))
    with pytest.raises(ValueError, match="invalid reshape code"):
        nd.reshape(x, shape=(-5,))
    with pytest.raises(ValueError, match="not divisible"):
        nd.reshape(x, shape=(-1, 5))
    with pytest.raises(ValueError, match="does not factor"):
        nd.reshape(x, shape=(0, -4, 2, -1, 0))
    with pytest.raises(ValueError, match="factors must be positive"):
        nd.reshape(x, shape=(0, -4, -1, 0, 0))


def test_softmax_output_full_semantics():
    """Ref softmax_output-inl.h: grad_scale, use_ignore, normalization
    ('null'/'batch'/'valid'), label smoothing."""
    x = nd.array(np.random.RandomState(0).rand(4, 3).astype(np.float32))
    label = nd.array([0, 1, -1, 1])
    p_np = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(1, keepdims=True)
    oh = np.zeros((4, 3), np.float32)
    for i, l in enumerate([0, 1, -1, 1]):
        if l >= 0:
            oh[i, l] = 1

    def grad_of(**kw):
        xx = nd.array(x.asnumpy())
        xx.attach_grad()
        with autograd.record():
            out = nd.SoftmaxOutput(xx, label, **kw)
        out.backward()
        return xx.grad.asnumpy()

    # ignore: row with label==ignore_label contributes zero gradient
    g = grad_of(use_ignore=True, ignore_label=-1)
    assert np.allclose(g[2], 0.0)
    assert np.allclose(g[0], p_np[0] - oh[0], atol=1e-5)
    # valid normalization divides by the non-ignored count (3)
    gv = grad_of(use_ignore=True, ignore_label=-1, normalization="valid")
    assert np.allclose(gv[0], (p_np[0] - oh[0]) / 3, atol=1e-5)
    # batch normalization divides by batch (4)
    gb = grad_of(normalization="batch")
    assert np.allclose(gb[1], (p_np[1] - oh[1]) / 4, atol=1e-5)
    # grad_scale multiplies
    gs = grad_of(grad_scale=0.5)
    assert np.allclose(gs[0], (p_np[0] - oh[0]) * 0.5, atol=1e-5)
    # label smoothing softens the one-hot target
    ga = grad_of(smooth_alpha=0.1)
    sm = oh * 0.9 + (1 - oh) * 0.05
    assert np.allclose(ga[0], p_np[0] - sm[0], atol=1e-5)


def test_regression_outputs_per_example_grads():
    """Ref regression_output-inl.h: grad = (pred - label) * grad_scale,
    per example — the 1/batch mean belongs to the optimizer's
    rescale_grad (Module folds it in automatically)."""
    x_np = np.random.RandomState(1).rand(4, 3).astype(np.float32)
    l_np = np.random.RandomState(2).rand(4, 3).astype(np.float32)

    def grad_of(op, **kw):
        x = nd.array(x_np)
        x.attach_grad()
        with autograd.record():
            out = op(x, nd.array(l_np), **kw)
        out.backward()
        return x.grad.asnumpy()

    g = grad_of(nd.LinearRegressionOutput)
    assert np.allclose(g, x_np - l_np, atol=1e-5)
    g2 = grad_of(nd.LinearRegressionOutput, grad_scale=0.5)
    assert np.allclose(g2, (x_np - l_np) * 0.5, atol=1e-5)
    p = 1 / (1 + np.exp(-x_np))
    gl = grad_of(nd.LogisticRegressionOutput)
    assert np.allclose(gl, p - l_np, atol=1e-5)
    gm = grad_of(nd.MAERegressionOutput)
    assert np.allclose(gm, np.sign(x_np - l_np), atol=1e-5)


def test_batch_norm_fused_matches_autodiff(monkeypatch):
    """The hand-written BN train fwd/bwd (one variadic reduce per
    direction; default on) must match the autodiff reference path
    (MXTPU_BN_FUSED=0) for out, moving stats, and all three grads."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops import nn as opnn

    rng = np.random.RandomState(42)
    x = jnp.asarray(rng.randn(4, 5, 6, 7).astype(np.float32))
    gamma = jnp.asarray(rng.rand(7).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(7).astype(np.float32))
    mm = jnp.asarray(rng.randn(7).astype(np.float32))
    mv = jnp.asarray(rng.rand(7).astype(np.float32) + 0.5)

    # loss = sum(out * w) with fixed random w: sum(out^2) would have an
    # analytically-zero dx (BN backward projects out the mean and the
    # xhat component of dy), making bf16 dx pure cancellation noise
    w = jnp.asarray(rng.randn(4, 5, 6, 7).astype(np.float32))

    def run(fused, dtype):
        monkeypatch.setenv("MXTPU_BN_FUSED", "1" if fused else "0")
        xd = x.astype(dtype)

        def f(xd, gamma, beta):
            out, nmm, nmv = opnn._k_batch_norm(
                xd, gamma, beta, mm, mv, eps=1e-3, momentum=0.9,
                fix_gamma=False, axis=-1, _train=True)
            return jnp.sum(out.astype(jnp.float32) * w), (nmm, nmv)

        (val, (nmm, nmv)), grads = jax.value_and_grad(
            f, argnums=(0, 1, 2), has_aux=True)(xd, gamma, beta)
        return val, nmm, nmv, grads

    # bf16: both paths round differently (fused keeps everything fp32
    # until the final dx cast; autodiff rounds per-op) — ~5% on sums
    for dtype in (jnp.float32, jnp.bfloat16):
        tol = 1e-5 if dtype == jnp.float32 else 8e-2
        va, ma, va_, ga = run(False, dtype)
        vb, mb, vb_, gb = run(True, dtype)
        assert np.allclose(float(va), float(vb), rtol=tol), (va, vb)
        assert np.allclose(np.asarray(ma), np.asarray(mb), atol=tol)
        assert np.allclose(np.asarray(va_), np.asarray(vb_), atol=tol)
        for a, b in zip(ga, gb):
            assert np.allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               atol=tol, rtol=tol), (a, b)
