"""Atomic filesystem commit primitives for checkpointing.

Ref: the reference repo's checkpoints (model.py save_checkpoint) write
files in place — a crash mid-write leaves a truncated ``.params`` that a
later load parses as garbage.  Production checkpointing (the
Orbax/TensorStore idiom assumed by the weight-update-sharding paper's
"periodic consistent snapshot") instead commits via write-to-temp →
fsync → atomic rename: a reader only ever observes an absent or a
complete file, never a partial one.
"""
from __future__ import annotations

import contextlib
import json
import os


def fsync_file(path):
    """Flush a written file's blocks to stable storage."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path):
    """Flush a directory entry (the rename itself) to stable storage.

    POSIX: durability of a rename requires an fsync on the PARENT
    directory; some filesystems refuse O_RDONLY fsync on dirs — best
    effort there (the rename is still atomic, just not yet durable).
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_file(path):
    """Yield a temp path to write; on success fsync + rename onto `path`.

    Usage::

        with atomic_file(fname) as tmp:
            writer(tmp)          # arbitrary writer, may crash freely
    """
    path = os.fspath(path)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        yield tmp
        fsync_file(tmp)
        os.replace(tmp, path)
        fsync_dir(os.path.dirname(path) or ".")
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def write_json(path, obj):
    """Durably write a JSON file (fsync'd; atomic when replacing)."""
    with atomic_file(path) as tmp:
        with open(tmp, "w") as f:
            json.dump(obj, f, indent=1, sort_keys=True)
