"""mxnet_tpu.parallel.spmd — multi-axis sharded whole-step training.

One compiled program per step over a named mesh (``'dcn','dp','mp','pp'``):

- :mod:`.mesh` — ``MXTPU_MESH_SHAPE`` spec parsing/validation
  (:func:`parse_mesh_shape`), mesh construction
  (:func:`make_spmd_mesh`), and the elastic-resize shape rule
  (:func:`pick_mesh_shape`);
- :mod:`.plan` — :class:`ShardingPlan`: auto PartitionSpec rules for
  Dense/Conv/attention params plus per-path glob overrides;
- :mod:`.lowering` — :class:`SpmdStepCompiler`: the GSPMD whole-step
  (params over 'mp', batch over 'dp', ZeRO state over both) as ONE
  pre-warmed ``jax.jit`` executable — Trainer routes here when
  ``mesh_shape`` is set;
- :mod:`.schedule` — the 'pp' axis: :func:`stage_partition`,
  :func:`pipeline_apply` (inference rotate schedule) and
  :class:`PipelineTrainStep` (microbatched training loop traced into
  one pjit'd program).

See docs/parallelism.md for the user-facing tour.
"""
from .mesh import (AXIS_ORDER, format_mesh_shape, make_spmd_mesh,
                   mesh_shape_from_env, model_axes, parse_mesh_shape,
                   pick_mesh_shape)
from .plan import ShardingPlan
from .lowering import SpmdStepCompiler
from .schedule import (PipelineTrainStep, default_microbatches,
                       pipeline_apply, stage_partition)

__all__ = [
    "AXIS_ORDER",
    "parse_mesh_shape",
    "format_mesh_shape",
    "mesh_shape_from_env",
    "make_spmd_mesh",
    "model_axes",
    "pick_mesh_shape",
    "ShardingPlan",
    "SpmdStepCompiler",
    "stage_partition",
    "default_microbatches",
    "pipeline_apply",
    "PipelineTrainStep",
]
