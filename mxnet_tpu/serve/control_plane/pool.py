"""Process management for the control plane: spawn replica worker
processes, wait for their warm registration, and front them with the
Router as a cross-process pool.

:class:`ReplicaProcess` owns ONE worker subprocess: ``spawn()`` forks
it (behind the ``serve.replica.spawn`` fault point) and
``wait_registered()`` blocks until the worker's lease appears in the
shared registry dir — and because workers only register AFTER their
server's AOT-warming ``start()`` completed, a registered replica is a
WARM replica.  :class:`ControlPlane` packages that as a Router
``factory``: the router's existing eviction/warm-spare machinery now
replaces whole PROCESSES, and ``scale_up()/scale_down()`` expose the
admit/retire actuation surface the :class:`~.autoscale.Autoscaler`
drives.
"""
from __future__ import annotations

import subprocess
import time

from ... import engine
from ...base import MXNetError, getenv
from ...log import get_logger
from ..router import HEALTHY, Router
from . import _sec_bump
from .rpc import RemoteReplica, _registry

logger = get_logger("mxnet_tpu.serve.control_plane.pool")


class ReplicaSpawnError(MXNetError):
    """A replica worker process could not be spawned or never
    registered.  Worded as a transient condition on purpose: the
    supervisor/router retry machinery treats spawn hiccups as
    retry-with-pacing, not fatal."""

    def __init__(self, msg):
        super().__init__(
            f"replica process spawn failed (temporarily "
            f"unavailable): {msg}")


class ReplicaProcess:
    """One replica worker subprocess plus its registration handshake.

    The worker's stdout/stderr land in ``replica-<id>.log`` next to the
    registry markers, and the tail of that log is quoted in the
    :class:`ReplicaSpawnError` when the worker dies before registering
    — the difference between "spawn failed" and "spawn failed: port
    already in use" at 3am.
    """

    def __init__(self, argv, registry_dir, replica_id, *, env=None,
                 start_timeout=None, lease_sec=None):
        self.argv = list(argv)
        self.registry_dir = registry_dir
        self.replica_id = replica_id
        self._env = env          # None = inherit the parent environment
        self._start_timeout = float(
            getenv("CTRL_SPAWN_TIMEOUT_SEC", 120.0, float)
            if start_timeout is None else start_timeout)
        self._leases = _registry(registry_dir, lease_sec)
        self._log_path = self._leases.path_for(replica_id)[:-5] + ".log"
        self._proc = None

    def spawn(self):
        """Fork the worker (fault point ``serve.replica.spawn``)."""
        engine.fault_point("serve.replica.spawn",
                           replica=self.replica_id)
        try:
            with open(self._log_path, "ab") as log:
                self._proc = subprocess.Popen(
                    self.argv, stdout=log, stderr=subprocess.STDOUT,
                    env=self._env)
        except OSError as e:
            raise ReplicaSpawnError(
                f"exec {self.argv[0]!r} for replica "
                f"{self.replica_id}: {e}") from e
        logger.info("replica %s spawned as pid %d",
                    self.replica_id, self._proc.pid)
        return self

    def wait_registered(self, timeout=None):
        """Block until the worker's lease shows up (it warmed and is
        serving); returns the registration payload ``{"host", "port",
        "pid", "kind"}``."""
        if self._proc is None:
            raise MXNetError("wait_registered() before spawn()")
        deadline = time.monotonic() + (self._start_timeout
                                       if timeout is None else timeout)
        key = str(self.replica_id)
        while True:
            payload = self._leases.fresh().get(key)
            if payload is not None and payload.get("pid") == \
                    self._proc.pid:
                return payload
            if self._proc.poll() is not None:
                raise ReplicaSpawnError(
                    f"replica {self.replica_id} worker (pid "
                    f"{self._proc.pid}) exited with code "
                    f"{self._proc.returncode} before registering:"
                    f"\n{self._log_tail()}")
            if time.monotonic() > deadline:
                raise ReplicaSpawnError(
                    f"replica {self.replica_id} worker (pid "
                    f"{self._proc.pid}) did not register within "
                    f"{self._start_timeout}s:\n{self._log_tail()}")
            time.sleep(0.05)

    def _log_tail(self, n=2000):
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(f.tell() - n, 0))
                return f.read().decode(errors="replace")
        except OSError:
            return "<no worker log>"

    @property
    def pid(self):
        return self._proc.pid if self._proc is not None else None

    def alive(self):
        return self._proc is not None and self._proc.poll() is None

    def stop(self, timeout=10.0):
        """Terminate the worker (escalating to SIGKILL) and retire its
        lease so routers stop discovering a corpse."""
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(5.0)
        self._leases.retire(str(self.replica_id))

    def kill(self):
        """SIGKILL, no grace — the chaos path."""
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(5.0)


class ControlPlane:
    """A Router whose replicas are worker PROCESSES.

    ``worker_argv_fn(replica_id) -> argv`` describes how to launch one
    worker (typically ``python -m mxnet_tpu.serve.control_plane.worker
    --registry DIR --id N --seed S ...``; every worker gets the SAME
    seed so replicas are bit-identical and failover is invisible).
    The control plane wires that through a Router factory: spawn →
    wait for the warm registration → :class:`~.rpc.RemoteReplica` —
    so health eviction replaces dead processes with freshly spawned
    warm ones, and ``scale_up()/scale_down()`` grow and drain the pool
    through the router's admit/retire paths (never a cold compile, and
    never a dropped request, in traffic).
    """

    def __init__(self, worker_argv_fn, registry_dir, n_replicas, *,
                 capacity_hint=8, spawn_timeout=None, lease_sec=None,
                 spawn_env=None, **router_kwargs):
        self._argv_fn = worker_argv_fn
        self._registry_dir = registry_dir
        self._lease_sec = lease_sec
        self._spawn_timeout = spawn_timeout
        self._spawn_env = spawn_env
        self._capacity_hint = max(int(capacity_hint), 1)
        self._replicas = {}     # rid -> RemoteReplica (live members)
        self.router = Router(factory=self._spawn_replica,
                             n_replicas=int(n_replicas),
                             **router_kwargs)

    # -- the Router factory (also the eviction warm-spare path) -------------

    def _spawn_replica(self, rid):
        _sec_bump(spawns=1)
        proc = ReplicaProcess(self._argv_fn(rid), self._registry_dir,
                              rid, env=self._spawn_env,
                              start_timeout=self._spawn_timeout,
                              lease_sec=self._lease_sec)
        try:
            proc.spawn()
            payload = proc.wait_registered()
        except Exception:
            _sec_bump(spawn_failures=1)
            try:
                proc.stop(timeout=2.0)
            except Exception:  # noqa: BLE001 — the spawn error wins
                pass
            raise
        replica = RemoteReplica(payload["host"], payload["port"],
                                rid=rid, process=proc)
        self._replicas[rid] = replica
        return replica

    # -- lifecycle + the serving edge (delegates to the Router) -------------

    def start(self):
        self.router.start()
        _sec_bump(replicas=self.healthy_count())
        return self

    def shutdown(self, drain=True, timeout=None):
        try:
            self.router.shutdown(drain=drain, timeout=timeout)
        finally:
            for replica in list(self._replicas.values()):
                if replica.process is not None:
                    try:
                        replica.process.stop(timeout=5.0)
                    except Exception:  # noqa: BLE001 — teardown sweep
                        pass
            self._replicas.clear()
            _sec_bump(replicas=0)

    def submit(self, example, deadline_ms=None, tenant=None, **kw):
        return self.router.submit(example, deadline_ms=deadline_ms,
                                  tenant=tenant, **kw)

    def submit_stream(self, example, deadline_ms=None, tenant=None,
                      **kw):
        return self.router.submit_stream(example,
                                         deadline_ms=deadline_ms,
                                         tenant=tenant, **kw)

    def predict(self, example, deadline_ms=None, timeout=None,
                tenant=None, **kw):
        return self.router.predict(example, deadline_ms=deadline_ms,
                                   timeout=timeout, tenant=tenant, **kw)

    def stats(self, reset=False):
        return self.router.stats(reset=reset)

    def rolling_reload(self, step=None, timeout=60.0):
        return self.router.rolling_reload(step=step, timeout=timeout)

    # -- the autoscaler's actuation + sensing surface -----------------------

    def healthy_count(self):
        with self.router._lock:
            return sum(1 for r in self.router._pool
                       if r.state == HEALTHY)

    def replica_count(self):
        with self.router._lock:
            return len(self.router._pool)

    def load(self):
        """Mean replica occupancy in [0, ~1.5]: live queue depth over
        the per-replica ``capacity_hint``.  An unreachable replica
        reports a huge ``pending()`` (the router's scoring convention)
        and is clamped, so one dead worker reads as pressure, not as
        infinity."""
        with self.router._lock:
            reps = [r for r in self.router._pool
                    if r.state == HEALTHY]
        if not reps:
            return 0.0
        occ = [min(r.server.pending() / self._capacity_hint, 1.5)
               for r in reps]
        return sum(occ) / len(occ)

    def scale_up(self):
        """Admit one freshly spawned, warm replica; returns its id."""
        rep = self.router.admit()
        _sec_bump(replicas=self.replica_count())
        return rep.id

    def scale_down(self, timeout=60.0):
        """Drain and retire the least-loaded replica (the router
        refuses to take the last one); returns the retired id."""
        rid = self.router.retire(timeout=timeout)
        self._replicas.pop(rid, None)
        _sec_bump(retired=1, replicas=self.replica_count())
        return rid
