"""HybridBlock.export / SymbolBlock plumbing.

Ref: gluon/block.py HybridBlock.export → model-symbol.json +
model-0000.params, loadable by SymbolBlock.imports or the Module API —
the cross-frontend checkpoint format (SURVEY §5 checkpoint mechanism 2).
"""
from __future__ import annotations

from ..base import MXNetError
from ..ndarray import ndarray as _nd


def trace_block_to_symbol(block, num_inputs=1):
    """Run the block's forward with Symbol placeholders (the reference's
    _get_graph deferred trace)."""
    from . import symbol as sym

    inputs = [sym.var("data" if num_inputs == 1 else f"data{i}")
              for i in range(num_inputs)]
    params = block.collect_params()
    traced = []
    try:
        for p in params.values():
            p._traced_value = sym.var(p.name)
            traced.append(p)
        out = block.forward(*inputs)
    finally:
        for p in traced:
            p._traced_value = None
    if isinstance(out, (list, tuple)):
        if len(out) != 1:
            raise MXNetError("export of multi-output blocks: pick one head")
        out = out[0]
    return out, inputs


def export_block(block, path, epoch=0):
    """Write {path}-symbol.json + {path}-{epoch:04d}.params."""
    out_sym, _ = trace_block_to_symbol(block)
    sym_file = f"{path}-symbol.json"
    param_file = f"{path}-{epoch:04d}.params"
    out_sym.save(sym_file)
    arg_names = set(out_sym.list_arguments())
    aux_names = set(out_sym.list_auxiliary_states())
    payload = {}
    for name, p in block.collect_params().items():
        if p._data is None:
            continue
        if name in aux_names:
            payload["aux:" + name] = p.data()
        elif name in arg_names:
            payload["arg:" + name] = p.data()
    _nd.save(param_file, payload)
    return sym_file, param_file
