"""SPMD data-parallel training: the whole-step compiled path.

Ref: §3.3 of SURVEY.md — Trainer.step's kvstore push/pull pair becomes a
psum INSIDE the compiled step ("TPU translation: push+pull → psum over
ICI mesh axis inside the step computation; update_on_kvstore → sharded
optimizer state").  This module is that north-star path: ONE jitted XLA
computation per training step containing forward, backward, gradient
all-reduce (inserted by GSPMD from shardings) and the optimizer update,
with parameter donation for in-place update.

Works with any HybridBlock + gluon Loss + optimizer name.  The eager
Trainer (gluon/trainer.py) stays for MXNet-parity semantics; this class
is the performance path the bench uses.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import autograd
from .. import random as _random
from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _wrap
from . import mesh as mesh_mod


class DataParallelTrainer:
    """Compiled SPMD train step over a device mesh.

    batch axis sharded on 'dp'; params replicated (or tp-sharded via
    shard_params=True); grads psum'ed by GSPMD; optimizer fused in-step.
    """

    def __init__(self, block, loss_fn, optimizer="sgd", optimizer_params=None,
                 mesh=None, shard_params=False, donate=True):
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        opt_params = dict(optimizer_params or {})
        self._lr = float(opt_params.pop("learning_rate", 0.01))
        self._opt_name = optimizer
        self._opt_params = opt_params
        self._shard_params = shard_params
        self._donate = donate
        self._step_fn = None
        self._named = None      # [(name, Parameter)]
        self._params = None     # list of raw jax arrays (device, sharded)
        self._states = None     # optimizer state pytree per param
        self._t = 0

    # -- param plumbing ------------------------------------------------------

    def _gather_params(self, sample_x):
        if self.block._active is False:
            self.block.hybridize()
        # one eager probe to finish deferred init
        probe = self.block(sample_x)
        if isinstance(probe, (list, tuple)):
            for p in probe:
                p.wait_to_read()
        self._named = self.block._ordered_params()
        from jax.sharding import NamedSharding

        params = []
        self._param_shardings = []
        for name, p in self._named:
            raw = p.data()._data
            if self._shard_params:
                spec = mesh_mod.shard_param_spec(raw.shape, self.mesh)
            else:
                from jax.sharding import PartitionSpec

                spec = PartitionSpec()
            sh = NamedSharding(self.mesh, spec)
            params.append(jax.device_put(raw, sh))
            self._param_shardings.append(sh)
        self._params = tuple(params)
        self._trainable = [p.grad_req != "null" for _, p in self._named]

    def _init_opt_states(self):
        name = self._opt_name
        states = []
        # built below; stored as a tuple to keep jit pytree structure stable
        for raw, trainable in zip(self._params, self._trainable):
            if not trainable:
                states.append(None)
            elif name == "sgd" and self._opt_params.get("momentum", 0):
                states.append(jnp.zeros_like(raw))
            elif name in ("adam", "adamw", "lamb"):
                states.append((jnp.zeros_like(raw), jnp.zeros_like(raw)))
            elif name == "sgd":
                states.append(None)
            else:
                raise MXNetError(
                    f"DataParallelTrainer supports sgd/adam/adamw/lamb, "
                    f"got {name!r}")
        self._states = tuple(states)

    # -- the compiled step --------------------------------------------------

    def _build_step(self):
        from jax.sharding import NamedSharding, PartitionSpec

        block, loss_block = self.block, self.loss_fn
        named = self._named
        trainable = self._trainable
        opt_name = self._opt_name
        op = dict(self._opt_params)
        momentum = float(op.get("momentum", 0.0))
        wd = float(op.get("wd", 0.0))
        beta1 = float(op.get("beta1", 0.9))
        beta2 = float(op.get("beta2", 0.999))
        eps = float(op.get("epsilon", 1e-8))
        clip = op.get("clip_gradient")

        from ..gluon.block import _tracing

        def forward_loss(param_raws, x_raw, y_raw, key):
            params = [p for _, p in named]
            old = [p._traced_value for p in params]
            prev = getattr(_tracing, "active", False)
            _tracing.active = True
            tok = _random.push_trace_key(key)
            wrappers = [_wrap(r) for r in param_raws]
            try:
                for p, w in zip(params, wrappers):
                    p._traced_value = w
                with autograd.pause(train_mode=True):
                    out = block.forward(_wrap(x_raw))
                    loss = loss_block(out, _wrap(y_raw))
            finally:
                _random.pop_trace_key(tok)
                _tracing.active = prev
                for p, o in zip(params, old):
                    p._traced_value = o
            # aux side effects (BatchNorm moving stats): wrappers mutated
            # in place during forward; surface as aux outputs
            aux = tuple(w._data for w in wrappers)
            return jnp.mean(loss._data), aux

        def apply_opt(raw, g, state, lr, t):
            if clip is not None:
                g = jnp.clip(g, -clip, clip)
            if opt_name == "sgd":
                g = g + wd * raw
                if momentum:
                    new_m = momentum * state - lr * g
                    return raw + new_m, new_m
                return raw - lr * g, None
            m, v = state
            if opt_name != "adamw":
                g = g + wd * raw
            nm = beta1 * m + (1 - beta1) * g
            nv = beta2 * v + (1 - beta2) * jnp.square(g)
            mhat = nm / (1 - beta1 ** t)
            vhat = nv / (1 - beta2 ** t)
            upd = mhat / (jnp.sqrt(vhat) + eps)
            if opt_name == "adamw":
                upd = upd + wd * raw
            if opt_name == "lamb":
                wn = jnp.linalg.norm(raw)
                un = jnp.linalg.norm(upd)
                ratio = jnp.where((wn > 0) & (un > 0), wn / un, 1.0)
                upd = ratio * upd
            return raw - lr * upd, (nm, nv)

        def step(params, states, x, y, key, lr, t):
            (loss, aux), grads = jax.value_and_grad(
                forward_loss, has_aux=True)(params, x, y, key)
            new_params, new_states = [], []
            for raw, g, st, tr, new_raw in zip(params, grads, states,
                                               trainable, aux):
                if not tr:
                    # non-trainable: take the aux-updated value (BN stats)
                    new_params.append(new_raw)
                    new_states.append(st)
                else:
                    nw, ns = apply_opt(raw, g, st, lr, t)
                    new_params.append(nw)
                    new_states.append(ns)
            return loss, tuple(new_params), tuple(new_states)

        data_sh = mesh_mod.batch_sharding(self.mesh)
        repl = NamedSharding(self.mesh, PartitionSpec())
        in_shardings = (tuple(self._param_shardings),
                        None, data_sh, data_sh, repl, repl, repl)
        # pin param output shardings to the input layout, else GSPMD may
        # pick a different layout for returned params and the next call's
        # in_shardings check rejects them
        out_shardings = (repl, tuple(self._param_shardings), None)
        donate = (0, 1) if self._donate else ()
        self._step_fn = jax.jit(step, in_shardings=in_shardings,
                                out_shardings=out_shardings,
                                donate_argnums=donate)

    # -- public api ---------------------------------------------------------

    def step(self, x, y):
        """One compiled SPMD step; returns scalar loss NDArray."""
        if isinstance(x, NDArray):
            x = x._data
        if isinstance(y, NDArray):
            y = y._data
        if self._step_fn is None:
            self._gather_params(_wrap(jnp.asarray(x[:2])))
            self._init_opt_states()
            self._build_step()
        data_sh = mesh_mod.batch_sharding(self.mesh)
        x = jax.device_put(jnp.asarray(x), data_sh)
        y = jax.device_put(jnp.asarray(y), data_sh)
        self._t += 1
        key = _random.next_key()
        loss, self._params, self._states = self._step_fn(
            self._params, self._states, x, y, key,
            jnp.asarray(self._lr, jnp.float32),
            jnp.asarray(float(self._t), jnp.float32))
        return _wrap(loss)

    @property
    def learning_rate(self):
        return self._lr

    def set_learning_rate(self, lr):
        self._lr = float(lr)

    def sync_to_block(self):
        """Write the trained params back into the block's Parameters."""
        if self._named is None:
            return
        for (name, p), raw in zip(self._named, self._params):
            gathered = jax.device_get(raw)
            from ..ndarray import ndarray as _nd

            p.set_data(_nd.array(np.asarray(gathered)))
