#!/usr/bin/env python
"""Parse training logs into a per-epoch table (ref: tools/parse_log.py).

Consumes the Speedometer/validation log lines this framework (and the
reference) emit:

    Epoch[3] Batch [20]  Speed: 1234.56 samples/sec  accuracy=0.912
    Epoch[3] Validation-accuracy=0.901
    Epoch[3] Time cost=42.1

and prints one row per epoch: train metric, validation metric, mean
speed, time cost.  Output is TSV (or markdown with --format md).
"""
from __future__ import annotations

import argparse
import re
import sys
from collections import defaultdict

_RE_SPEED = re.compile(
    r"Epoch\[(\d+)\]\s+Batch\s*\[\d+\]\s+Speed:\s*([\d.]+)\s*samples/sec"
    r"(?:\s+(\S+)=([\d.eE+-]+))?")
_RE_VAL = re.compile(r"Epoch\[(\d+)\]\s+Validation-(\S+)=([\d.eE+-]+)")
_RE_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.]+)")
_RE_TRAIN = re.compile(r"Epoch\[(\d+)\]\s+Train-(\S+)=([\d.eE+-]+)")


def parse(lines):
    """Return {epoch: {"speed": [..], "train": x, "val": x, "time": x}}."""
    epochs = defaultdict(lambda: {"speed": [], "train": None, "val": None,
                                  "time": None, "metric": None})
    for line in lines:
        m = _RE_SPEED.search(line)
        if m:
            e = epochs[int(m.group(1))]
            e["speed"].append(float(m.group(2)))
            if m.group(3):
                e["train"] = float(m.group(4))
                e["metric"] = m.group(3)
            continue
        m = _RE_TRAIN.search(line)
        if m:
            e = epochs[int(m.group(1))]
            e["train"] = float(m.group(3))
            e["metric"] = m.group(2)
            continue
        m = _RE_VAL.search(line)
        if m:
            epochs[int(m.group(1))]["val"] = float(m.group(3))
            continue
        m = _RE_TIME.search(line)
        if m:
            epochs[int(m.group(1))]["time"] = float(m.group(2))
    return dict(epochs)


def render(epochs, fmt="tsv", out=sys.stdout):
    header = ["epoch", "train", "val", "speed(samples/s)", "time(s)"]
    rows = []
    for ep in sorted(epochs):
        e = epochs[ep]
        speed = (sum(e["speed"]) / len(e["speed"])) if e["speed"] else None
        fmtv = lambda v: "-" if v is None else (f"{v:.4f}"
                                                if isinstance(v, float)
                                                else str(v))
        rows.append([str(ep), fmtv(e["train"]), fmtv(e["val"]),
                     fmtv(speed), fmtv(e["time"])])
    if fmt == "md":
        out.write("| " + " | ".join(header) + " |\n")
        out.write("|" + "|".join(["---"] * len(header)) + "|\n")
        for r in rows:
            out.write("| " + " | ".join(r) + " |\n")
    else:
        out.write("\t".join(header) + "\n")
        for r in rows:
            out.write("\t".join(r) + "\n")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("logfile", help="training log file (- for stdin)")
    ap.add_argument("--format", choices=("tsv", "md"), default="tsv")
    args = ap.parse_args(argv)
    lines = (sys.stdin if args.logfile == "-"
             else open(args.logfile)).readlines()
    render(parse(lines), args.format)


if __name__ == "__main__":
    main()
