"""ImageRecordIter augmentation parity.

Ref: src/io/image_aug_default.cc — random-resized-crop with area/aspect
ranges, color (brightness/contrast/saturation/hue) jitter, inter_method
choices.  Exercised through BOTH the native C++ pipeline and the python
fallback path.
"""
import numpy as np
import pytest

from mxnet_tpu.io import ImageRecordIter, recordio
from mxnet_tpu.utils import native


def _make_rec(tmp_path, n=8, size=48, constant=None):
    rec = str(tmp_path / "a.rec")
    idx = str(tmp_path / "a.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        if constant is not None:
            img = np.full((size, size, 3), constant, np.uint8)
        else:
            img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 4), i, 0), img, quality=95,
            img_fmt=".jpg"))
    w.close()
    return rec


NATIVE = [False] + ([True] if native.load() is not None else [])


@pytest.mark.parametrize("use_native", NATIVE)
def test_random_resized_crop_shapes_and_variation(tmp_path, use_native):
    rec = _make_rec(tmp_path)
    it = ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 32, 32), batch_size=4,
        shuffle=False, rand_mirror=False, use_native=use_native,
        random_resized_crop=True, min_random_area=0.2,
        max_random_area=0.5, min_aspect_ratio=0.75,
        max_aspect_ratio=1.333, seed=3)
    b1 = next(iter(it)).data[0].asnumpy()
    assert b1.shape == (4, 3, 32, 32)
    it.reset()
    b2 = next(iter(it)).data[0].asnumpy()
    # different epoch -> different random crops of the same records
    assert not np.allclose(b1, b2)


@pytest.mark.parametrize("use_native", NATIVE)
def test_color_jitter_bounded_brightness(tmp_path, use_native):
    """Constant-gray images: brightness jitter scales the value within
    [1-b, 1+b]; no other channel coupling appears."""
    rec = _make_rec(tmp_path, constant=100)
    it = ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 32, 32), batch_size=8,
        use_native=use_native, brightness=0.4, seed=11)
    vals = next(iter(it)).data[0].asnumpy()
    per_img = vals.mean(axis=(1, 2, 3))
    assert (per_img >= 100 * 0.6 - 3).all(), per_img
    assert (per_img <= 100 * 1.4 + 3).all(), per_img
    # jitter draws differ across images
    assert per_img.std() > 0.5, per_img


@pytest.mark.parametrize("use_native", NATIVE)
def test_hue_saturation_preserve_gray(tmp_path, use_native):
    """Hue rotation and saturation jitter fix the gray axis — constant
    gray images pass through (within JPEG/rounding tolerance)."""
    rec = _make_rec(tmp_path, constant=128)
    it = ImageRecordIter(
        path_imgrec=rec, data_shape=(3, 32, 32), batch_size=8,
        use_native=use_native, saturation=0.5, random_h=90, seed=5)
    vals = next(iter(it)).data[0].asnumpy()
    assert np.abs(vals - 128).max() < 6.0, np.abs(vals - 128).max()


@pytest.mark.parametrize("use_native", NATIVE)
def test_augment_disabled_is_deterministic(tmp_path, use_native):
    rec = _make_rec(tmp_path)
    kw = dict(path_imgrec=rec, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False, rand_crop=False, rand_mirror=False,
              use_native=use_native)
    a = next(iter(ImageRecordIter(**kw))).data[0].asnumpy()
    b = next(iter(ImageRecordIter(**kw))).data[0].asnumpy()
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("use_native", NATIVE)
def test_inter_method_nearest_vs_bilinear(tmp_path, use_native):
    rec = _make_rec(tmp_path, size=40)
    out = {}
    for m in (0, 1):
        it = ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 24, 24), batch_size=4,
            shuffle=False, resize=24, use_native=use_native,
            inter_method=m)
        out[m] = next(iter(it)).data[0].asnumpy()
    assert not np.allclose(out[0], out[1])


def test_native_and_python_agree_statistically(tmp_path):
    """Same augmentation config through both pipelines: per-batch mean/
    std must land in the same ballpark (different RNG streams, so only
    statistics can match)."""
    if native.load() is None:
        pytest.skip("native lib unavailable")
    rec = _make_rec(tmp_path, n=16)
    stats = {}
    for use_native in (True, False):
        it = ImageRecordIter(
            path_imgrec=rec, data_shape=(3, 32, 32), batch_size=16,
            shuffle=False, random_resized_crop=True, min_random_area=0.5,
            max_random_area=1.0, min_aspect_ratio=0.8,
            max_aspect_ratio=1.25, brightness=0.2, contrast=0.2,
            saturation=0.2, use_native=use_native, seed=1)
        b = next(iter(it)).data[0].asnumpy()
        stats[use_native] = (b.mean(), b.std())
    assert abs(stats[True][0] - stats[False][0]) < 12.0, stats
    assert abs(stats[True][1] - stats[False][1]) < 12.0, stats


def test_vision_surface_fills():
    """CIFAR100, color-jitter transforms, composite augmenters."""
    import mxnet_tpu.gluon.data.vision.transforms as T
    import mxnet_tpu.image as img
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.data import vision

    ds = vision.CIFAR100(synthetic=True)
    x, y = ds[0]
    assert x.shape == (32, 32, 3) and 0 <= y < 100
    ds20 = vision.CIFAR100(synthetic=True, fine_label=False)
    assert all(0 <= ds20[i][1] < 20 for i in range(10))

    im = nd.array(np.random.RandomState(0).randint(
        0, 255, (8, 8, 3)).astype(np.uint8))
    for t in (T.RandomSaturation(0.3), T.RandomHue(0.2),
              T.RandomColorJitter(0.2, 0.2, 0.2, 0.1),
              T.RandomLighting(0.1)):
        out = t(im).asnumpy()
        assert np.isfinite(out).all() and out.min() >= 0
    assert T.CropResize(1, 1, 5, 5, size=4)(im).shape == (4, 4, 3)
    assert T.CropResize(1, 1, 5, 5)(im).shape == (5, 5, 3)
    # saturation=0 factor path: identity up to dtype
    sat = T.RandomSaturation(0.0)(im).asnumpy()
    assert np.allclose(sat, im.asnumpy().astype(np.float32), atol=1e-3)

    seq = img.SequentialAug([img.CastAug(), img.ColorNormalizeAug(
        nd.array(np.zeros(3, np.float32)),
        nd.array(np.ones(3, np.float32)))])
    assert seq(im).dtype == np.float32
    assert img.ForceResizeAug((4, 6))(im).shape == (6, 4, 3)
    assert img.RandomOrderAug([img.CastAug()])(im).dtype == np.float32


def test_image_augmenter_classes():
    """mx.image jitter/lighting/gray/sized-crop augmenters (ref:
    python/mxnet/image/image.py augmenter classes)."""
    import mxnet_tpu as mx
    from mxnet_tpu import image as img, nd

    np.random.seed(0)
    src = nd.array(np.random.uniform(0, 255, (32, 48, 3))
                   .astype(np.float32))

    out = img.BrightnessJitterAug(0.4)(src)
    assert out.shape == src.shape
    ratio = out.asnumpy() / np.maximum(src.asnumpy(), 1e-6)
    assert np.allclose(ratio, ratio.flat[0], atol=1e-4)  # pure scale

    out = img.ContrastJitterAug(0.4)(src)
    assert out.shape == src.shape and np.isfinite(out.asnumpy()).all()

    # saturation/hue jitter leave pure-gray images (R=G=B) gray
    gray = nd.array(np.tile(np.random.uniform(
        0, 255, (8, 8, 1)).astype(np.float32), (1, 1, 3)))
    # (the reference's YIQ/gray matrices are approximate — rows do not
    # sum exactly to 1 — so gray is preserved to ~1%, not exactly)
    for aug in (img.SaturationJitterAug(0.9), img.HueJitterAug(0.4)):
        o = aug(gray).asnumpy()
        assert np.allclose(o[..., 0], o[..., 1], rtol=0.01, atol=0.5)
        assert np.allclose(o[..., 1], o[..., 2], rtol=0.01, atol=0.5)

    out = img.RandomGrayAug(1.0)(src).asnumpy()
    assert np.allclose(out[..., 0], out[..., 1], atol=1e-3)

    out = img.LightingAug(0.1, np.array([55.46, 4.794, 1.148]),
                          np.eye(3))(src)
    assert out.shape == src.shape

    out = img.RandomSizedCropAug((24, 16), (0.5, 1.0),
                                 (0.75, 1.333))(src)
    assert out.shape == (16, 24, 3)

    jl = img.ColorJitterAug(0.3, 0.3, 0.3)
    assert jl(src).shape == src.shape

    augs = img.CreateAugmenter((3, 24, 24), rand_crop=True,
                               rand_resize=True, rand_mirror=True,
                               brightness=0.2, contrast=0.2,
                               saturation=0.2, hue=0.1, pca_noise=0.05,
                               rand_gray=0.2, mean=True, std=True)
    x = src
    for a in augs:
        x = a(x)
    assert x.shape == (24, 24, 3)


def test_mcc_metric():
    import mxnet_tpu as mx

    m = mx.metric.create("mcc")
    labels = mx.nd.array([1, 1, 0, 0, 1, 0])
    # logits: predict [1, 0, 0, 1, 1, 0]
    preds = mx.nd.array([[0.2, 0.8], [0.7, 0.3], [0.9, 0.1],
                         [0.4, 0.6], [0.1, 0.9], [0.8, 0.2]])
    m.update([labels], [preds])
    tp, fp, fn, tn = 2, 1, 1, 2
    want = (tp * tn - fp * fn) / np.sqrt(
        (tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    name, val = m.get()
    assert name == "mcc" and abs(val - want) < 1e-6
    m.reset()
    assert m.get()[1] == 0.0


def test_transforms_random_crop_and_gray():
    """gluon transforms RandomCrop (with padding) + RandomGray (ref:
    gluon/data/vision/transforms.py)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd
    from mxnet_tpu.gluon.data.vision import transforms as T

    rng = np.random.RandomState(0)
    x = nd.array(rng.randint(0, 255, (20, 24, 3)).astype(np.float32))
    assert T.RandomCrop(12)(x).shape == (12, 12, 3)
    padded = T.RandomCrop((24, 20), pad=4)(x)
    assert padded.shape == (20, 24, 3)
    g = T.RandomGray(1.0)(x)
    assert np.allclose(g.asnumpy()[..., 0], g.asnumpy()[..., 1], atol=1e-3)
    assert g.dtype == x.dtype  # no stochastic dtype change
    assert T.RandomGray(0.0)(x) is x  # skip path returns input untouched
    out = T.Compose([T.RandomCrop(16), T.RandomGray(0.5),
                     T.ToTensor()])(x)
    assert out.shape == (3, 16, 16)
    import pytest as _pytest

    with _pytest.raises(mx.MXNetError, match="smaller than crop"):
        T.RandomCrop(64)(x)
    u8 = nd.array(np.zeros((8, 8, 3)), dtype="uint8")
    assert T.RandomGray(1.0)(u8).dtype == u8.dtype
