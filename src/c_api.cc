// Flat C ABI over the framework surface (multi-frontend boundary).
//
// Ref (behavioral parity, not translation): include/mxnet/c_api.h +
// src/c_api/c_api.cc — the reference exposes ~400 flat MX* functions so
// Scala/R/Julia/C++ frontends can drive the same core the Python
// frontend uses.
//
// TPU-native inversion: the reference's core is C++ with Python layered
// on top; here the core orchestration layer is Python (driving XLA/PjRt,
// which are themselves native) with C++ subsystems below it (engine,
// storage, IO).  The multi-frontend boundary therefore EMBEDS the
// orchestrator: this library hosts a CPython interpreter and exposes the
// same flat, stateless C calling convention the reference does —
// handle-based NDArrays, string-keyed op invoke against the central op
// registry, MXTPUGetLastError error protocol.  Any language with a C FFI
// gets the full op surface (260+ registered ops), not a re-binding of a
// Python API.
//
// Thread contract: every entry point takes the GIL (PyGILState_Ensure),
// so frontends may call from any thread — same guarantee as the
// reference's engine-backed C API.
//
// Build: make lib/libmxtpu_capi.so   (links libpython3.x)
// Test: tests/test_capi.py compiles+runs a C driver against this ABI.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

std::mutex g_init_mu;
bool g_initialized = false;
PyObject* g_nd_module = nullptr;      // mxnet_tpu.ndarray.ops (op table)
PyObject* g_nd_array_fn = nullptr;    // mxnet_tpu.nd.array
PyObject* g_registry = nullptr;       // mxnet_tpu.ops.registry module

thread_local std::string tl_last_error;

// Cached storage for MXTPUListAllOpNames (stable pointers after init).
std::vector<std::string> g_op_names;
std::vector<const char*> g_op_name_ptrs;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  tl_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) tl_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

// dtype codes follow the reference's mshadow enum order
// (c_api: 0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64); we add 7=bf16.
const char* dtype_name(int code) {
  switch (code) {
    case 0: return "float32";
    case 1: return "float64";
    case 2: return "float16";
    case 3: return "uint8";
    case 4: return "int32";
    case 5: return "int8";
    case 6: return "int64";
    case 7: return "bfloat16";
    default: return nullptr;
  }
}

int dtype_code(const std::string& name) {
  if (name == "float32") return 0;
  if (name == "float64") return 1;
  if (name == "float16") return 2;
  if (name == "uint8") return 3;
  if (name == "int32") return 4;
  if (name == "int8") return 5;
  if (name == "int64") return 6;
  if (name == "bfloat16") return 7;
  return -1;
}

}  // namespace

MXTPU_API const char* MXTPUGetLastError() { return tl_last_error.c_str(); }

namespace {
// Import the framework and snapshot the op table (GIL held inside).
int init_body(const char* platform) {
  Gil gil;
  do {
    if (platform && platform[0]) {
      std::string code =
          "import jax\n"
          "jax.config.update('jax_platforms', '" + std::string(platform) +
          "')\n";
      if (PyRun_SimpleString(code.c_str()) != 0) {
        tl_last_error = "failed to pin jax platform";
        return -1;
      }
    }
    PyObject* mx = PyImport_ImportModule("mxnet_tpu");
    if (!mx) break;
    PyObject* nd = PyObject_GetAttrString(mx, "nd");
    Py_DECREF(mx);
    if (!nd) break;
    g_nd_module = nd;
    g_nd_array_fn = PyObject_GetAttrString(nd, "array");
    if (!g_nd_array_fn) break;
    g_registry = PyImport_ImportModule("mxnet_tpu.ops.registry");
    if (!g_registry) break;
    // snapshot op names once; pointers stay valid for the process life
    PyObject* keys = PyObject_CallMethod(g_registry, "list_ops", nullptr);
    if (!keys) break;
    PyObject* keys_list = PySequence_List(keys);
    Py_DECREF(keys);
    if (!keys_list) break;
    keys = keys_list;
    Py_ssize_t n = PyList_Size(keys);
    g_op_names.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char* c = PyUnicode_AsUTF8(PyList_GetItem(keys, i));
      if (c) g_op_names.emplace_back(c);
    }
    Py_DECREF(keys);
    for (auto& s : g_op_names) g_op_name_ptrs.push_back(s.c_str());
    g_initialized = true;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}
}  // namespace

// Initialize the embedded interpreter + framework. `platform` may be
// nullptr/"" (leave backend selection to the environment) or "cpu" /
// "tpu" to pin jax's platform before first device use.
MXTPU_API int MXTPUCAPIInit(const char* platform) {
  std::lock_guard<std::mutex> lk(g_init_mu);
  if (g_initialized) return 0;
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: the host app owns them
    we_initialized = true;
  }
  int rc = init_body(platform);
  if (we_initialized) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // other frontend threads' PyGILState_Ensure can proceed (the
    // any-thread contract in the header comment).
    PyEval_SaveThread();
  }
  return rc;
}

MXTPU_API int MXTPUListAllOpNames(int* out_size, const char*** out_array) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  *out_size = static_cast<int>(g_op_name_ptrs.size());
  *out_array = g_op_name_ptrs.data();
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray handles: an opaque pointer owning one PyObject* (the NDArray).
// ---------------------------------------------------------------------------

typedef void* NDArrayHandle;

MXTPU_API int MXTPUNDArrayCreate(const void* data, const int64_t* shape,
                                 int ndim, int dtype, const char* ctx,
                                 NDArrayHandle* out) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  const char* dt = dtype_name(dtype);
  if (!dt || ndim < 0 || ndim > 16) {
    tl_last_error = "bad dtype code or ndim";
    return -1;
  }
  Gil gil;
  do {
    // build via numpy: np.frombuffer(bytes, dtype).reshape(shape)
    PyObject* np = PyImport_ImportModule("numpy");
    if (!np) break;
    PyObject* npdt = PyObject_CallMethod(np, "dtype", "s", dt);
    if (!npdt) { Py_DECREF(np); break; }
    PyObject* itemsize_o = PyObject_GetAttrString(npdt, "itemsize");
    int64_t itemsize = PyLong_AsLongLong(itemsize_o);
    Py_DECREF(itemsize_o);
    int64_t count = 1;
    for (int i = 0; i < ndim; ++i) count *= shape[i];
    PyObject* buf = PyBytes_FromStringAndSize(
        static_cast<const char*>(data), count * itemsize);
    PyObject* flat = buf ? PyObject_CallMethod(np, "frombuffer", "OO",
                                               buf, npdt)
                         : nullptr;
    Py_XDECREF(buf);
    Py_DECREF(npdt);
    Py_DECREF(np);
    if (!flat) break;
    PyObject* shp = PyTuple_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
    PyObject* arr = PyObject_CallMethod(flat, "reshape", "O", shp);
    Py_DECREF(flat);
    Py_DECREF(shp);
    if (!arr) break;
    PyObject* kwargs = PyDict_New();
    if (ctx && ctx[0]) {
      PyObject* mx = PyImport_ImportModule("mxnet_tpu");
      PyObject* ctx_mod = mx ? PyObject_GetAttrString(mx, "Context")
                             : nullptr;
      Py_XDECREF(mx);
      if (!ctx_mod) { Py_DECREF(arr); Py_DECREF(kwargs); break; }
      // ctx strings look like "cpu(0)" / "xla(0)"
      std::string s(ctx);
      auto lp = s.find('(');
      std::string dev = s.substr(0, lp);
      int idx = lp == std::string::npos
                    ? 0
                    : std::atoi(s.c_str() + lp + 1);
      PyObject* ctx_obj = PyObject_CallFunction(ctx_mod, "si",
                                                dev.c_str(), idx);
      Py_DECREF(ctx_mod);
      if (!ctx_obj) { Py_DECREF(arr); Py_DECREF(kwargs); break; }
      PyDict_SetItemString(kwargs, "ctx", ctx_obj);
      Py_DECREF(ctx_obj);
    }
    PyObject* args = PyTuple_Pack(1, arr);
    PyObject* nd_arr = PyObject_Call(g_nd_array_fn, args, kwargs);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    Py_DECREF(arr);
    if (!nd_arr) break;
    *out = nd_arr;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUNDArrayFree(NDArrayHandle h) {
  if (!h) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(h));
  return 0;
}

MXTPU_API int MXTPUNDArrayGetShape(NDArrayHandle h, int* out_ndim,
                                   int64_t* out_shape /* >=16 slots */) {
  Gil gil;
  do {
    PyObject* shp = PyObject_GetAttrString(static_cast<PyObject*>(h),
                                           "shape");
    if (!shp) break;
    Py_ssize_t n = PyTuple_Size(shp);
    if (n > 16) { Py_DECREF(shp); tl_last_error = "ndim > 16"; return -1; }
    *out_ndim = static_cast<int>(n);
    for (Py_ssize_t i = 0; i < n; ++i)
      out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
    Py_DECREF(shp);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUNDArrayGetDType(NDArrayHandle h, int* out_dtype) {
  Gil gil;
  do {
    PyObject* dt = PyObject_GetAttrString(static_cast<PyObject*>(h),
                                          "dtype");
    if (!dt) break;
    PyObject* nm = PyObject_GetAttrString(dt, "name");
    if (!nm) {
      PyErr_Clear();  // the AttributeError must not leak into the
      nm = PyObject_Str(dt);  // fallback call or a later API call
    }
    Py_DECREF(dt);
    if (!nm) break;
    const char* c = PyUnicode_AsUTF8(nm);
    int code = c ? dtype_code(c) : -1;
    Py_DECREF(nm);
    if (code < 0) { tl_last_error = "unmapped dtype"; return -1; }
    *out_dtype = code;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// Synchronously copy device data out to a host buffer (asnumpy +
// memcpy) — the MXNDArraySyncCopyToCPU equivalent.
MXTPU_API int MXTPUNDArraySyncCopyToCPU(NDArrayHandle h, void* out,
                                        int64_t nbytes) {
  Gil gil;
  do {
    PyObject* npy = PyObject_CallMethod(static_cast<PyObject*>(h),
                                        "asnumpy", nullptr);
    if (!npy) break;
    PyObject* contig = PyObject_CallMethod(npy, "tobytes", nullptr);
    Py_DECREF(npy);
    if (!contig) break;
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(contig, &buf, &len) != 0) {
      Py_DECREF(contig);
      break;
    }
    if (len != nbytes) {
      Py_DECREF(contig);
      tl_last_error = "size mismatch: have " + std::to_string(len) +
                      " bytes, caller asked " + std::to_string(nbytes);
      return -1;
    }
    std::memcpy(out, buf, len);
    Py_DECREF(contig);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// ---------------------------------------------------------------------------
// Op invoke: the MXImperativeInvokeEx equivalent. Inputs are NDArray
// handles; kwargs arrive as parallel string arrays and are parsed as
// Python literals (so "(2, 2)" / "1e-5" / "'valid'" all work — same
// stringly-typed convention as the reference's C API).
// ---------------------------------------------------------------------------

MXTPU_API int MXTPUImperativeInvoke(const char* op_name,
                                    NDArrayHandle* inputs, int num_inputs,
                                    const char** keys, const char** vals,
                                    int num_kwargs,
                                    NDArrayHandle* outputs,
                                    int* num_outputs /* in: capacity */) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  Gil gil;
  do {
    PyObject* fn = PyObject_GetAttrString(g_nd_module, op_name);
    if (!fn) break;
    PyObject* args = PyTuple_New(num_inputs);
    for (int i = 0; i < num_inputs; ++i) {
      PyObject* o = static_cast<PyObject*>(inputs[i]);
      Py_INCREF(o);
      PyTuple_SET_ITEM(args, i, o);
    }
    PyObject* kwargs = PyDict_New();
    PyObject* ast = PyImport_ImportModule("ast");
    PyObject* lit = ast ? PyObject_GetAttrString(ast, "literal_eval")
                        : nullptr;
    Py_XDECREF(ast);
    bool kw_ok = true;
    for (int i = 0; i < num_kwargs && kw_ok; ++i) {
      PyObject* v = lit ? PyObject_CallFunction(lit, "s", vals[i])
                        : nullptr;
      if (!v) {  // not a literal -> pass the raw string (e.g. act_type)
        PyErr_Clear();
        v = PyUnicode_FromString(vals[i]);
      }
      if (!v || PyDict_SetItemString(kwargs, keys[i], v) != 0)
        kw_ok = false;
      Py_XDECREF(v);
    }
    Py_XDECREF(lit);
    PyObject* res = kw_ok ? PyObject_Call(fn, args, kwargs) : nullptr;
    Py_DECREF(fn);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    if (!res) break;
    // normalize to a list of outputs
    PyObject* res_list;
    if (PyTuple_Check(res) || PyList_Check(res)) {
      res_list = PySequence_Fast(res, "op outputs");
      Py_DECREF(res);
    } else {
      res_list = PyTuple_Pack(1, res);
      Py_DECREF(res);
    }
    if (!res_list) break;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(res_list);
    if (n > *num_outputs) {
      Py_DECREF(res_list);
      tl_last_error = "output capacity too small: need " +
                      std::to_string(n);
      return -1;
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* o = PySequence_Fast_GET_ITEM(res_list, i);
      Py_INCREF(o);
      outputs[i] = o;
    }
    *num_outputs = static_cast<int>(n);
    Py_DECREF(res_list);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// Block until all async work is visible (mx.nd.waitall).
MXTPU_API int MXTPUWaitAll() {
  Gil gil;
  do {
    PyObject* r = PyObject_CallMethod(g_nd_module, "waitall", nullptr);
    if (!r) break;
    Py_DECREF(r);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// Save/load NDArrays in the reference-compatible .params container
// (MXNDArraySave/Load equivalents; keys optional for save).
// Load a .params artifact (ref: MXNDArrayLoad). Each returned handle
// carries its own reference — free with MXTPUNDArrayFree (same caller-
// owned contract as the reference). The handle/name POINTER ARRAYS live
// in thread-local storage valid until the next Load on this thread;
// names is empty for list-form artifacts.
static thread_local std::vector<NDArrayHandle> tl_load_handles;
static thread_local std::vector<std::string> tl_load_names;
static thread_local std::vector<const char*> tl_load_name_ptrs;

MXTPU_API int MXTPUNDArrayLoad(const char* fname, int* out_size,
                               NDArrayHandle** out_handles,
                               int* out_name_size,
                               const char*** out_names) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  Gil gil;
  do {
    PyObject* r = PyObject_CallMethod(g_nd_module, "load", "s", fname);
    if (!r) break;
    tl_load_handles.clear();
    tl_load_names.clear();
    tl_load_name_ptrs.clear();
    if (PyDict_Check(r)) {
      PyObject *key, *val;
      Py_ssize_t pos = 0;
      while (PyDict_Next(r, &pos, &key, &val)) {
        const char* k = PyUnicode_AsUTF8(key);
        if (!k) {
          // drop the references taken so far — they would otherwise
          // leak when the next Load clears the vector without DECREF
          for (auto h : tl_load_handles)
            Py_DECREF(static_cast<PyObject*>(h));
          tl_load_handles.clear();
          tl_load_names.clear();
          Py_DECREF(r);
          goto fail;
        }
        tl_load_names.emplace_back(k);
        Py_INCREF(val);
        tl_load_handles.push_back(val);
      }
    } else {
      PyObject* seq = PySequence_Fast(r, "nd.load returned non-sequence");
      if (!seq) { Py_DECREF(r); break; }
      Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
      for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* o = PySequence_Fast_GET_ITEM(seq, i);
        Py_INCREF(o);
        tl_load_handles.push_back(o);
      }
      Py_DECREF(seq);
    }
    Py_DECREF(r);
    for (auto& s : tl_load_names) tl_load_name_ptrs.push_back(s.c_str());
    *out_size = static_cast<int>(tl_load_handles.size());
    *out_handles = tl_load_handles.data();
    *out_name_size = static_cast<int>(tl_load_name_ptrs.size());
    *out_names = tl_load_name_ptrs.data();
    return 0;
  } while (false);
fail:
  set_error_from_python();
  return -1;
}

// Op self-documentation through the C boundary (ref: MXSymbolGetAtomicSymbolInfo
// role): returns the rendered docstring for a registered op. The pointer is
// owned by a thread-local string valid until the next call on the thread.
static thread_local std::string tl_op_doc;

MXTPU_API int MXTPUOpGetDoc(const char* op_name, const char** out_doc) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  Gil gil;
  do {
    PyObject* entry = PyObject_CallMethod(g_registry, "get", "s", op_name);
    if (!entry) break;
    PyObject* doc = PyObject_CallMethod(entry, "build_doc", nullptr);
    Py_DECREF(entry);
    if (!doc) break;
    if (doc == Py_None) {  // undocumented op: legitimately empty
      tl_op_doc.clear();
    } else {
      const char* c = PyUnicode_AsUTF8(doc);
      if (!c) { Py_DECREF(doc); break; }
      tl_op_doc = c;
    }
    Py_DECREF(doc);
    *out_doc = tl_op_doc.c_str();
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUNDArraySave(const char* fname, NDArrayHandle* handles,
                               const char** keys, int num) {
  Gil gil;
  do {
    PyObject* d;
    if (keys) {
      d = PyDict_New();
      for (int i = 0; i < num; ++i)
        PyDict_SetItemString(d, keys[i],
                             static_cast<PyObject*>(handles[i]));
    } else {
      d = PyList_New(num);
      for (int i = 0; i < num; ++i) {
        PyObject* o = static_cast<PyObject*>(handles[i]);
        Py_INCREF(o);
        PyList_SET_ITEM(d, i, o);
      }
    }
    PyObject* r = PyObject_CallMethod(g_nd_module, "save", "sO", fname, d);
    Py_DECREF(d);
    if (!r) break;
    Py_DECREF(r);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}
