"""Host-staging storage manager — mx.storage.

Ref: include/mxnet/storage.h (`Storage::Get()->Alloc/Free/DirectFree`)
+ src/storage/pooled_storage_manager.h.  Native pool in src/storage.cc
(size-class free-lists over 64-byte-aligned host memory — the staging
tier for decode buffers / batch assembly / checkpoint IO; device HBM is
owned by PjRt and needs no framework pool).  Pure-Python fallback when
the native lib is unavailable.

Pool policy via MXTPU_MEM_POOL_TYPE: Pooled (default) | RoundedMany |
Unpooled (ref: MXNET_GPU_MEM_POOL_TYPE naive/round).
"""
from __future__ import annotations

import ctypes
import threading

import numpy as np

from .base import MXNetError, getenv
from .utils.libloader import load_native_lib

_POOL_TYPES = {"Pooled": 0, "Round": 0, "RoundedMany": 1, "Naive": 0,
               "Unpooled": 2}
_sigs_done = False


def _load_native():
    global _sigs_done
    lib = load_native_lib("libmxtpu_storage.so", "lib/libmxtpu_storage.so")
    if lib is None or _sigs_done:
        return lib
    _sigs_done = True
    lib.MXTPUStorageCreate.restype = ctypes.c_void_p
    lib.MXTPUStorageCreate.argtypes = [ctypes.c_int]
    lib.MXTPUStorageAlloc.restype = ctypes.c_void_p
    lib.MXTPUStorageAlloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    for name in ("MXTPUStorageFree", "MXTPUStorageDirectFree"):
        getattr(lib, name).restype = None
        getattr(lib, name).argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.MXTPUStorageReleaseAll.restype = None
    lib.MXTPUStorageReleaseAll.argtypes = [ctypes.c_void_p]
    lib.MXTPUStorageDestroy.restype = None
    lib.MXTPUStorageDestroy.argtypes = [ctypes.c_void_p]
    for name in ("MXTPUStorageUsedBytes", "MXTPUStoragePoolBytes",
                 "MXTPUStorageHits", "MXTPUStorageMisses"):
        getattr(lib, name).restype = ctypes.c_uint64
        getattr(lib, name).argtypes = [ctypes.c_void_p]
    return lib


class Handle:
    """An allocation handle (ref: Storage::Handle)."""

    __slots__ = ("ptr", "size", "_owner")

    def __init__(self, ptr, size, owner):
        self.ptr = ptr
        self.size = size
        self._owner = owner

    def as_numpy(self, dtype=np.uint8):
        """Zero-copy numpy view over the staged buffer.

        The view aliases pooled memory: it must NOT outlive
        ``Storage.free(handle)`` — after free the pool may recycle the
        block for a concurrent prefetch worker. Copy
        (``.copy()``) before freeing if the data must persist.
        """
        if self.ptr is None:
            raise MXNetError("as_numpy on a freed storage handle")
        dt = np.dtype(dtype)
        count = self.size // dt.itemsize
        buf = (ctypes.c_uint8 * self.size).from_address(self.ptr)
        return np.frombuffer(buf, dtype=dt, count=count)


class Storage:
    """Singleton staging allocator (ref: Storage::Get())."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lib = _load_native()
        pool_name = getenv("MEM_POOL_TYPE", "Pooled")
        if pool_name not in _POOL_TYPES:
            raise MXNetError(
                f"unknown MXTPU_MEM_POOL_TYPE {pool_name!r}; "
                f"one of {sorted(_POOL_TYPES)}")
        self._pool_type = _POOL_TYPES[pool_name]
        self._handle = (self._lib.MXTPUStorageCreate(self._pool_type)
                        if self._lib is not None else None)
        self._py_live = {}  # fallback: id -> np buffer

    @classmethod
    def get(cls):
        # first callers are concurrent prefetch workers — double-checked
        # lock so only one native pool ever exists
        if cls._instance is None:
            with cls._instance_lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance

    @property
    def native(self):
        return self._handle is not None

    def alloc(self, nbytes):
        if nbytes < 0:
            raise MXNetError("negative allocation size")
        if self._handle is not None:
            p = self._lib.MXTPUStorageAlloc(self._handle, nbytes)
            if not p and nbytes:
                raise MXNetError(f"staging allocation of {nbytes}B failed")
            return Handle(p, nbytes, self)
        buf = np.empty(nbytes, np.uint8)
        h = Handle(buf.ctypes.data, nbytes, self)
        self._py_live[h.ptr] = buf
        return h

    @staticmethod
    def _free_impl(handle, native_fn):
        # Always frees into the handle's OWNING pool — a handle may
        # outlive a Storage-instance swap (tests, reconfiguration), and
        # freeing a foreign pointer into another pool corrupts both
        # pools' accounting. Double-free is a no-op.
        owner = handle._owner
        if handle.ptr is None:
            return
        if owner._handle is not None:
            getattr(owner._lib, native_fn)(owner._handle, handle.ptr)
        else:
            owner._py_live.pop(handle.ptr, None)
        handle.ptr = None

    def free(self, handle):
        """Return to the pool (ref: Storage::Free)."""
        self._free_impl(handle, "MXTPUStorageFree")

    def direct_free(self, handle):
        """Bypass the pool (ref: Storage::DirectFree)."""
        self._free_impl(handle, "MXTPUStorageDirectFree")

    def release_all(self):
        if self._handle is not None:
            self._lib.MXTPUStorageReleaseAll(self._handle)

    def stats(self):
        if self._handle is None:
            return {"native": False,
                    "used_bytes": sum(b.nbytes
                                      for b in self._py_live.values())}
        return {
            "native": True,
            "used_bytes": self._lib.MXTPUStorageUsedBytes(self._handle),
            "pool_bytes": self._lib.MXTPUStoragePoolBytes(self._handle),
            "hits": self._lib.MXTPUStorageHits(self._handle),
            "misses": self._lib.MXTPUStorageMisses(self._handle),
        }
