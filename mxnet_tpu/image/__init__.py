"""Image module (ref: python/mxnet/image/)."""
from .image import (imdecode, imread, imresize, resize_short, fixed_crop,  # noqa: F401
                    center_crop, random_crop, color_normalize, Augmenter,
                    ResizeAug, CenterCropAug, RandomCropAug,
                    HorizontalFlipAug, CastAug, ColorNormalizeAug,
                    ForceResizeAug, SequentialAug, RandomOrderAug,
                    CreateAugmenter, ImageIter)
