"""`make int8-smoke`: compiled INT8 serving CI gate.

Trains a small classifier (real decision margins — the quality band is
meaningless on iid-random logits), calibrates + quantizes a twin with
`contrib.quantization.quantize_net`, and serves a request burst through
ModelServer plus a quantized decode burst through DecodeServer,
asserting the INT8 serving invariants from docs/quantization.md:

    graph.post_warmup_compiles == 0        (closed compile surface)
    dispatch delta == batches              (ModelServer: ONE executable
                                            per batch, nothing eager
                                            leaks into the hot path)
    dispatch delta == steps + admissions   (DecodeServer: one per token
                                            step, one per fused
                                            prefill+write group)
    argmax agreement vs fp32 >= 99%        (quality band, held-out data)
    compiled == eager BIT-identical        (one fused executable ==
                                            the per-op eager bytes)
    requant folds happened; activations travel int8 between layers
    int8_serve_batches booked in the `quantize` profiler section

Exit code 0 = every invariant holds.  Runs on the CPU backend so it is
chip-independent.
"""
import json
import sys


def _train_classifier(mx, nd, nn, steps=150):
    import numpy as np

    from mxnet_tpu import autograd, gluon

    rs = np.random.RandomState(0)
    centers = rs.randn(10, 32).astype(np.float32) * 2.0

    def sample(n, rng):
        y = rng.randint(0, 10, n)
        x = (centers[y] + rng.randn(n, 32)).astype(np.float32)
        return x, y.astype(np.int32)

    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu", in_units=32, flatten=False),
            nn.Dense(64, activation="relu", in_units=64, flatten=False),
            nn.Dense(10, in_units=64, flatten=False))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    for _ in range(steps):
        x, y = sample(64, rs)
        with autograd.record():
            loss = loss_fn(net(nd.array(x)), nd.array(y))
        loss.backward()
        trainer.step(64)
    return net, sample


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _imperative, nd, profiler, serve
    from mxnet_tpu.contrib import quantization as qz
    from mxnet_tpu.gluon import nn

    failures = []

    def check(name, cond):
        if not cond:
            failures.append(name)

    # ---- calibrate -> quantize ------------------------------------------
    fp32, sample = _train_classifier(mx, nd, nn)
    rs = np.random.RandomState(1)
    calib, _ = sample(256, rs)
    qz.reset_quantize_stats()
    qnet = qz.quantize_net(_copy_net(mx, nn, fp32), calib_data=calib,
                           calib_mode="entropy")
    st = qz.quantize_stats()
    check("3 layers quantized", st["layers_quantized"] == 3)
    check("requantize folds happened", st["requant_folds"] == 2)
    check("calibration cost visible", st["calib_ms"] > 0
          and st["calib_batches"] >= 1)

    # int8 boundary really is int8 between folded layers
    probe = qnet._layers[0](nd.array(calib[:2]))
    check("folded boundary carries int8", probe.dtype == np.int8)

    # compiled-vs-eager bit parity on one bucket-shaped batch
    xb, _ = sample(8, rs)
    eager = qnet(nd.array(xb)).asnumpy()
    qnet.hybridize()
    compiled = qnet(nd.array(xb)).asnumpy()
    check("compiled == eager bit-identical",
          np.array_equal(eager, compiled))

    # ---- quality band (held-out) ----------------------------------------
    xe, _ = sample(500, np.random.RandomState(42))
    ref = fp32(nd.array(xe)).asnumpy()
    got = qnet(nd.array(xe)).asnumpy()
    agreement = float((got.argmax(1) == ref.argmax(1)).mean())
    check("argmax agreement >= 99% vs fp32", agreement >= 0.99)

    # ---- serve burst through ModelServer --------------------------------
    attempts = 60
    spec = serve.BucketSpec(batch_sizes=(1, 2, 4, 8),
                            example_shape=(32,))
    srv = serve.ModelServer(qnet, spec, max_queue=attempts + 8,
                            linger_ms=1.0)
    srv.start()
    d0 = _imperative.device_dispatch_count()
    xs, _ = sample(attempts, rs)
    futs = [srv.submit(x) for x in xs]
    for f in futs:
        f.result(timeout=300)
    srv.drain()
    d1 = _imperative.device_dispatch_count()
    s = srv.stats()
    check("zero post-warmup compiles (ModelServer)",
          s["graph"]["post_warmup_compiles"] == 0)
    check("exact dispatch accounting: one executable per batch",
          d1 - d0 == s["batches"])
    check("every request served", s["served"] == s["submitted"]
          == attempts)
    check("accounting invariant",
          s["served"] + s["expired_deadline"] + s["failed"]
          + s["cancelled"] == s["submitted"])
    sec = profiler.sections().get("quantize", {})
    check("int8 batches booked in the quantize section",
          sec.get("int8_serve_batches") == s["batches"] > 0)

    # ---- INT8 decode path through DecodeServer --------------------------
    mx.random.seed(0)
    model = serve.TinyDecoder(vocab=64, embed=16, proj_block=True)
    model.initialize(mx.init.Xavier())
    dcal = rs.randint(0, 64, size=(16, 8)).astype(np.int32)

    def calib_fwd(m, x):
        b, length = x.shape
        m.prefill(x, nd.array(np.full(b, length, np.int32)))

    qz.quantize_net(model, calib_data=dcal, calib_mode="naive",
                    calib_forward=calib_fwd)
    dspec = serve.BucketSpec(batch_sizes=(1, 2, 4), example_shape=(None,),
                             lengths=(4, 8), dtype="int32")
    dsrv = serve.DecodeServer(model, dspec, max_slots=4, max_len=32,
                              max_queue=64)
    dsrv.start()
    d0 = _imperative.device_dispatch_count()
    handles = [dsrv.submit(
        rs.randint(0, 64, size=int(rs.randint(2, 9))).astype(np.int32),
        max_new_tokens=int(rs.randint(1, 10))) for _ in range(24)]
    for h in handles:
        h.result(timeout=300)
    dsrv.drain()
    d1 = _imperative.device_dispatch_count()
    ds = dsrv.stats()
    check("zero post-warmup compiles (DecodeServer)",
          ds["graph"]["post_warmup_compiles"] == 0)
    check("exact decode dispatch accounting (steps + admissions)",
          d1 - d0 == ds["decode_steps"] + ds["batches"])
    check("every decode request served",
          ds["served"] == ds["submitted"] == 24)

    print(json.dumps({
        "agreement_argmax": agreement,
        "serve": {k: s[k] for k in ("served", "batches",
                                    "batch_fill_ratio")},
        "serve_graph": s["graph"],
        "decode": {k: ds[k] for k in ("served", "decode_steps",
                                      "batches", "tokens")},
        "decode_graph": ds["graph"],
        "quantize_section": profiler.sections().get("quantize"),
    }, default=str))

    if failures:
        print("int8-smoke FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    print(f"int8-smoke OK: {s['served']} requests + {ds['tokens']} "
          f"decode tokens served int8, agreement={agreement}, "
          f"0 post-warmup compiles, "
          f"{s['batches']} + {ds['decode_steps'] + ds['batches']} "
          f"dispatches accounted")
    return 0


def _copy_net(mx, nn, src):
    """Fresh identical architecture carrying src's exact weights."""
    mx.random.seed(123)
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu", in_units=32, flatten=False),
            nn.Dense(64, activation="relu", in_units=64, flatten=False),
            nn.Dense(10, in_units=64, flatten=False))
    net.initialize(mx.init.Xavier())
    for dst_p, src_p in zip(net.collect_params().values(),
                            src.collect_params().values()):
        dst_p.set_data(src_p.data())
    return net


if __name__ == "__main__":
    sys.exit(main())
