"""Shared one-time Mosaic compile probes for Pallas kernel families.

Generalizes flash-attention's d%64 probe (VERDICT r3 #2): a Mosaic
lowering failure surfaces at jit-COMPILE time — after trace time, past
any trace-time try/except — so an un-lowerable kernel would error in
the middle of the user's train step with no runtime fallback.  Each
kernel family therefore compile-probes a tiny instance ONCE per
process on first TPU dispatch and falls back to the XLA path for the
process lifetime if the chip rejects the tiling.

Latching rules (same as _headdim64_allowed):
- compile succeeds            -> True forever;
- Mosaic rejection            -> False forever (the chip genuinely
                                 can't lower this family);
- transient failure (tunnel RPC, compile-service hiccup) -> False for
  THIS call, verdict stays open; strikes are counted at most once per
  60s window and 3 strikes latch False (persistent non-Mosaic failure,
  e.g. probe OOM, must not re-compile on every dispatch).

``MXTPU_PALLAS_<FAMILY>_OK=1/0`` forces the verdict either way.
Re-entrant calls (the probe's own compile dispatching back through the
family's gate) report True so the probe exercises the real Pallas path.
"""
from __future__ import annotations

import time

_state = {}


def _family(name):
    return _state.setdefault(name, {
        "verdict": None, "strikes": 0,
        "last_strike_t": float("-inf"), "probing": False})


def reset(name=None):
    """Test hook: forget cached verdicts."""
    if name is None:
        _state.clear()
    else:
        _state.pop(name, None)


def probe_ok(name, compile_fn, max_strikes=3, strike_spacing=60.0,
             _clock=time.monotonic):
    """True iff kernel family `name` may be dispatched on this backend.
    `compile_fn` must .lower().compile() tiny instances of every kernel
    in the family (fwd AND bwd, f32 and bf16)."""
    from ...base import getenv

    forced = getenv(f"PALLAS_{name.upper()}_OK", None, bool)
    if forced is not None:
        return forced
    st = _family(name)
    if st["probing"]:
        return True  # re-entrant: let the probe reach the pallas path
    if st["verdict"] is None:
        st["probing"] = True
        try:
            compile_fn()
            st["verdict"] = True
        except Exception as e:
            if "mosaic" in f"{type(e).__name__} {e}".lower():
                st["verdict"] = False
            else:
                now = _clock()
                if now - st["last_strike_t"] >= strike_spacing:
                    st["strikes"] += 1
                    st["last_strike_t"] = now
                if st["strikes"] >= max_strikes:
                    st["verdict"] = False
                return False
        finally:
            st["probing"] = False
    return st["verdict"]
