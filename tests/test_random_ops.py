"""Random op families (ref: src/operator/random/sample_op.cc,
multisample_op.h; test model: tests/python/unittest/test_random.py's
distribution-moment checks)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def setup_function(_):
    mx.random.seed(20)


def test_sym_random_source_nodes():
    """Zero-input random generators are valid graph sources; each
    forward re-draws (the executor threads a fresh key)."""
    s = mx.sym.random.uniform(low=0.0, high=2.0, shape=(4, 5)) \
        + mx.sym.Variable("b")
    ex = s.simple_bind(b=(4, 5))
    b = np.zeros((4, 5), np.float32)
    a1 = ex.forward(b=b)[0].asnumpy()
    a2 = ex.forward(b=b)[0].asnumpy()
    assert a1.shape == (4, 5)
    assert (a1 >= 0).all() and (a1 <= 2).all() and a1.std() > 0
    assert not np.allclose(a1, a2)
    n = mx.sym.random.normal(loc=3.0, scale=0.5, shape=(2000,))
    out = n.simple_bind().forward()[0].asnumpy()
    assert abs(out.mean() - 3.0) < 0.1 and abs(out.std() - 0.5) < 0.05


def test_nd_random_op_forms():
    u = nd.random_uniform(low=1.0, high=2.0, shape=(3, 3)).asnumpy()
    assert (u >= 1).all() and (u <= 2).all()
    r = nd.random_randint(low=0, high=5, shape=(100,)).asnumpy()
    assert r.dtype == np.int32 and r.min() >= 0 and r.max() < 5
    p = nd.random_poisson(lam=3.0, shape=(3000,)).asnumpy()
    assert abs(p.mean() - 3.0) < 0.3


def test_sample_uniform_normal_moments():
    lo = nd.array(np.array([0.0, 10.0], np.float32))
    hi = nd.array(np.array([1.0, 20.0], np.float32))
    s = nd.sample_uniform(lo, hi, shape=(2000,)).asnumpy()
    assert s.shape == (2, 2000)
    assert 0 <= s[0].min() and s[0].max() <= 1
    assert 10 <= s[1].min() and s[1].max() <= 20
    assert abs(s[0].mean() - 0.5) < 0.05 and abs(s[1].mean() - 15) < 0.5
    mu = nd.array(np.array([0.0, 5.0], np.float32))
    sg = nd.array(np.array([1.0, 0.1], np.float32))
    z = nd.sample_normal(mu, sg, shape=(4000,)).asnumpy()
    assert abs(z[0].mean()) < 0.1 and abs(z[0].std() - 1.0) < 0.08
    assert abs(z[1].mean() - 5.0) < 0.05 and abs(z[1].std() - 0.1) < 0.02


def test_sample_gamma_exponential_poisson_moments():
    g = nd.sample_gamma(nd.array(np.array([2.0], np.float32)),
                        nd.array(np.array([3.0], np.float32)),
                        shape=(5000,)).asnumpy()
    # mean alpha*beta = 6, var alpha*beta^2 = 18
    assert abs(g.mean() - 6.0) < 0.4 and abs(g.var() - 18.0) < 3.0
    e = nd.sample_exponential(nd.array(np.array([2.0], np.float32)),
                              shape=(5000,)).asnumpy()
    assert abs(e.mean() - 0.5) < 0.05
    p = nd.sample_poisson(nd.array(np.array([4.0], np.float32)),
                          shape=(5000,)).asnumpy()
    assert abs(p.mean() - 4.0) < 0.2 and abs(p.var() - 4.0) < 0.8


def test_sample_negative_binomial_moments():
    k, p = 3.0, 0.5
    nb = nd.sample_negative_binomial(
        nd.array(np.array([k], np.float32)),
        nd.array(np.array([p], np.float32)), shape=(6000,)).asnumpy()
    # mean k(1-p)/p = 3, var k(1-p)/p^2 = 6
    assert abs(nb.mean() - 3.0) < 0.3 and abs(nb.var() - 6.0) < 1.2
    assert (nb >= 0).all() and np.allclose(nb, np.round(nb))
    mu, alpha = 4.0, 0.25
    gnb = nd.sample_generalized_negative_binomial(
        nd.array(np.array([mu], np.float32)),
        nd.array(np.array([alpha], np.float32)), shape=(6000,)).asnumpy()
    # mean mu = 4, var mu + alpha*mu^2 = 8
    assert abs(gnb.mean() - 4.0) < 0.3 and abs(gnb.var() - 8.0) < 1.6


def test_sample_param_shape_broadcast():
    """Output is param_shape + shape (ref multisample_op.h)."""
    lam = nd.array(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    out = nd.sample_poisson(lam, shape=(50,))
    assert out.shape == (2, 2, 50)
    m = out.asnumpy().mean(axis=-1)
    assert np.allclose(m, [[1, 2], [3, 4]], atol=0.8)


def test_seed_determinism():
    mx.random.seed(123)
    a = nd.random_uniform(shape=(16,)).asnumpy()
    mx.random.seed(123)
    b = nd.random_uniform(shape=(16,)).asnumpy()
    assert np.allclose(a, b)


def test_random_source_feeds_nn_layer():
    """Param-shape inference must thread a key through needs_rng ops so
    layers fed by random sources backward-fill their weights."""
    s = mx.sym.FullyConnected(data=mx.sym.random.normal(shape=(32, 100)),
                              num_hidden=10)
    out = s.simple_bind().forward()[0]
    assert out.shape == (32, 10)


def test_random_namespace_parity_across_fronts():
    """Names eager code uses must survive hybridization: the namespace
    maps multinomial/shuffle/randn/bernoulli onto their registry ops."""
    p = mx.sym.random.multinomial(mx.sym.Variable("p"))
    out = p.simple_bind(p=(2, 3)).forward(
        p=np.array([[0, 1, 0], [1, 0, 0]], np.float32))[0].asnumpy()
    assert (out == [1, 0]).all()
    b = mx.sym.random.bernoulli(p=0.3, shape=(4000,)) \
        .simple_bind().forward()[0].asnumpy()
    assert abs(b.mean() - 0.3) < 0.05
    assert mx.sym.random.randn(3, 4).simple_bind().forward()[0] \
        .shape == (3, 4)
    so = mx.sym.random.shuffle(mx.sym.Variable("d")).simple_bind(
        d=(10,)).forward(d=np.arange(10, dtype=np.float32))[0].asnumpy()
    assert sorted(so.tolist()) == list(range(10))


def test_exponential_scale_lam_equivalent():
    e1 = mx.sym.random.exponential(scale=2.0, shape=(5000,)) \
        .simple_bind().forward()[0].asnumpy()
    e2 = mx.sym.random.exponential(lam=0.5, shape=(5000,)) \
        .simple_bind().forward()[0].asnumpy()
    assert abs(e1.mean() - 2.0) < 0.2 and abs(e2.mean() - 2.0) < 0.2
