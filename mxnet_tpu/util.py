"""Misc utilities (ref: python/mxnet/util.py).

The numpy-semantics toggles (`is_np_array`/`is_np_shape`) exist for
script compatibility and report the classic MXNet semantics this
framework implements (scalar tensors and zero-size arrays are
supported natively by jax, so the toggle is a constant).
"""
from __future__ import annotations

import functools
import os


def makedirs(d):
    """mkdir -p (ref: mx.util.makedirs)."""
    os.makedirs(os.path.expanduser(d), exist_ok=True)


def is_np_shape():
    return False


def is_np_array():
    return False


def use_np_shape(func):
    """No-op decorator: numpy-style shapes are always available."""
    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        return func(*args, **kwargs)
    return wrapper


use_np = use_np_shape
use_np_array = use_np_shape


def get_gpu_count():
    from .context import num_gpus

    return num_gpus()


def get_gpu_memory(dev_id=0):
    """Per-device (free, total) memory in bytes, via PjRt stats."""
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if dev_id >= len(devs):
        raise ValueError(f"no accelerator device {dev_id}")
    stats = devs[dev_id].memory_stats() or {}
    total = stats.get("bytes_limit", 0)
    free = total - stats.get("bytes_in_use", 0)
    return free, total
