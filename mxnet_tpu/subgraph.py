"""Subgraph/fusion API — pattern-match op chains, replace with fused ops.

Ref: src/operator/subgraph/subgraph_property.h + build_subgraph.cc and
the MKL-DNN fusion properties (src/operator/subgraph/mkldnn/ — the fork
owner's specialty: conv+bn+relu / fc+relu fusion for int8 and fp32).

TPU-native design: XLA already fuses elementwise chains into matmuls,
so this pass exists for substitutions the compiler CANNOT make —
swapping an op chain for a Pallas kernel (e.g. the attention qk→softmax
→valatt chain → flash attention) or for a semantically-rewritten fused
op.  The mechanism mirrors the reference: a ``SubgraphProperty``
declares a linear op pattern and a rewrite; ``build_subgraph`` (exposed
as ``Symbol.get_backend_symbol(backend)``) walks the graph and replaces
every match whose intermediates have no external consumers.
"""
from __future__ import annotations

import jax

from .base import MXNetError
from .ops import registry as _registry

_properties = {}  # backend -> [SubgraphProperty]


class SubgraphProperty:
    """One fusion rule (ref: SubgraphProperty / SgMKLDNNConvProperty).

    ``pattern``: list of op names forming a producer→consumer chain
    (each later op consumes the previous op's output as its first
    input).  ``fused_op``: the registered op that replaces the chain.
    ``attr_map(nodes)``: build the fused node's attrs from the matched
    nodes (first-to-last order).
    """

    pattern = ()
    fused_op = None

    def attr_map(self, nodes):
        merged = {}
        for n in nodes:
            merged.update(n.attrs)
        return merged

    def match_extra(self, nodes):
        """Optional extra predicate on the matched chain."""
        return True


def register_subgraph_property(backend, prop):
    _properties.setdefault(backend, []).append(prop)
    return prop


def get_subgraph_properties(backend):
    return list(_properties.get(backend, ()))


def build_subgraph(symbol, backend="TPU"):
    """Return a new Symbol with all registered fusions applied
    (ref: BuildSubgraph pass; exposed as get_backend_symbol)."""
    from .symbol.symbol import Symbol, _Node, _topo_order

    props = get_subgraph_properties(backend)
    if not props:
        return symbol
    heads = [symbol._node]
    order = _topo_order(heads)

    # consumer counts: an intermediate with >1 consumer cannot be fused
    # away (its value escapes the subgraph)
    consumers = {}
    for n in order:
        for src, _ in n.inputs:
            consumers[id(src)] = consumers.get(id(src), 0) + 1

    replaced = {}  # id(old node) -> new node

    def resolve(n):
        return replaced.get(id(n), n)

    for prop in props:
        pat = list(prop.pattern)
        if len(pat) < 2 or prop.fused_op is None:
            raise MXNetError("SubgraphProperty needs a >=2-op pattern "
                             "and a fused_op")
        for node in order:
            if node.op != pat[-1] or id(node) in replaced:
                continue
            # walk producer chain backwards through first inputs
            chain = [node]
            ok = True
            for want in reversed(pat[:-1]):
                prev = chain[0].inputs[0][0] if chain[0].inputs else None
                prev = resolve(prev) if prev is not None else None
                if (prev is None or prev.op != want
                        or id(prev) in replaced
                        or consumers.get(id(prev), 0) != 1):
                    ok = False
                    break
                chain.insert(0, prev)
            if not ok or not prop.match_extra(chain):
                continue
            # fused node: head-of-chain inputs + extra inputs of the
            # later ops (skipping the chain-internal edge)
            inputs = list(chain[0].inputs)
            for later in chain[1:]:
                inputs.extend(later.inputs[1:])
            fused = _Node(prop.fused_op, node.name + "_fused",
                          prop.attr_map(chain), inputs)
            replaced[id(node)] = fused

    if not replaced:
        return symbol

    # rebuild the graph bottom-up with replacements spliced in
    rebuilt = {}

    def rebuild(n):
        n = resolve(n)
        if id(n) in rebuilt:
            return rebuilt[id(n)]
        new = _Node(n.op, n.name, dict(n.attrs),
                    [(rebuild(src), oi) for src, oi in n.inputs])
        rebuilt[id(n)] = new
        return new

    return Symbol(rebuild(symbol._node), symbol._index)


# ---------------------------------------------------------------------------
# built-in TPU properties (ref: the MKL-DNN property set)


def _k_fc_act(data, weight, bias=None, *, num_hidden, act_type="relu",
              no_bias=False, flatten=True):
    from .ops.nn import _k_activation, _k_fully_connected

    out = _k_fully_connected(data, weight, bias, num_hidden=num_hidden,
                             no_bias=no_bias, flatten=flatten)
    return _k_activation(out, act_type=act_type)


_registry.register("_sg_tpu_fully_connected_act", _k_fc_act,
                   arg_names=("data", "weight", "bias"))


class FCActProperty(SubgraphProperty):
    """FullyConnected → Activation fusion (ref: SgMKLDNNFCProperty)."""

    pattern = ("FullyConnected", "Activation")
    fused_op = "_sg_tpu_fully_connected_act"


register_subgraph_property("TPU", FCActProperty())
