"""Basic Gluon layers (ref: python/mxnet/gluon/nn/basic_layers.py)."""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError


class Sequential(Block):
    """Stack of blocks executed in order (ref: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b, str(len(self._layers)))
            self._layers.append(b)

    def forward(self, x, *args):
        for b in self._layers:
            x = b(x)
        return x

    def __len__(self):
        return len(self._layers)

    def __getitem__(self, i):
        if isinstance(i, slice):
            net = type(self)()
            net.add(*self._layers[i])
            return net
        return self._layers[i]

    def __iter__(self):
        return iter(self._layers)


class HybridSequential(HybridBlock):
    """Hybridizable Sequential (ref: nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b, str(len(self._layers)))
            self._layers.append(b)

    def hybrid_forward(self, F, x):
        for b in self._layers:
            x = b(x)
        return x

    def __len__(self):
        return len(self._layers)

    def __getitem__(self, i):
        if isinstance(i, slice):
            net = type(self)()
            net.add(*self._layers[i])
            return net
        return self._layers[i]

    def __iter__(self):
        return iter(self._layers)


class Dense(HybridBlock):
    """Fully-connected layer (ref: nn.Dense → FullyConnected op)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._activation = activation
        self.weight = self.params.get(
            "weight", shape=(units, in_units), dtype=dtype,
            init=weight_initializer, allow_deferred_init=True)
        self.bias = self.params.get(
            "bias", shape=(units,), dtype=dtype, init=bias_initializer,
            allow_deferred_init=True) if use_bias else None

    def infer_shape(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._activation:
            out = F.Activation(out, act_type=self._activation)
        return out


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.flatten(x)


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act = activation

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act)


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer="zeros", in_channels=1, **kwargs):
        super().__init__(**kwargs)
        self.alpha = self.params.get("alpha", shape=(in_channels,),
                                     init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class Embedding(HybridBlock):
    """Ref: nn.Embedding → Embedding op."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self.weight = self.params.get(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class BatchNorm(HybridBlock):
    """Ref: nn.BatchNorm; moving stats are aux params updated by the op
    (in place when eager, as extra graph outputs when hybridized)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self.gamma = self.params.get(
            "gamma", grad_req="write" if scale else "null",
            shape=(in_channels,), init=gamma_initializer,
            allow_deferred_init=True)
        self.beta = self.params.get(
            "beta", grad_req="write" if center else "null",
            shape=(in_channels,), init=beta_initializer,
            allow_deferred_init=True)
        self.running_mean = self.params.get(
            "running_mean", grad_req="null", shape=(in_channels,),
            init=running_mean_initializer, allow_deferred_init=True,
            differentiable=False)
        self.running_var = self.params.get(
            "running_var", grad_req="null", shape=(in_channels,),
            init=running_variance_initializer, allow_deferred_init=True,
            differentiable=False)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           **self._kwargs)


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._eps = epsilon
        self._axis = axis
        self.gamma = self.params.get("gamma", shape=(in_channels,),
                                     init=gamma_initializer,
                                     allow_deferred_init=True)
        self.beta = self.params.get("beta", shape=(in_channels,),
                                    init=beta_initializer,
                                    allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = x.shape[self._axis]
        self.gamma.shape = (c,)
        self.beta.shape = (c,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._eps)


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._fn = function

    def forward(self, *args):
        return self._fn(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            name = function
            self._fn = lambda F, *a: getattr(F, name)(*a)
        else:
            self._fn = function

    def hybrid_forward(self, F, x, *args):
        return self._fn(F, x, *args)


class GroupNorm(HybridBlock):
    """Group normalization (ref: gluon.nn.GroupNorm over
    src/operator/nn/group_norm.cc)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True,
                 scale=True, beta_initializer="zeros",
                 gamma_initializer="ones", **kwargs):
        super().__init__(**kwargs)
        self._num_groups = num_groups
        self._eps = epsilon
        # affine params are PER GROUP (reference group_norm.cc);
        # center/scale=False: the param exists but stays fixed
        # (grad_req null) — the same convention BatchNorm uses above
        self.gamma = self.params.get("gamma", shape=(num_groups,),
                                     init=gamma_initializer,
                                     grad_req="write" if scale
                                     else "null")
        self.beta = self.params.get("beta", shape=(num_groups,),
                                    init=beta_initializer,
                                    grad_req="write" if center
                                    else "null")

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._eps)
