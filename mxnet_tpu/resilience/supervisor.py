"""Self-healing training supervisor (the recovery half of
``mxnet_tpu.resilience``).

``Supervisor.run(train_fn)`` owns the retry/resume policy for a long
training job.  ``train_fn(ctx)`` is written restartably — it restores
from ``ctx.manager.latest()`` when one exists, registers its preemption
state, and reports progress::

    mgr = checkpoint.CheckpointManager(ckpt_dir, keep_n=3)
    sup = resilience.Supervisor(mgr, on_preemption="resume")

    def train(ctx):
        net, trainer = build_model()
        pipe = build_pipeline()
        start = 0
        if ctx.manager.latest() is not None:   # (re)start: resume
            meta = ctx.manager.restore(params=net, trainer=trainer,
                                       pipeline=pipe)
            start = meta["step"] + 1
        state = {"step": start - 1}
        ctx.set_preemption_state(lambda: dict(
            step=state["step"], params=net, trainer=trainer,
            pipeline=pipe))
        for step, (x, y) in enumerate(pipe, start):
            ...forward/backward/trainer.step...
            state["step"] = step
            ctx.step_done(step, save=dict(params=net, trainer=trainer,
                                          pipeline=pipe))
        return net

    net = sup.run(train)

The supervisor classifies every failure that escapes ``train_fn``:

- **transient** (injected :class:`~.faults.TransientFault`, or real
  flaky-transport / UNAVAILABLE / RESOURCE_EXHAUSTED shapes) — bounded
  exponential backoff via :class:`~.retry.RetryPolicy`, then re-invoke
  ``train_fn`` (which resumes from the last committed checkpoint).
- **preemption** (SIGTERM) — the supervisor chains BEHIND the
  CheckpointManager's final-save hook, so by the time its handler
  raises :class:`Preempted` the final checkpoint is committed.
  ``on_preemption="resume"`` restarts in-process (chaos rehearsal);
  ``"exit"`` (default, the real-preemption behavior) writes a resume
  marker and re-raises as :class:`ResumeRequired`.
- **peer_death** (the ``parallel.dist`` bounded-failure-detector
  message) — with elastic resize on (``MXTPU_ELASTIC``, the default),
  a RESIZE event: survivors agree on the new world through
  ``dist.shrink`` (the ``dist.rendezvous`` fault point; the rendezvous
  itself is retried under the :class:`RetryPolicy`, so a transient
  failure inside the resize is not fatal), the process group re-forms
  at the surviving size, and ``train_fn`` is re-invoked — it reads
  ``ctx.world``, rebuilds its model/trainer/pipeline for the new mesh
  (exactly one recompile per resize event), and resumes from the
  latest checkpoint through the manager's resharding restore.  A
  surviving world below ``MXTPU_MIN_WORLD`` exits cleanly with the
  resume marker instead.  When the resize is unavailable (no
  dead-rank information in a single process, rendezvous failure, the
  coordinator itself died), fall back to the legacy path: attempt
  ``dist.reinit()`` where possible, else clean exit with the resume
  marker.
- **corrupt_checkpoint** — restart; ``CheckpointManager.restore()``
  itself falls back to the previous retained step (loudly).
- **watchdog** — no ``ctx.step_done`` within ``watchdog_sec``: the
  watchdog thread captures the stuck phase from the profiler's OPEN op
  scopes, books the diagnostic, and interrupts the training thread.
- **fatal** — everything else re-raises unchanged.

Non-transient recoveries consume the ``max_restarts`` budget
(``MXTPU_MAX_RESTARTS``); transient retries are bounded by the
:class:`RetryPolicy`.  Both budgets are per STALL POINT: steps
completed between two failures reset the counters, so a long job
absorbing an occasional flake never exhausts them while a loop stuck
at one step still trips the bound.  Every recovery is visible in the profiler's
``resilience`` section (restarts, retries by fault class,
fallback_restores, watchdog_fires, time_lost_ms).

Watchdog scope: it interrupts Python-level stalls (a stuck map fn, a
dead data source, host-side deadlock).  A hang inside a C-level XLA
collective does not take the interrupt — bound those with
``MXTPU_DIST_TIMEOUT``, which converts the hang into a diagnosable
(peer_death) error the supervisor classifies normally.
"""
from __future__ import annotations

import _thread
import os
import signal
import threading
import time

from .. import engine, profiler
from ..base import MXNetError, getenv
from ..log import get_logger
from ..telemetry import flight as _flight, tracer as _tracer
from . import stats as _stats
from .faults import TransientFault
from .retry import RetryPolicy

logger = get_logger("mxnet_tpu.resilience")

RESUME_MARKER = "RESUME.json"

_UNSET = object()  # train_fn-result sentinel (None is a valid result)


class Preempted(MXNetError):
    """SIGTERM landed; the final checkpoint (if registered) is saved."""


class WatchdogTimeout(MXNetError):
    """No training step completed within the watchdog window."""


class ResumeRequired(MXNetError):
    """Clean exit on an unrecoverable-in-process fault: a resume marker
    was written; restart the job to continue from the last
    checkpoint."""


# -- classification ---------------------------------------------------------

# dist._peer_death_msg's stable phrase — checked FIRST because transport
# errors ("connection reset") would otherwise look transient
_PEER_SIGNATURES = ("likely dead or partitioned",)
# restore()'s terminal errors mention corruption but restarting cannot
# fix them (every retained step already failed / the target was left
# partially mutated and needs a rebuild) — fatal, checked before the
# corrupt signatures
_UNRECOVERABLE_SIGNATURES = ("no retained checkpoint",
                             "every step failed",
                             "partially mutated")
_CORRUPT_SIGNATURES = ("corrupt", "truncated")
# RPC/transport blips between serving processes (the control plane's
# socket wire): RETRYABLE — the router re-dispatches on another replica
# instead of forwarding them as fatal.  Raw socket exceptions
# (ConnectionResetError & friends) are OSErrors, not MXNetErrors, so
# they get an isinstance check of their own; the text shapes cover
# errors re-wrapped by the wire layer.  Checked after the peer-death
# signature (a peer-death message may embed "connection reset") and
# BEFORE the corrupt signatures: a "truncated frame" is a dropped
# connection, not a corrupt checkpoint.
_NETWORK_EXC_TYPES = (ConnectionResetError, ConnectionRefusedError,
                      ConnectionAbortedError, BrokenPipeError)
_NETWORK_SIGNATURES = ("connection reset", "connection refused",
                       "connection aborted", "broken pipe",
                       "econnreset", "econnrefused", "epipe",
                       "truncated frame", "mid-frame",
                       "rpc connection")
# serving shed-don't-retry shapes, checked BEFORE the transient list:
# both read "try again later", but retrying an overloaded pool is
# exactly how a retry loop turns one slow replica into a meltdown, and
# an exhausted deadline budget cannot be retried back into existence
_OVERLOAD_SIGNATURES = ("queue full", "overloaded", "quota exceeded")
_DEADLINE_SIGNATURES = ("deadline exceeded", "deadline_exceeded",
                        "deadline passed", "deadline budget")
_TRANSIENT_SIGNATURES = (
    "injected transient", "transient", "unavailable",
    "resource exhausted", "resource_exhausted",
    "try again", "temporarily", "aborted",
)


def _serve_request_class(exc):
    """``'overloaded'`` / ``'deadline'`` for the serve tier's
    backpressure and deadline errors — resolved through ``sys.modules``
    so classifying never imports the serving stack (if serve was never
    imported, ``exc`` cannot be one of its exception types)."""
    import sys

    batcher = sys.modules.get(
        __package__.rsplit(".", 1)[0] + ".serve.batcher")
    if batcher is None:
        return None
    if isinstance(exc, batcher.ServerOverloadedError):
        return "overloaded"
    if isinstance(exc, batcher.DeadlineExceededError):
        return "deadline"
    return None


def classify(exc):
    """Map an exception to its fault class: ``'transient'``,
    ``'preemption'``, ``'peer_death'``, ``'corrupt_checkpoint'``,
    ``'watchdog'``, ``'overloaded'``, ``'deadline'``, ``'network'``
    or ``'fatal'``.

    ``overloaded`` (a full bounded queue / exhausted tenant quota) and
    ``deadline`` (an expired request budget) are NON-RETRYABLE: the
    right reaction is shedding load (or spilling to a less-loaded
    replica) and failing the request, respectively — a naive retry
    loop treating their "try again"-shaped messages as ``transient``
    burns its whole budget hammering a pool that needs the opposite.

    ``network`` (a dropped/refused connection, a truncated RPC frame)
    IS retryable — on a DIFFERENT path: the serve router re-dispatches
    the request to another replica, and the supervisor paces it like a
    transient.  It is distinct from ``peer_death``, whose collective
    cannot proceed without a world resize.
    """
    if isinstance(exc, TransientFault):
        return "transient"
    if isinstance(exc, Preempted):
        return "preemption"
    if isinstance(exc, WatchdogTimeout):
        return "watchdog"
    kind = _serve_request_class(exc)
    if kind is not None:
        return kind
    if isinstance(exc, _NETWORK_EXC_TYPES):
        return "network"
    if isinstance(exc, MXNetError):
        text = str(exc).lower()
        if any(s in text for s in _PEER_SIGNATURES):
            return "peer_death"
        if any(s in text for s in _UNRECOVERABLE_SIGNATURES):
            return "fatal"
        if any(s in text for s in _NETWORK_SIGNATURES):
            return "network"
        if any(s in text for s in _CORRUPT_SIGNATURES):
            return "corrupt_checkpoint"
        if any(s in text for s in _OVERLOAD_SIGNATURES):
            return "overloaded"
        if any(s in text for s in _DEADLINE_SIGNATURES):
            return "deadline"
        if any(s in text for s in _TRANSIENT_SIGNATURES):
            return "transient"
    return "fatal"


# -- the per-invocation context the train_fn sees ---------------------------


class RunContext:
    """Handed to ``train_fn`` on every (re)invocation.

    attempt : 0 on the first invocation, +1 per recovery
    manager : the supervisor's CheckpointManager (or None)
    world   : the CURRENT world size — after an elastic resize this is
              the surviving size; an elastic ``train_fn`` sizes its
              replica mesh / shard stages from it on every invocation
    dead_ranks : ranks lost so far (as numbered at failure time)
    resizes : elastic resize events so far
    """

    def __init__(self, supervisor):
        self._sup = supervisor
        self.attempt = 0

    @property
    def manager(self):
        return self._sup.manager

    @property
    def world(self):
        return self._sup._world

    @property
    def dead_ranks(self):
        return list(self._sup._dead_ranks)

    @property
    def resizes(self):
        return self._sup._resizes

    def mesh_shape(self, world=None):
        """The spmd mesh shape THIS invocation should train at, or
        None when no multi-axis mesh is configured.

        Starts from the configured ``MXTPU_MESH_SHAPE`` (which the
        supervisor rewrites after every elastic resize) and — as a
        belt-and-braces guard against a train_fn that sized its own
        world — re-applies :func:`parallel.spmd.mesh.pick_mesh_shape`
        to the current ``world``: model axes ('mp'/'pp') are preserved,
        data axes shrink to the survivors.  An elastic spmd train_fn
        builds its Trainer as ``Trainer(..., mesh_shape=
        ctx.mesh_shape())`` on every invocation."""
        from ..parallel.spmd.mesh import (mesh_shape_from_env,
                                          pick_mesh_shape)

        shape = mesh_shape_from_env()
        if shape is None:
            return None
        world = self.world if world is None else int(world)
        if world:
            shape = pick_mesh_shape(shape, world)
        return shape

    def step_done(self, step, save=None):
        """Report step ``step`` completed: feeds the progress watchdog,
        fires the ``train.step`` fault point (where kill-at-step-N chaos
        plans trigger), and — when ``save`` kwargs are given — commits a
        checkpoint through the manager (``save`` maps to
        ``manager.save(step, **save)``)."""
        step = int(step)
        self._sup._last_step = step
        self._sup._progress = time.monotonic()
        engine.fault_point("train.step", step=step)
        if save is not None:
            if self._sup.manager is None:
                raise MXNetError(
                    "step_done(save=...) needs a CheckpointManager: "
                    "construct the Supervisor with manager=")
            self._sup.manager.save(step, **save)

    def heartbeat(self):
        """Feed the progress watchdog WITHOUT completing a step — for
        legitimately step-free phases longer than ``watchdog_sec``
        (initial restore of a huge model, end-of-run export/eval), so
        they are not misread as a stall."""
        self._sup._progress = time.monotonic()

    def set_preemption_state(self, state_fn):
        """Register the final-save state provider: ``state_fn()``
        returns ``manager.save`` kwargs (``step``, ``params``, ...)
        capturing everything a resume needs, or None to skip.  A
        SIGTERM then commits that state synchronously before the
        supervisor sees :class:`Preempted`."""
        self._sup._state_fn = state_fn


# -- the supervisor ---------------------------------------------------------


class Supervisor:
    """Retry/resume policy owner for a supervised training job.

    manager       : CheckpointManager used for final saves, restores and
                    the resume marker (optional but required for
                    ``step_done(save=...)`` / preemption saves)
    max_restarts  : non-transient recovery budget
                    (``MXTPU_MAX_RESTARTS``, default 3)
    watchdog_sec  : progress watchdog window; 0 disables
                    (``MXTPU_WATCHDOG_SEC``, default 0)
    retry         : :class:`RetryPolicy` bounding transient retries
    on_preemption : ``'exit'`` (default — write the resume marker and
                    raise :class:`ResumeRequired`, the real-preemption
                    behavior) or ``'resume'`` (restart in-process, the
                    chaos-rehearsal behavior)
    elastic       : treat classified peer death as a RESIZE event —
                    shrink the world to the survivors and resume from
                    the latest checkpoint via the resharding restore
                    (``MXTPU_ELASTIC``, default on; degrades to the
                    legacy reinit-or-exit path when the resize is
                    unavailable)
    world         : the job's world size; defaults to
                    ``dist.num_workers()``.  Chaos rehearsals pass the
                    VIRTUAL world here (replica contexts standing in
                    for ranks on the virtual device mesh)
    min_world     : never resize below this many ranks — exit with the
                    resume marker instead (``MXTPU_MIN_WORLD``,
                    default 1)
    rendezvous_timeout : elastic survivor-rendezvous bound, seconds
                    (``MXTPU_RENDEZVOUS_TIMEOUT``, default 60)
    """

    def __init__(self, manager=None, *, max_restarts=None,
                 watchdog_sec=None, retry=None, on_preemption="exit",
                 resume_marker=None, elastic=None, world=None,
                 min_world=None, rendezvous_timeout=None):
        if on_preemption not in ("exit", "resume"):
            raise MXNetError(
                f"on_preemption must be 'exit' or 'resume', got "
                f"{on_preemption!r}")
        self.manager = manager
        self.max_restarts = int(getenv("MAX_RESTARTS", 3, int)
                                if max_restarts is None else max_restarts)
        self.watchdog_sec = float(getenv("WATCHDOG_SEC", 0.0, float)
                                  if watchdog_sec is None else watchdog_sec)
        self.retry = retry if retry is not None else RetryPolicy()
        self.on_preemption = on_preemption
        self.resume_marker = resume_marker or (
            os.path.join(manager.directory, RESUME_MARKER)
            if manager is not None else RESUME_MARKER)
        self.elastic = bool(getenv("ELASTIC", True, bool)
                            if elastic is None else elastic)
        self.min_world = int(getenv("MIN_WORLD", 1, int)
                             if min_world is None else min_world)
        self.rendezvous_timeout = float(
            getenv("RENDEZVOUS_TIMEOUT", 60.0, float)
            if rendezvous_timeout is None else rendezvous_timeout)
        self._world = None if world is None else int(world)
        self._dead_ranks = []
        self._resizes = 0
        self._state_fn = None
        self._last_step = None
        self._progress = time.monotonic()
        self._watchdog_diag = None
        self._orig_sigterm = None

    # -- the loop ------------------------------------------------------------

    def run(self, train_fn):
        """Drive ``train_fn(ctx)`` to completion through failures;
        returns its result.  See the module docstring for the policy per
        fault class."""
        is_main = threading.current_thread() is threading.main_thread()
        if self._world is None:
            from ..parallel import dist

            try:
                self._world = dist.num_workers()
            except Exception:  # jax not initialized: single process
                self._world = 1
        ctx = RunContext(self)
        restarts = 0
        transient_failures = 0
        last_fail_step = None
        # flight recorder rides along for the whole supervised job
        # (unless MXTPU_FLIGHT_RECORDER=off): any crash below leaves a
        # loadable timeline next to the checkpoints
        flight_token = _flight.auto_enable(
            directory=self.manager.directory
            if self.manager is not None else None)
        try:
            return self._run_supervised(train_fn, ctx, restarts,
                                        transient_failures,
                                        last_fail_step, is_main)
        finally:
            _flight.auto_disable(flight_token)

    def _run_supervised(self, train_fn, ctx, restarts,
                        transient_failures, last_fail_step, is_main):
        while True:
            ctx.attempt = restarts + transient_failures
            self._watchdog_diag = None
            self._progress = time.monotonic()
            watchdog = self._start_watchdog() if (
                self.watchdog_sec > 0 and is_main) else None
            chained = self._install_signal_chain() if is_main else False
            result = _UNSET
            try:
                result = train_fn(ctx)
                return result
            except KeyboardInterrupt:
                if result is not _UNSET:
                    # the watchdog lost the race with completion: its
                    # SIGINT landed after train_fn returned — the run
                    # SUCCEEDED, don't discard the result or restart
                    return result
                if self._watchdog_diag is None:
                    raise  # a real Ctrl-C is never swallowed
                exc, kind = WatchdogTimeout(self._watchdog_diag), "watchdog"
            except BaseException as e:  # noqa: BLE001 — classified below
                kind = classify(e)
                if kind == "fatal":
                    # post-mortem before the re-raise: the ring holds
                    # the job's last seconds
                    _flight.dump_if_enabled(
                        "fatal", extra={"error": str(e)[:500],
                                        "type": type(e).__name__,
                                        "last_step": self._last_step})
                    raise
                exc = e
            finally:
                try:
                    self._stop_watchdog(watchdog)
                    if chained:
                        self._uninstall_signal_chain()
                except KeyboardInterrupt:
                    # a last-instant watchdog SIGINT landing inside this
                    # cleanup would escape run() uncatchable; swallow it
                    # iff it is ours (teardown below already completed
                    # enough: stop is set, the thread is a daemon)
                    if self._watchdog_diag is None:
                        raise
                    if chained:
                        self._uninstall_signal_chain()
            t_fail = time.monotonic()

            # recovery budgets are per STALL POINT, not per job
            # lifetime: steps completed since the previous failure mean
            # the job is progressing, so a months-long run surviving a
            # flake every few hours never exhausts its budget
            if self._last_step is not None and last_fail_step is not None \
                    and self._last_step > last_fail_step:
                transient_failures = 0
                restarts = 0
            last_fail_step = self._last_step

            if kind in ("transient", "network"):
                transient_failures += 1
                if not self.retry.should_retry(transient_failures):
                    raise MXNetError(
                        f"transient failure persisted through "
                        f"{transient_failures - 1} retries "
                        f"(RetryPolicy.max_retries="
                        f"{self.retry.max_retries}): {exc}") from exc
                delay = self.retry.delay_for(transient_failures)
                logger.warning(
                    "transient failure (retry %d/%d, backoff %.3fs): %s",
                    transient_failures, self.retry.max_retries, delay, exc)
                time.sleep(delay)
            elif kind == "preemption":
                if self.on_preemption != "resume" \
                        or restarts >= self.max_restarts:
                    self._write_resume_marker("preemption", exc)
                    raise ResumeRequired(
                        f"preempted (SIGTERM); final checkpoint "
                        f"committed and resume marker written to "
                        f"{self.resume_marker} — restart the job to "
                        "resume from CheckpointManager.latest()") from exc
                restarts += 1
                logger.warning(
                    "preempted; restarting in-process (restart %d/%d)",
                    restarts, self.max_restarts)
            elif kind == "peer_death":
                resized = False
                if self.elastic and restarts < self.max_restarts:
                    resized = self._try_resize(exc)
                if resized:
                    restarts += 1
                    logger.warning(
                        "peer death; world resized to %d survivor(s), "
                        "restarting (restart %d/%d): %s",
                        self._world, restarts, self.max_restarts, exc)
                elif restarts >= self.max_restarts \
                        or not self._try_reinit():
                    self._write_resume_marker("peer_death", exc)
                    raise ResumeRequired(
                        f"peer death and the process group could not be "
                        f"re-initialized in-process; resume marker "
                        f"written to {self.resume_marker} — restart the "
                        f"whole job to resume from the last checkpoint "
                        f"(original failure: {exc})") from exc
                else:
                    restarts += 1
                    logger.warning(
                        "peer death; process group re-initialized, "
                        "restarting (restart %d/%d): %s",
                        restarts, self.max_restarts, exc)
            else:  # watchdog / corrupt_checkpoint / overloaded / deadline
                if restarts >= self.max_restarts:
                    raise exc
                restarts += 1
                if kind in ("overloaded", "deadline"):
                    # non-retryable at the REQUEST level (the serve
                    # router sheds), but a training job seeing these
                    # shapes from a collective/RPC must restart PACED:
                    # an instant restart hammers the very resource the
                    # error names, and back-to-back restarts would burn
                    # the whole budget inside one network blip
                    delay = self.retry.delay_for(restarts)
                    logger.warning(
                        "%s failure; backing off %.3fs before restart "
                        "(restart %d/%d): %s", kind, delay, restarts,
                        self.max_restarts, exc)
                    time.sleep(delay)
                else:
                    logger.warning(
                        "%s failure; restarting (restart %d/%d): %s",
                        kind, restarts, self.max_restarts, exc)

            _stats.add("restarts")
            _stats.add_retry(kind)
            _stats.add("time_lost_ms",
                       (time.monotonic() - t_fail) * 1e3)
            _tracer.instant("resilience.retry", cat="resilience",
                            kind=kind, last_step=self._last_step
                            if self._last_step is not None else -1,
                            error=str(exc)[:200])

    # -- preemption chain ----------------------------------------------------

    def _install_signal_chain(self):
        """Install SIGTERM handling so delivery runs: manager final save
        -> (chained) supervisor handler -> raise Preempted in the
        training thread."""

        def _handler(sig, frame):
            raise Preempted(
                "SIGTERM received (preemption notice); the final "
                "checkpoint, if a preemption state was registered, is "
                "already committed")

        try:
            self._orig_sigterm = signal.signal(signal.SIGTERM, _handler)
        except ValueError:  # not the main thread after all
            return False
        if self.manager is not None:
            self.manager.install_sigterm_hook(self._final_state)
        return True

    def _uninstall_signal_chain(self):
        if self.manager is not None:
            self.manager.uninstall_sigterm_hook()
        if self._orig_sigterm is not None:
            signal.signal(signal.SIGTERM, self._orig_sigterm)
            self._orig_sigterm = None

    def _final_state(self):
        fn = self._state_fn
        if fn is None:
            return None
        kwargs = fn()
        if kwargs is not None:
            kwargs.setdefault("sync", True)
            if "step" not in kwargs:
                kwargs["step"] = self._last_step if self._last_step \
                    is not None else 0
        return kwargs

    # -- resume marker -------------------------------------------------------

    def _write_resume_marker(self, reason, exc, dead_applied=False):
        # surviving topology: dead_applied says whether the caller
        # already shrank _world for THIS failure's dead ranks (the
        # min-world path does; a non-elastic peer death does not).
        # An explicit flag, not a membership test against the historic
        # _dead_ranks — those ids are from PRE-resize numberings, so a
        # re-used rank number must still be subtracted here
        dead_now = sorted({int(r) for r in
                           getattr(exc, "dead_ranks", ()) or ()})
        world = self._world
        surviving = ((world if dead_applied
                      else max(world - len(dead_now), 0))
                     if world is not None else None)
        marker = {
            "reason": reason,
            "error": str(exc)[:500],
            "last_step": self._last_step,
            "latest_checkpoint": (self.manager.latest()
                                  if self.manager is not None else None),
            "topology": {
                "world": surviving,
                "dead_ranks": sorted(set(self._dead_ranks) | set(dead_now)),
                "resizes": self._resizes,
            },
            "resume": "restart the job; a train_fn that restores from "
                      "CheckpointManager.latest() continues from "
                      "latest_checkpoint. topology.world is the "
                      "surviving world size — an on_preemption='exit' "
                      "relauncher sizes the next job with it (the "
                      "resharding restore repartitions the checkpoint)",
        }
        try:
            # atomic (tmp+fsync+rename): this path runs in the SIGKILL
            # escalation window, where a plain write could leave a
            # truncated marker for the restart tooling to parse
            from ..checkpoint import atomic

            atomic.write_json(self.resume_marker, marker)
        except OSError as e:  # the marker is advisory, never fatal
            logger.warning("could not write resume marker %s: %s",
                           self.resume_marker, e)

    # -- elastic resize ------------------------------------------------------

    def _try_resize(self, exc):
        """Shrink the world to the survivors of ``exc`` and arrange the
        next ``train_fn`` invocation to run at the new size.  Returns
        True when the resize happened, False to fall back to the
        legacy reinit-or-exit path (no dead-rank information in a
        single process, rendezvous failure, coordinator death).  The
        whole shrink — rendezvous plus re-init — is retried under the
        supervisor's :class:`RetryPolicy`, so a TRANSIENT failure
        inside the resize (an injected ``dist.rendezvous`` fault, a
        flaky shared-storage listing) is itself recovered, not fatal.
        A surviving world below ``min_world`` raises
        :class:`ResumeRequired` after writing the resume marker (whose
        ``topology`` section sizes the relaunch)."""
        from ..parallel import dist

        dead = sorted({int(r) for r in
                       getattr(exc, "dead_ranks", ()) or ()})

        def _attempt():
            return dist.shrink(
                dead_ranks=dead, world=self._world,
                timeout=self.rendezvous_timeout,
                rendezvous_dir=(self.manager.directory
                                if self.manager is not None else None),
                round_index=self._resizes)

        def _on_retry(attempt, e):
            _stats.add_retry("transient")
            logger.warning(
                "transient failure inside the elastic resize (retry "
                "%d/%d): %s", attempt, self.retry.max_retries, e)

        try:
            new_world, new_rank = self.retry.call(
                _attempt, retriable=(TransientFault,),
                on_retry=_on_retry)
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            logger.warning("elastic resize unavailable (%s); falling "
                           "back to reinit-or-exit", e)
            return False
        lost = max((self._world or 0) - new_world, 0) or len(dead)
        self._dead_ranks.extend(dead)
        if new_world < max(1, self.min_world):
            self._world = new_world
            self._write_resume_marker("peer_death", exc,
                                      dead_applied=True)
            raise ResumeRequired(
                f"elastic resize would leave {new_world} rank(s), "
                f"below MXTPU_MIN_WORLD={self.min_world}; resume "
                f"marker (with the surviving topology) written to "
                f"{self.resume_marker} — relaunch at an acceptable "
                "world size to resume from the last checkpoint") \
                from exc
        self._world = new_world
        self._resizes += 1
        _stats.add("resizes")
        _stats.add("ranks_lost", lost)
        mesh_txt = self._resize_mesh_shape(new_world)
        _tracer.instant("resilience.resize", cat="resilience",
                        world=new_world, new_rank=new_rank,
                        ranks_lost=lost, resizes=self._resizes,
                        mesh_shape=mesh_txt)
        return True

    def _resize_mesh_shape(self, new_world):
        """Pick the spmd mesh shape the shrunken job trains at and
        publish it through MXTPU_MESH_SHAPE, so the next ``train_fn``
        invocation's Trainer (env-configured or ``ctx.mesh_shape()``)
        builds the surviving mesh: model axes ('mp'/'pp') preserved,
        data axes shrunk (``parallel.spmd.mesh.pick_mesh_shape``).  A
        survivor count that breaks the model-axis product raises
        ResumeRequired — that resize needs an operator decision (new
        MXTPU_MESH_SHAPE + restore), not a silent repartition.  Returns
        the new spec text (or None when no mesh is configured)."""
        from ..base import setenv
        from ..parallel.spmd.mesh import (format_mesh_shape,
                                          mesh_shape_from_env,
                                          pick_mesh_shape)

        shape = mesh_shape_from_env()
        if shape is None:
            return None
        try:
            new_shape = pick_mesh_shape(shape, new_world)
        except MXNetError as e:
            self._write_resume_marker("peer_death", e,
                                      dead_applied=True)
            raise ResumeRequired(
                f"elastic resize to {new_world} rank(s) cannot keep "
                f"the model axes of mesh "
                f"{format_mesh_shape(shape)}: {e}. Resume marker "
                f"written to {self.resume_marker} — relaunch with an "
                "explicit smaller MXTPU_MESH_SHAPE to reshard from "
                "the last checkpoint") from e
        txt = format_mesh_shape(new_shape)
        if new_shape != shape:
            setenv("MESH_SHAPE", txt)
            logger.info("elastic resize: mesh shape %s -> %s (model "
                        "axes preserved)", format_mesh_shape(shape),
                        txt)
        return txt

    # -- peer-death re-init --------------------------------------------------

    def _try_reinit(self):
        """Best-effort process-group re-init.  True in a single process
        (nothing to re-init — the rehearsal path); multi-process, tries
        ``dist.reinit()`` which only helps when every SURVIVING peer
        does the same (a replacement worker must rejoin under the same
        coordinator) — otherwise False routes to the clean-exit path."""
        from ..parallel import dist

        try:
            if not dist.is_multiprocess():
                return True
            dist.reinit()
            return True
        except Exception as e:  # noqa: BLE001 — any failure = exit path
            logger.warning("process-group re-init failed: %s", e)
            return False

    # -- watchdog ------------------------------------------------------------

    def _start_watchdog(self):
        profiler.track_scopes(True)
        stop = threading.Event()
        th = threading.Thread(target=self._watch, args=(stop,),
                              daemon=True, name="mxtpu-watchdog")
        th.start()
        return stop, th

    def _stop_watchdog(self, watchdog):
        if watchdog is None:
            return
        stop, th = watchdog
        stop.set()
        th.join(timeout=2.0)
        profiler.track_scopes(False)

    def _watch(self, stop):
        period = max(0.05, min(1.0, self.watchdog_sec / 4.0))
        while not stop.wait(period):
            idle = time.monotonic() - self._progress
            if idle < self.watchdog_sec:
                continue
            diag = self._diagnose(idle)
            _stats.add("watchdog_fires")
            _tracer.instant("resilience.watchdog", cat="resilience",
                            idle_s=round(idle, 3))
            # the post-mortem: dump the ring BEFORE interrupting the
            # training thread, while the stall is still in progress
            # (active scopes name the stuck phase)
            _flight.dump_if_enabled("watchdog",
                                    extra={"diagnostic": diag})
            logger.error(diag)
            if stop.is_set():  # train_fn finished while we diagnosed
                return
            self._watchdog_diag = diag
            # a REAL signal (not just the interpreter's async-exception
            # flag): pthread_kill EINTRs a blocking C call like
            # time.sleep / a socket read, where interrupt_main would
            # wait for the next bytecode boundary that never comes
            try:
                signal.pthread_kill(threading.main_thread().ident,
                                    signal.SIGINT)
            except (AttributeError, ValueError, ProcessLookupError):
                _thread.interrupt_main()
            return

    def _diagnose(self, idle):
        scopes = profiler.active_scopes()
        phases = sorted({stack[-1] for stack in scopes.values() if stack})
        where = (f"stuck phase (open profiler sections): "
                 f"{', '.join(phases)}" if phases else
                 "no profiler section is open — the stall is in user "
                 "code between instrumented phases")
        # an armed HealthMonitor knows what was SLOW before the hang
        # (phase breakdown + firing SLO rules), not just which scope is
        # open now — append its last window to the diagnostic
        try:
            from ..telemetry import health as _health

            where += _health.describe_for_diagnostic()
        except Exception:  # noqa: BLE001 — diagnosis must never fail
            pass
        return (
            f"watchdog: no training step completed in {idle:.1f}s "
            f"(MXTPU_WATCHDOG_SEC={self.watchdog_sec:g}; last completed "
            f"step: {self._last_step}); {where}. A stuck "
            "'dist.allreduce'/'barrier' means a dead or partitioned "
            "peer — set MXTPU_DIST_TIMEOUT to convert the hang into a "
            "diagnosable error; a stuck 'pipeline.map' names the input "
            "pipeline (raise its timeout= or inspect the batch); "
            "'checkpoint.save.*' points at storage. See "
            "docs/resilience.md.")
