"""Operator registry.

Ref: the nnvm op registry (NNVM_REGISTER_OP in src/operator/*; attrs
FCompute/FInferShape/FInferType, dmlc parameter structs) and the
frontend codegen that builds ``mx.nd.*`` / ``mx.sym.*`` from
MXListAllOpNames (python/mxnet/ndarray/register.py).

TPU-native design: one entry per op holding a *pure JAX function*
(positional array inputs, keyword-only static attrs).  ``FCompute``
becomes "jit the fn" (see _imperative), ``FInferShape/Type`` become
``jax.eval_shape`` of the same fn, and ``FGradient`` becomes
``jax.vjp``.  The same entry powers the eager namespace (mx.nd), the
symbolic namespace (mx.sym), and hybrid tracing — so the three fronts
can never drift apart.
"""
from __future__ import annotations

from ..base import MXNetError

_ops = {}


class OpEntry:
    __slots__ = ("name", "fn", "arg_names", "aliases", "needs_rng",
                 "train_aware", "nondiff", "variadic", "num_outputs",
                 "jit_compile", "wrapper", "mutate_aux", "validator", "doc")

    def __init__(self, name, fn, arg_names=("data",), aliases=(),
                 needs_rng=False, train_aware=False, nondiff=False,
                 variadic=False, num_outputs=1, jit_compile=True,
                 wrapper=None, mutate_aux=None, validator=None, doc=None):
        self.name = name
        self.fn = fn
        self.arg_names = tuple(arg_names)
        self.aliases = tuple(aliases)
        self.needs_rng = needs_rng
        self.train_aware = train_aware
        self.nondiff = nondiff
        self.variadic = variadic
        self.num_outputs = num_outputs
        self.jit_compile = jit_compile
        self.wrapper = wrapper  # fully custom python-level wrapper
        self.mutate_aux = mutate_aux  # (aux_arg_indices, out_indices) pairs
        self.validator = validator  # host-side (arrays, attrs) precheck
        self.doc = doc or (fn.__doc__ if fn else None)


def register(name, fn=None, **kwargs):
    """Register an op (decorator or direct)."""

    def _do(f):
        if name in _ops:
            raise MXNetError(f"op '{name}' already registered")
        entry = OpEntry(name, f, **kwargs)
        _ops[name] = entry
        for a in entry.aliases:
            if a in _ops:
                raise MXNetError(f"op alias '{a}' already registered")
            _ops[a] = entry
        return f

    if fn is not None:
        return _do(fn)
    return _do


def get(name):
    if name not in _ops:
        raise MXNetError(f"unknown operator '{name}'")
    return _ops[name]


def exists(name):
    return name in _ops


def list_ops():
    return sorted(_ops)


def canonical_items():
    """(name, entry) pairs excluding alias duplicates."""
    seen = set()
    for k, v in _ops.items():
        if id(v) not in seen:
            seen.add(id(v))
            yield v.name, v
