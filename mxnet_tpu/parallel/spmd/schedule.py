"""Pipelined microbatch schedule over the 'pp' mesh axis.

Canonical home of the machinery that started as
``parallel/pipeline.py`` (which now re-exports from here), promoted
under the spmd plan API so pipeline parallelism composes with the
multi-axis mesh instead of living as an orphaned fragment.

Ref capability: ABSENT in the reference (SURVEY §2.3 'PP: ABSENT —
closest: group2ctx manual staging, no microbatching'); this is a
capability upgrade alongside TP/SP.

TPU-native design: stage parameters are STACKED on a leading axis of
size P and sharded over the 'pp' mesh axis, so each device holds one
stage.  Inside shard_map, a fori_loop runs the rotating microbatch
schedule: at tick t, device 0 feeds microbatch t, every device applies
its stage to its current activation, and activations rotate one hop
along the pipeline with ppermute (ICI neighbour exchange).  After P-1
warmup ticks the pipe is full; outputs stream off the last device and
are broadcast with a masked psum.  Backward is jax autodiff through
the whole schedule — ppermute transposes to the reverse rotation,
giving the mirrored fill/drain automatically, with the forward of
later microbatches overlapping the drain of earlier ones inside the
one program (XLA schedules the interleave; no host round-trips between
microbatches).

Constraints (the standard stacked-pipeline contract): all stages share
one jittable ``stage_fn(params_slice, x) -> y`` with x and y of the
same shape, and the number of microbatches must be >= 1 (default: the
``MXTPU_PP_MICROBATCHES`` knob, else P).  Wall-clock efficiency is
n_micro / (n_micro + P - 1) (the pipeline bubble).

:class:`PipelineTrainStep` closes the loop ROADMAP item 1 asks for:
forward schedule, loss, backward (the transposed schedule), a 'dp'
gradient psum, and an SGD-momentum update of the stacked stage params
— ONE pjit'd executable per training step on a ('dp','pp') mesh, lr
riding as a traced scalar so schedules never retrace.

``stage_partition`` maps a layer count onto P stages (the loud
``pp stages > layers`` error lives there); a generic
``Trainer(mesh_shape="...,pp=N")`` is rejected at construction with a
pointer here — an arbitrary HybridBlock cannot be auto-staged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...base import MXNetError, getenv


def default_microbatches(n_stages):
    """Microbatch count: ``MXTPU_PP_MICROBATCHES`` when set, else the
    stage count (one microbatch in flight per stage — the smallest
    full-pipe schedule)."""
    n = getenv("PP_MICROBATCHES", 0, int)
    return int(n) if n and n > 0 else int(n_stages)


def stage_partition(n_layers, n_stages):
    """Partition ``n_layers`` sequential layers onto ``n_stages``
    pipeline stages: returns a tuple of ``(start, stop)`` layer ranges,
    balanced to within one layer (earlier stages take the remainder).

    Loud errors: a non-positive stage count, or MORE stages than layers
    — an empty stage would sit in the rotate schedule doing identity
    work while costing a full pipeline-bubble slot."""
    n_layers, n_stages = int(n_layers), int(n_stages)
    if n_stages < 1:
        raise MXNetError(f"pp stage count must be >= 1, got {n_stages}")
    if n_stages > n_layers:
        raise MXNetError(
            f"pp={n_stages} pipeline stages > {n_layers} layers — an "
            "empty stage wastes a bubble slot; shrink the 'pp' axis in "
            "MXTPU_MESH_SHAPE or deepen the model")
    base, rem = divmod(n_layers, n_stages)
    out, start = [], 0
    for s in range(n_stages):
        stop = start + base + (1 if s < rem else 0)
        out.append((start, stop))
        start = stop
    return tuple(out)


def _pipeline_sharded(params, xs_local, *, stage_fn, axis_name, n_micro,
                      P):
    """Runs INSIDE shard_map: params leaves are the local (1, ...)
    stage slice; xs_local is the replicated (n_micro, mb, ...) batch."""
    idx = jax.lax.axis_index(axis_name)
    local = jax.tree.map(lambda p: p[0], params)
    T = n_micro + P - 1
    # carries vary across the 'pp' axis (per-device state) — mark them
    # so shard_map's vma check accepts the fori_loop carry
    from .. import mesh as _mesh_mod

    acts, outs = _mesh_mod.pcast(
        (jnp.zeros_like(xs_local[0]), jnp.zeros_like(xs_local)),
        axis_name, to="varying")

    def tick(t, carry):
        acts, outs = carry
        # device 0 ingests microbatch t (zeros once drained)
        feed = jnp.where(t < n_micro, xs_local[jnp.minimum(
            t, n_micro - 1)], jnp.zeros_like(acts))
        inp = jnp.where(idx == 0, feed, acts)
        out = stage_fn(local, inp)
        # last device emits microbatch t-(P-1) at tick t
        emit_t = t - (P - 1)
        outs = jnp.where(
            (idx == P - 1) & (emit_t >= 0),
            outs.at[jnp.maximum(emit_t, 0)].set(out), outs)
        # rotate activations one hop down the pipe
        acts = jax.lax.ppermute(
            out, axis_name, [(j, (j + 1) % P) for j in range(P)])
        return acts, outs

    _, outs = jax.lax.fori_loop(0, T, tick, (acts, outs))
    # broadcast the last device's outputs to every device
    mask = (idx == P - 1).astype(outs.dtype)
    return jax.lax.psum(outs * mask, axis_name)


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis="pp",
                   n_micro=None):
    """Run x through P pipelined stages.

    stage_fn: (params_slice, x_mb) -> y_mb, same shape in/out.
    stacked_params: pytree whose leaves have leading dim P (one slice
      per stage) — shard leading dim over `axis` for real PP.
    x: (B, ...) with B divisible by n_micro (n_micro >= 1; default
      ``MXTPU_PP_MICROBATCHES``, else P).
    Returns (B, ...) outputs (the composition of all stages).
    """
    from jax.sharding import PartitionSpec

    from .. import mesh as mesh_mod

    shard_map = mesh_mod.shard_map()

    P = mesh.shape[axis]
    n_micro = default_microbatches(P) if n_micro is None else int(n_micro)
    if n_micro < 1:
        raise MXNetError(f"n_micro must be >= 1, got {n_micro}")
    B = x.shape[0]
    if B % n_micro:
        raise MXNetError(f"batch {B} must divide into n_micro={n_micro}")
    mb = B // n_micro
    xs = x.reshape((n_micro, mb) + x.shape[1:])

    pspec = jax.tree.map(lambda _: PartitionSpec(axis), stacked_params)
    in_specs = (pspec, PartitionSpec())
    try:
        # cached jit(shard_map) keyed on (stage_fn, mesh, specs, attrs)
        # — a fresh closure per call would retrace every training step
        fn = mesh_mod.spmd_jit(
            _pipeline_sharded, mesh, in_specs, PartitionSpec(),
            stage_fn=stage_fn, axis_name=axis, n_micro=n_micro, P=P)
    except TypeError:
        # unhashable param pytree (dict specs): uncached fallback
        import functools

        fn = jax.jit(shard_map(
            functools.partial(_pipeline_sharded, stage_fn=stage_fn,
                              axis_name=axis, n_micro=n_micro, P=P),
            mesh=mesh, in_specs=in_specs, out_specs=PartitionSpec()))
    out = fn(stacked_params, xs)
    return out.reshape((B,) + x.shape[1:])


# -- compiled pipelined training step ---------------------------------------


def _pp_train_sharded(params, states, xs_local, y_local, lr, *,
                      stage_fn, loss_fn, pp_axis, dp_axis, n_micro, P,
                      momentum):
    """One training step inside shard_map on a ('dp','pp') mesh: the
    rotate-schedule forward, loss over this dp-shard's batch, autodiff
    backward through the schedule (transposed ppermute rotation), a
    psum of loss+grads over 'dp' (params replicate across dp), and the
    SGD-momentum update of the stacked stage params."""
    def _loss(params_):
        out = _pipeline_sharded(params_, xs_local, stage_fn=stage_fn,
                                axis_name=pp_axis, n_micro=n_micro, P=P)
        return jnp.sum(loss_fn(out, y_local))

    loss, grads = jax.value_and_grad(_loss)(params)
    if dp_axis is not None:
        loss = jax.lax.psum(loss, dp_axis)
        grads = jax.tree.map(lambda g: jax.lax.psum(g, dp_axis), grads)
    new_states = jax.tree.map(lambda s, g: momentum * s + g, states,
                              grads)
    new_params = jax.tree.map(lambda w, s: w - lr * s, params,
                              new_states)
    return loss, new_params, new_states


class PipelineTrainStep:
    """A compiled training step for a stack of P uniform stages on a
    ('dp','pp') mesh: ONE pjit'd executable per step.

    >>> step = PipelineTrainStep(stage_fn, mesh, momentum=0.9)
    >>> loss, params, states = step(params, states, x, y, lr=0.1)

    ``params`` is a pytree with leading dim P on every leaf (one slice
    per stage, sharded over 'pp'); ``states`` the momentum buffers of
    the same structure (``init_states`` builds zeros).  ``x``/``y``
    shard over 'dp'; ``lr`` is traced, so schedules never retrace.  The
    executable is cached per (mesh, shapes) — repeat calls at one shape
    are zero-compile, one dispatch (``_imperative.count_dispatch``)."""

    def __init__(self, stage_fn, mesh, loss_fn=None, pp_axis="pp",
                 dp_axis="dp", n_micro=None, momentum=0.9):
        if pp_axis not in mesh.axis_names:
            raise MXNetError(
                f"mesh has no {pp_axis!r} axis (axes: "
                f"{tuple(mesh.axis_names)}) — add pp=N to the mesh "
                "shape to pipeline")
        for a in mesh.axis_names:
            if a not in (pp_axis, dp_axis):
                raise MXNetError(
                    f"PipelineTrainStep runs on ('dp','pp') meshes; "
                    f"axis {a!r} is unsupported here — tensor-parallel "
                    "('mp') composition rides the Trainer whole-step "
                    "path (docs/parallelism.md)")
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.loss_fn = loss_fn or (lambda out, y: (out - y) ** 2)
        self.pp_axis = pp_axis
        self.dp_axis = dp_axis if dp_axis in mesh.axis_names else None
        self.P = int(mesh.shape[pp_axis])
        self.n_micro = (default_microbatches(self.P) if n_micro is None
                        else int(n_micro))
        self.momentum = float(momentum)
        self._fn = None

    def init_states(self, params):
        """Zero momentum buffers matching ``params``."""
        return jax.tree.map(jnp.zeros_like, params)

    def _build(self, params):
        import functools

        from jax.sharding import PartitionSpec as PS

        from .. import mesh as mesh_mod

        pspec = jax.tree.map(lambda _: PS(self.pp_axis), params)
        # batch arrives microbatch-major (n_micro, mb, ...): dim 1 — the
        # per-microbatch batch — shards over 'dp'; the microbatch dim is
        # the schedule's loop index and stays whole on every device
        data = PS(None, self.dp_axis) if self.dp_axis else PS()
        body = functools.partial(
            _pp_train_sharded, stage_fn=self.stage_fn,
            loss_fn=self.loss_fn, pp_axis=self.pp_axis,
            dp_axis=self.dp_axis, n_micro=self.n_micro, P=self.P,
            momentum=self.momentum)
        return jax.jit(mesh_mod.shard_map()(
            body, mesh=self.mesh,
            in_specs=(pspec, pspec, data, data, PS()),
            out_specs=(PS(), pspec, pspec)))

    def __call__(self, params, states, x, y, lr):
        from ... import _imperative

        mb_total = self.n_micro
        B = int(x.shape[0])
        if B % mb_total:
            raise MXNetError(
                f"batch {B} must divide into n_micro={mb_total}")
        dp = (int(self.mesh.shape[self.dp_axis])
              if self.dp_axis else 1)
        if B % (mb_total * dp):
            raise MXNetError(
                f"batch {B} must divide across dp={dp} shards x "
                f"n_micro={mb_total} microbatches")
        xs = x.reshape((mb_total, B // mb_total) + tuple(x.shape[1:]))
        ys = y.reshape((mb_total, B // mb_total) + tuple(y.shape[1:]))
        if self._fn is None:
            self._fn = self._build(params)
        lr = jnp.asarray(lr, jnp.float32)
        _imperative.count_dispatch()
        loss, new_params, new_states = self._fn(params, states, xs, ys,
                                                lr)
        return loss, new_params, new_states
