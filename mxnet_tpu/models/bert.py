"""BERT (ref workload: BASELINE config 'BERT-base MLM pretrain
(GluonNLP, Trainer + kvstore all-reduce on pod)'; model structure after
the GluonNLP-era BERTModel: embeddings + transformer encoder + MLM/NSP
heads).

TPU notes: attention uses the fused scaled_dot_product_attention op
(pallas flash path on TPU); everything hybridizes into one XLA step.
"""
from __future__ import annotations

import math

from ..gluon import nn
from ..gluon.block import HybridBlock


class BERTEncoderLayer(HybridBlock):
    def __init__(self, units=768, hidden_size=3072, num_heads=12,
                 dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._num_heads = num_heads
        self.attn_in_weight = self.params.get(
            "attn_in_weight", shape=(3 * units, units))
        self.attn_in_bias = self.params.get(
            "attn_in_bias", shape=(3 * units,), init="zeros")
        self.attn_out_weight = self.params.get(
            "attn_out_weight", shape=(units, units))
        self.attn_out_bias = self.params.get(
            "attn_out_bias", shape=(units,), init="zeros")
        self.attn_ln = nn.LayerNorm(in_channels=units)
        self.ffn1 = nn.Dense(hidden_size, flatten=False)
        self.ffn2 = nn.Dense(units, flatten=False)
        self.ffn_ln = nn.LayerNorm(in_channels=units)
        self.dropout = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None, attn_in_weight=None,
                       attn_in_bias=None, attn_out_weight=None,
                       attn_out_bias=None):
        att = F.multihead_attention(x, x, x, attn_in_weight, attn_in_bias,
                                    attn_out_weight, attn_out_bias, mask,
                                    num_heads=self._num_heads)
        x = self.attn_ln(x + self.dropout(att))
        h = self.ffn2(F.LeakyReLU(self.ffn1(x), act_type="gelu"))
        return self.ffn_ln(x + self.dropout(h))


class BERTEncoder(HybridBlock):
    def __init__(self, num_layers=12, units=768, hidden_size=3072,
                 num_heads=12, dropout=0.1, **kwargs):
        super().__init__(**kwargs)
        self.layers = nn.HybridSequential()
        for _ in range(num_layers):
            self.layers.add(BERTEncoderLayer(units, hidden_size, num_heads,
                                             dropout))

    def hybrid_forward(self, F, x, mask=None):
        for layer in self.layers:
            x = layer(x, mask)
        return x


class BERTModel(HybridBlock):
    """BERT backbone + MLM decoder + NSP classifier."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 type_vocab_size=2, dropout=0.1, use_pooler=True,
                 use_decoder=True, use_classifier=True, **kwargs):
        # use_pooler/use_decoder/use_classifier follow gluonnlp's
        # BERTModel: fine-tuning builds the backbone WITHOUT the MLM
        # decoder / NSP classifier heads (their params would otherwise
        # sit deferred-uninitialized in the block tree)
        super().__init__(**kwargs)
        if use_classifier and not use_pooler:
            raise ValueError(
                "use_classifier=True requires use_pooler=True (the NSP "
                "head reads the pooled [CLS]); gluonnlp enforces the "
                "same combination")
        self._units = units
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        self.word_embed = nn.Embedding(vocab_size, units)
        self.token_type_embed = nn.Embedding(type_vocab_size, units)
        self.position_embed = nn.Embedding(max_length, units)
        self.embed_ln = nn.LayerNorm(in_channels=units)
        self.embed_dropout = nn.Dropout(dropout)
        self.encoder = BERTEncoder(num_layers, units, hidden_size,
                                   num_heads, dropout)
        if use_pooler:
            self.pooler = nn.Dense(units, flatten=False,
                                   activation="tanh")
        if use_decoder:
            # MLM head (decoder shares transform; tied embedding
            # optional)
            self.mlm_transform = nn.Dense(units, flatten=False)
            self.mlm_ln = nn.LayerNorm(in_channels=units)
            self.mlm_decoder = nn.Dense(vocab_size, flatten=False)
        if use_classifier:
            self.nsp_classifier = nn.Dense(2, flatten=False)

    def _encode_sequence(self, inputs, token_types, valid_length=None):
        """Embeddings + attention-masked encoder stack — shared by the
        pretraining heads and fine-tune classifiers (ref: gluonnlp
        BERTModel's encode path reused by BERTClassifier)."""
        from .. import ndarray as F

        seq_len = inputs.shape[1]
        positions = F.arange(0, seq_len, dtype="int32")
        x = self.word_embed(inputs) + self.token_type_embed(token_types)
        x = x + self.position_embed(positions)
        x = self.embed_dropout(self.embed_ln(x))
        mask = None
        if valid_length is not None:
            steps = F.arange(0, seq_len, dtype="float32")
            m = F.broadcast_lesser(
                steps.reshape(1, -1), valid_length.reshape(-1, 1))
            mask = (m.reshape(m.shape[0], 1, 1, seq_len) - 1.0) * 1e9
        return self.encoder(x, mask)

    def pool(self, seq):
        """[CLS] representation through the tanh pooler."""
        return self.pooler(seq.slice_axis(1, 0, 1).reshape(
            seq.shape[0], self._units))

    def hybrid_forward(self, F, inputs, token_types, valid_length=None,
                       masked_positions=None):
        """Full heads: (mlm_scores, nsp_scores) — the pretraining
        contract.  With use_decoder=False/use_classifier=False
        (fine-tuning backbones) returns (sequence, pooled) or just the
        sequence, matching gluonnlp's output arity rules.

        `masked_positions` (b, K) int32 — gluonnlp's BERTModel
        contract: the MLM head decodes ONLY the gathered positions,
        giving (b, K, vocab).  At seq 128 the all-positions vocab
        projection is ~35% of the training step's FLOPs for ~15%
        masked tokens — the gather is both the reference recipe and
        the throughput win.  Omitted: decode every position (b, S,
        vocab), the fine-tune/scoring form."""
        seq = self._encode_sequence(inputs, token_types, valid_length)
        if not (self._use_decoder or self._use_classifier):
            if not self._use_pooler:
                return seq
            return seq, self.pool(seq)
        mlm_in = seq
        if self._use_decoder and masked_positions is not None:
            b, S = inputs.shape[0], inputs.shape[1]
            K = masked_positions.shape[1]
            flat = seq.reshape(b * S, self._units)
            offsets = F.arange(0, b, dtype="int32").reshape(b, 1) * S
            fidx = (masked_positions.astype("int32") + offsets) \
                .reshape(b * K)
            mlm_in = F.take(flat, fidx).reshape(b, K, self._units)
        mlm = self.mlm_decoder(
            self.mlm_ln(F.LeakyReLU(self.mlm_transform(mlm_in),
                                    act_type="gelu"))) \
            if self._use_decoder else None
        # pool only when the NSP head consumes it (an MLM-only model
        # must not pay for a discarded pooler forward)
        nsp = self.nsp_classifier(self.pool(seq)) \
            if self._use_classifier else None
        if mlm is not None and nsp is not None:
            return mlm, nsp
        return mlm if mlm is not None else nsp


def bert_base(vocab_size=30522, **kwargs):
    """BERT-base: 12 layers, 768 units, 12 heads (the BASELINE config)."""
    return BERTModel(vocab_size, 768, 3072, 12, 12, **kwargs)


def bert_large(vocab_size=30522, **kwargs):
    return BERTModel(vocab_size, 1024, 4096, 24, 16, **kwargs)


def bert_tiny(vocab_size=1000, **kwargs):
    """Small config for tests."""
    return BERTModel(vocab_size, 64, 128, 2, 4, max_length=128, **kwargs)
