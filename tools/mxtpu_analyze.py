"""mxtpu-analyze CLI — `make analyze` (a `make verify` prerequisite).

Runs the mxnet_tpu.analysis pass families over the repo, applies the
checked-in baseline (tools/analysis_baseline.json), and fails on any
NON-baselined finding.  See docs/static-analysis.md.

  python tools/mxtpu_analyze.py            # human table, exit 1 on new
  python tools/mxtpu_analyze.py --json     # machine-readable (CI)
  python tools/mxtpu_analyze.py --passes locks,invariants
  python tools/mxtpu_analyze.py --no-baseline   # raw findings

Exit codes: 0 clean (modulo baseline), 1 new findings, 2 usage/crash.
The run also enforces its own latency budget: --max-seconds (default
30) fails the gate if the analyzer itself gets slow enough to drag
`make verify`.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join("tools", "analysis_baseline.json")


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxtpu_analyze")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output for CI")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"suppression file (default {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report every finding")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset (locks,trace,"
                         "determinism,invariants)")
    ap.add_argument("--root", default=REPO)
    ap.add_argument("--max-seconds", type=float, default=30.0,
                    help="fail if the analyzer itself exceeds this "
                         "budget (0 disables)")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    from mxnet_tpu import analysis

    passes = args.passes.split(",") if args.passes else None
    baseline_path = None if args.no_baseline else \
        os.path.join(args.root, args.baseline)
    try:
        result = analysis.analyze(args.root, passes=passes,
                                  baseline_path=baseline_path)
    except Exception as e:  # noqa: BLE001 — a broken analyzer must not
        # masquerade as a clean repo
        print(f"mxtpu-analyze: internal error: {e}", file=sys.stderr)
        raise SystemExit(2)
    runtime_s = time.perf_counter() - t0

    new, suppressed, unused = (result["new"], result["suppressed"],
                               result["unused"])
    if args.json:
        from mxnet_tpu.analysis import load_baseline

        just = load_baseline(baseline_path) if baseline_path else {}
        print(json.dumps({
            "new": [f.to_dict() for f in new],
            "suppressed": [dict(f.to_dict(),
                                justification=just.get(f.key, ""))
                           for f in suppressed],
            "unused_suppressions": unused,
            "counts": {"new": len(new), "suppressed": len(suppressed),
                       "unused_suppressions": len(unused)},
            "runtime_s": round(runtime_s, 3),
        }, indent=2))
    else:
        if new:
            print(f"{'CODE':<8}{'LOCATION':<44}MESSAGE")
            print("-" * 100)
            for f in new:
                loc = f"{f.path}:{f.line}"
                print(f"{f.code:<8}{loc:<44}{f.message}")
                print(f"{'':<8}{'':<44}key: {f.key}")
        for k in unused:
            print(f"warning: stale baseline suppression (no longer "
                  f"fires): {k}")
        print(f"mxtpu-analyze: {len(new)} new finding(s), "
              f"{len(suppressed)} baselined, {len(unused)} stale "
              f"suppression(s), {runtime_s:.2f}s")
    if args.max_seconds and runtime_s > args.max_seconds:
        print(f"mxtpu-analyze: runtime {runtime_s:.1f}s exceeds the "
              f"{args.max_seconds:.0f}s budget", file=sys.stderr)
        raise SystemExit(1)
    raise SystemExit(1 if new else 0)


if __name__ == "__main__":
    main()
