"""Pallas TPU kernels (the custom-call tier; ref: the reference's
hand-CUDA/cuDNN kernels, re-expressed compiler-first)."""
