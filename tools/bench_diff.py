#!/usr/bin/env python
"""Bench trajectory differ: compare the latest bench run against the
previous one, flagging per-leaf regressions past a tolerance.

``bench.py`` appends every run's full record to ``BENCH_HISTORY.jsonl``
(one JSON object per line, newest last; ``MXTPU_BENCH_HISTORY`` moves
the file).  This tool flattens the two newest records' numeric leaves
(``records.<leaf>.<key>`` plus the top-level primary metric), classifies
each key's direction — throughput-like (higher is better),
latency/cost-like (lower is better), or informational — and reports
every leaf whose value moved PAST its tolerance in the bad direction.

With no history file yet, it falls back to the archived ``BENCH_r0*.json``
driver snapshots (their ``parsed`` field is the same record shape), so
the existing trajectory is readable before the first post-change run.

Usage::

    python tools/bench_diff.py                 # report, exit 0
    python tools/bench_diff.py --strict        # exit 1 on any regression
    python tools/bench_diff.py --tolerance 0.2 # global tolerance 20%
    python tools/bench_diff.py --json          # machine-readable report
    python tools/bench_diff.py --file TUNE_HISTORY.jsonl
                                               # diff the two newest
                                               # records of any jsonl
                                               # (tuner trial records)
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_HISTORY = os.path.join(REPO, "BENCH_HISTORY.jsonl")

# direction classification by key substring (first match wins).
# Anything unmatched is informational: reported, never flagged —
# batch_size changing is a config drift to eyeball, not a regression.
_LOWER_IS_BETTER = (
    "p50", "p95", "p99", "latency", "_ms", "ms_per", "us_per",
    "lost", "compiles", "dispatches", "steps_lost", "time_to_resume",
    "overhead", "wait", "blocked_moves", "pages_in_flight",
    "hbm_bytes", "spawn_failures", "rpc_errors",
    "stale_leases_rejected", "blocked_cooldown", "blocked_bounds",
    # spmd mesh leaf: per-device memory footprints and their ratio to
    # the single-device arm shrink as sharding improves; fallbacks are
    # eager escapes from the compiled step path
    "bytes_per_device", "shrink_ratio", "fallbacks",
)
_HIGHER_IS_BETTER = (
    "throughput", "tokens_per", "images_per", "rps", "speedup",
    "value", "mfu", "goodput", "fill", "hit", "occupancy",
    "vs_baseline", "best_over_baseline", "score", "samples_per",
    "accept_rate", "concurrent_sequences",
)

# per-leaf tolerance overrides (fraction of the previous value) for
# leaves known to be noisy on shared CPU boxes; everything else uses
# --tolerance (default 10%)
PER_LEAF_TOLERANCE = {
    re.compile(r"records\.(serve|serve_decode|serve_int8|serve_router)"
               r"\..*(value|rps|p99_ms|p50_ms|tokens_per_sec"
               r"|_at_fixed_mem)$"): 0.35,
    re.compile(r"records\.(trainer_step|whole_step_mp|input_pipeline"
               r"|recovery)\."): 0.35,
    re.compile(r"(^|\.)value$"): 0.25,
}


def _direction(key):
    k = key.lower()
    for s in _LOWER_IS_BETTER:
        if s in k:
            return "lower"
    for s in _HIGHER_IS_BETTER:
        if s in k:
            return "higher"
    return "info"


def _tolerance_for(leaf, default):
    for pat, tol in PER_LEAF_TOLERANCE.items():
        if pat.search(leaf):
            return tol
    return default


def flatten(record, prefix=""):
    """``{"records": {"serve": {"value": 1}}}`` ->
    ``{"records.serve.value": 1.0}`` (numeric leaves only)."""
    out = {}
    if not isinstance(record, dict):
        return out
    for k, v in record.items():
        key = f"{prefix}{k}"
        if isinstance(v, bool) or v is None:
            continue
        if isinstance(v, (int, float)):
            out[key] = float(v)
        elif isinstance(v, dict):
            out.update(flatten(v, key + "."))
    return out


def load_history(path):
    records = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue   # a truncated tail line is not fatal
    return records


def load_bench_r_files(directory):
    """The archived driver snapshots, oldest first (their ``parsed``
    field is the bench record)."""
    out = []
    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_r*.json"))):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue
        rec = snap.get("parsed")
        if isinstance(rec, dict):
            out.append(rec)
    return out


def load_last_two(history_path, fallback_dir=None, explicit=False):
    """(previous, latest) bench records — from the history file, padded
    from the archived BENCH_r*.json snapshots when the history is
    short.  ``explicit=True`` (the ``--file`` path) never pads: an
    arbitrary jsonl (tuner trial records) must stand on its own two
    lines rather than be diffed against an unrelated bench snapshot."""
    records = load_history(history_path)
    if len(records) < 2 and not explicit:
        records = load_bench_r_files(fallback_dir or REPO) + records
    if len(records) < 2:
        raise SystemExit(
            f"need two bench records to diff; found {len(records)} "
            f"(history: {history_path}). Run `python bench.py` twice — "
            "each run appends to the history.")
    return records[-2], records[-1]


def diff_records(prev, new, tolerance=0.10):
    """Per-leaf comparison: ``[{"leaf", "prev", "new", "delta_pct",
    "direction", "tolerance", "verdict"}]`` with verdicts ``ok`` /
    ``improved`` / ``REGRESSED`` / ``info`` / ``new`` / ``dropped``."""
    fp, fn = flatten(prev), flatten(new)
    report = []
    for leaf in sorted(set(fp) | set(fn)):
        p, n = fp.get(leaf), fn.get(leaf)
        if p is None or n is None:
            report.append({"leaf": leaf, "prev": p, "new": n,
                           "delta_pct": None, "direction": "info",
                           "tolerance": None,
                           "verdict": "new" if p is None else "dropped"})
            continue
        direction = _direction(leaf)
        delta = (n - p) / abs(p) if p else (0.0 if n == p else None)
        tol = _tolerance_for(leaf, tolerance)
        verdict = "info"
        if direction != "info" and delta is not None:
            worse = delta < -tol if direction == "higher" else delta > tol
            better = delta > tol if direction == "higher" else delta < -tol
            verdict = ("REGRESSED" if worse else
                       "improved" if better else "ok")
        elif direction != "info":
            # previous value was 0: any nonzero move on a lower-is-
            # better leaf (lost requests, post-warmup compiles) is a
            # regression outright
            verdict = ("REGRESSED" if direction == "lower" and n > 0
                       else "ok")
        report.append({"leaf": leaf, "prev": p, "new": n,
                       "delta_pct": (round(delta * 100.0, 2)
                                     if delta is not None else None),
                       "direction": direction, "tolerance": tol,
                       "verdict": verdict})
    return report


def has_regression(report):
    return any(r["verdict"] == "REGRESSED" for r in report)


def render(report, show_all=False):
    lines = []
    header = (f"{'leaf':<52}{'prev':>14}{'new':>14}{'delta':>9}  "
              f"verdict")
    lines.append(header)
    lines.append("-" * len(header))
    for r in report:
        if not show_all and r["verdict"] in ("ok", "info"):
            continue
        delta = (f"{r['delta_pct']:+.1f}%" if r["delta_pct"] is not None
                 else "-")
        prev = f"{r['prev']:.4g}" if r["prev"] is not None else "-"
        new = f"{r['new']:.4g}" if r["new"] is not None else "-"
        lines.append(f"{r['leaf']:<52}{prev:>14}{new:>14}{delta:>9}  "
                     f"{r['verdict']}")
    if len(lines) == 2:
        lines.append("(no leaf moved past tolerance)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history",
                    default=os.environ.get("MXTPU_BENCH_HISTORY",
                                           DEFAULT_HISTORY),
                    help="bench history jsonl (newest last)")
    ap.add_argument("--file", dest="file", default=None,
                    help="diff the two newest records of this jsonl "
                         "instead of the bench history (tuner trial "
                         "records, ad-hoc measurement logs); no "
                         "BENCH_r*.json fallback padding")
    ap.add_argument("--tolerance", type=float, default=0.10,
                    help="default per-leaf tolerance fraction (0.10)")
    ap.add_argument("--all", action="store_true",
                    help="show every leaf, not just flagged ones")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when any leaf REGRESSED")
    args = ap.parse_args(argv)

    if args.file:
        prev, new = load_last_two(args.file, explicit=True)
    else:
        prev, new = load_last_two(args.history)
    report = diff_records(prev, new, tolerance=args.tolerance)
    regressed = has_regression(report)
    if args.json:
        print(json.dumps({"regressed": regressed, "report": report}))
    else:
        print(render(report, show_all=args.all))
        n_reg = sum(1 for r in report if r["verdict"] == "REGRESSED")
        n_imp = sum(1 for r in report if r["verdict"] == "improved")
        print(f"\nBENCH_DIFF {'REGRESSED' if regressed else 'OK'} "
              f"regressed={n_reg} improved={n_imp} "
              f"leaves={len(report)}")
    return 1 if (regressed and args.strict) else 0


if __name__ == "__main__":
    sys.exit(main())
