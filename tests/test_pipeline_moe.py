"""Pipeline (pp) and expert (ep) parallelism — oracle equivalence on
the virtual 8-device mesh (capability upgrades beyond the reference;
SURVEY §2.3 marks both ABSENT upstream)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import mesh as mesh_mod
from mxnet_tpu.parallel.moe import MoEBlock, moe_ffn
from mxnet_tpu.parallel.pipeline import pipeline_apply

P, D = 4, 8


def _stage(params, xb):
    W, b = params
    return jax.nn.relu(xb @ W + b)


def _pipeline_fixture():
    mesh = mesh_mod.make_mesh({"pp": P}, devices=jax.devices()[:P])
    rng = np.random.RandomState(0)
    Ws = jnp.asarray(rng.randn(P, D, D).astype(np.float32) * 0.3)
    bs = jnp.asarray(rng.randn(P, D).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(8, D).astype(np.float32))
    return mesh, Ws, bs, x


def _sequential(Ws, bs, x):
    for i in range(P):
        x = jax.nn.relu(x @ Ws[i] + bs[i])
    return x


def test_pipeline_matches_sequential():
    mesh, Ws, bs, x = _pipeline_fixture()
    out = pipeline_apply(_stage, (Ws, bs), x, mesh, n_micro=4)
    assert np.allclose(np.asarray(out), np.asarray(_sequential(Ws, bs, x)),
                       atol=1e-5)
    # more microbatches than stages (smaller bubble) must also match
    out8 = pipeline_apply(_stage, (Ws, bs), x, mesh, n_micro=8)
    assert np.allclose(np.asarray(out8), np.asarray(out), atol=1e-5)


def test_pipeline_gradients_match():
    mesh, Ws, bs, x = _pipeline_fixture()

    def loss_pp(Ws, bs):
        return (pipeline_apply(_stage, (Ws, bs), x, mesh,
                               n_micro=4) ** 2).mean()

    def loss_seq(Ws, bs):
        return (_sequential(Ws, bs, x) ** 2).mean()

    g = jax.grad(loss_pp, argnums=(0, 1))(Ws, bs)
    gref = jax.grad(loss_seq, argnums=(0, 1))(Ws, bs)
    for a, b in zip(g, gref):
        assert np.allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipeline_validates_microbatching():
    mesh, Ws, bs, x = _pipeline_fixture()
    with pytest.raises(MXNetError):
        pipeline_apply(_stage, (Ws, bs), x, mesh, n_micro=3)  # 8 % 3


def test_moe_sharded_matches_dense_oracle():
    mesh = mesh_mod.make_mesh({"ep": 4}, devices=jax.devices()[:4])
    blk = MoEBlock(num_experts=4, d_model=8, d_hidden=16, seed=1)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    y, aux = jax.jit(lambda v: moe_ffn(v, *blk.params(), mesh=mesh))(x)
    # dense per-token oracle: each kept token = gate * expert_ffn(token)
    probs = jax.nn.softmax(x @ blk.router_w, -1)
    e = jnp.argmax(probs, -1)
    gate = jnp.max(probs, -1)
    onehot = jax.nn.one_hot(e, 4, dtype=jnp.int32)
    pos = (jnp.cumsum(onehot, 0) * onehot - 1).max(-1)
    C = max(1, int(1.25 * 32 / 4))
    keep = np.asarray(pos < C)
    ref = []
    for i in range(32):
        ei = int(e[i])
        h = jax.nn.relu(x[i] @ blk.w1[ei] + blk.b1[ei])
        ref.append((h @ blk.w2[ei] + blk.b2[ei]) * gate[i] * keep[i])
    assert np.allclose(np.asarray(y), np.asarray(jnp.stack(ref)),
                       atol=1e-4)
    assert float(aux) > 0


def test_moe_capacity_drops_overflow():
    """With capacity_factor << 1 most tokens overflow and pass zeros."""
    blk = MoEBlock(num_experts=2, d_model=4, d_hidden=8, seed=0)
    x = jnp.asarray(np.random.RandomState(1).randn(64, 4)
                    .astype(np.float32))
    y, _ = moe_ffn(x, *blk.params(), capacity_factor=0.05)
    routed = (jnp.abs(y).sum(-1) > 1e-6).sum()
    assert int(routed) <= 2 * max(1, int(0.05 * 64 / 2))


def test_moe_gradients_finite_and_balanced_loss():
    mesh = mesh_mod.make_mesh({"ep": 4}, devices=jax.devices()[:4])
    blk = MoEBlock(num_experts=4, d_model=8, d_hidden=16, seed=2)
    x = jnp.asarray(np.random.RandomState(2).randn(32, 8)
                    .astype(np.float32))

    def loss(params):
        y, aux = moe_ffn(x, *params, mesh=mesh)
        return (y ** 2).mean() + 0.01 * aux

    g = jax.grad(loss)(blk.params())
    for leaf in g:
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
    # router must receive gradient (through gate and aux loss)
    assert np.abs(np.asarray(g[0])).max() > 0


def test_gluon_moe_block_trains():
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    mx.random.seed(0)
    moe = gluon.contrib.nn.MoEFFN(num_experts=4, d_model=8, d_hidden=16)
    moe.initialize(mx.init.Xavier())
    moe.hybridize()
    x = nd.random.uniform(shape=(32, 8))
    target = nd.array(np.sin(x.asnumpy() * 2))
    tr = gluon.Trainer(moe.collect_params(), "adam",
                       {"learning_rate": 1e-2})
    losses = []
    for _ in range(30):
        with autograd.record():
            y, aux = moe(x)
            loss = ((y - target) ** 2).mean() + 0.01 * aux.sum()
        loss.backward()
        tr.step(1)
        losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    with pytest.raises(ValueError):
        gluon.contrib.nn.MoEFFN(num_experts=1, d_model=4, d_hidden=4)


def test_moe_accepts_sequence_input():
    """(batch, seq, d_model) transformer activations flatten through
    the token axis and come back in shape."""
    blk = MoEBlock(num_experts=4, d_model=8, d_hidden=16, seed=4)
    x3 = jnp.asarray(np.random.RandomState(3).randn(2, 16, 8)
                     .astype(np.float32))
    y3, aux = moe_ffn(x3, *blk.params())
    assert y3.shape == (2, 16, 8)
    y2, _ = moe_ffn(x3.reshape(32, 8), *blk.params())
    assert np.allclose(np.asarray(y3).reshape(32, 8), np.asarray(y2),
                       atol=1e-6)
