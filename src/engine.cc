// Native async dependency engine for the TPU framework.
//
// Ref (behavioral parity, not translation): include/mxnet/engine.h,
// src/engine/threaded_engine.{h,cc}, src/engine/naive_engine.cc.
//
// Role in the TPU build: XLA/PjRt already serializes *device* work, so
// the native engine schedules the HOST side — decode threads, checkpoint
// writes, H2D staging, prefetch — with the same read/write-variable
// dependency contract the reference enforces for every op:
//   * multiple readers of a var may run concurrently (RAR),
//   * a writer is exclusive against readers and writers (RAW/WAR/WAW),
//   * grants are FIFO per var, so writers cannot starve.
// Ops are pushed with (const_vars, mutable_vars); an op runs once every
// var it touches has granted access.  NaiveEngine mode executes each op
// synchronously at push time (the reference's debugging fallback via
// MXNET_ENGINE_TYPE=NaiveEngine).
//
// Exposed as a flat C ABI (ref: the MXEngine* corner of c_api) consumed
// by ctypes from python (mxnet_tpu/utils/native.py).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <deque>
#include <functional>
#include <mutex>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace mxtpu {

using Fn = std::function<void()>;

struct Opr;

// A waiter queued on a variable: the op plus whether it wants write access.
struct VarWaiter {
  Opr* opr;
  bool write;
};

// Per-variable scheduling state (ref: ThreadedVar's pending-op chain).
struct Var {
  std::deque<VarWaiter> queue;  // FIFO of ops not yet granted this var
  int active_readers = 0;
  bool active_writer = false;
  bool dead = false;  // DeleteVariable processed; id will be reclaimed
};

struct Opr {
  Fn fn;
  std::vector<uint64_t> const_vars;
  std::vector<uint64_t> mutable_vars;
  // Number of vars that have not yet granted access (+1 sentinel held
  // during Push so a racing grant can't schedule the op early).
  std::atomic<int> wait{0};
};

class Engine {
 public:
  Engine(int num_workers, bool naive) : naive_(naive) {
    if (!naive_) {
      if (num_workers < 1) num_workers = 1;
      workers_.reserve(num_workers);
      for (int i = 0; i < num_workers; ++i)
        workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~Engine() {
    WaitForAll();
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      shutdown_ = true;
    }
    ready_cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  uint64_t NewVariable() {
    std::lock_guard<std::mutex> lk(state_mu_);
    uint64_t id = next_var_id_++;
    vars_.emplace(id, Var{});
    return id;
  }

  // Schedules var removal behind all currently queued ops on it
  // (ref: ThreadedEngine::DeleteVariable pushes a write op).
  void DeleteVariable(uint64_t var) {
    Push([this, var] {
      // runs with exclusive write access; erase under state_mu_ at
      // completion is handled by marking dead — OnComplete skips dead
      // vars' grant pass and erases them.
      std::lock_guard<std::mutex> lk(state_mu_);
      auto it = vars_.find(var);
      if (it != vars_.end()) it->second.dead = true;
    }, {}, {var});
  }

  void Push(Fn fn, std::vector<uint64_t> cvars, std::vector<uint64_t> mvars) {
    if (naive_) {
      fn();  // NaiveEngine: everything synchronous, deps trivially met
      return;
    }
    Opr* op = new Opr();
    op->fn = std::move(fn);
    op->const_vars = std::move(cvars);
    op->mutable_vars = std::move(mvars);
    // Normalize (ref: the engine CHECKs disjointness; here we repair):
    // dedup each list, and a var appearing in both is mutable-only —
    // otherwise the op would wait on its own read grant forever.
    auto dedup = [](std::vector<uint64_t>& v) {
      std::sort(v.begin(), v.end());
      v.erase(std::unique(v.begin(), v.end()), v.end());
    };
    dedup(op->const_vars);
    dedup(op->mutable_vars);
    op->const_vars.erase(
        std::remove_if(op->const_vars.begin(), op->const_vars.end(),
                       [&](uint64_t c) {
                         return std::binary_search(op->mutable_vars.begin(),
                                                   op->mutable_vars.end(), c);
                       }),
        op->const_vars.end());
    int nvars = static_cast<int>(op->const_vars.size() +
                                 op->mutable_vars.size());
    op->wait.store(nvars + 1, std::memory_order_relaxed);
    pending_.fetch_add(1, std::memory_order_relaxed);
    int granted = 0;
    {
      std::lock_guard<std::mutex> lk(state_mu_);
      for (uint64_t v : op->const_vars)
        if (Request(v, op, /*write=*/false)) ++granted;
      for (uint64_t v : op->mutable_vars)
        if (Request(v, op, /*write=*/true)) ++granted;
    }
    // drop sentinel + immediately granted vars
    if (op->wait.fetch_sub(granted + 1) == granted + 1) Schedule(op);
  }

  void WaitForVar(uint64_t var) {
    if (naive_) return;
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    Push([&] {
      std::lock_guard<std::mutex> lk(mu);
      done = true;
      cv.notify_one();
    }, {var}, {});
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }

  void WaitForAll() {
    if (naive_) return;
    std::unique_lock<std::mutex> lk(pending_mu_);
    pending_cv_.wait(lk, [this] {
      return pending_.load(std::memory_order_acquire) == 0;
    });
  }

 private:
  // state_mu_ held. Returns true if access granted immediately.
  bool Request(uint64_t vid, Opr* op, bool write) {
    Var& v = vars_[vid];
    if (v.queue.empty()) {
      if (write && v.active_readers == 0 && !v.active_writer) {
        v.active_writer = true;
        return true;
      }
      if (!write && !v.active_writer) {
        ++v.active_readers;
        return true;
      }
    }
    v.queue.push_back({op, write});
    return false;
  }

  void Schedule(Opr* op) {
    {
      std::lock_guard<std::mutex> lk(ready_mu_);
      ready_.push_back(op);
    }
    ready_cv_.notify_one();
  }

  void Grant(Opr* op, std::vector<Opr*>* runnable) {
    if (op->wait.fetch_sub(1) == 1) runnable->push_back(op);
  }

  // state_mu_ held: release one var the finished op held, then hand the
  // var to the longest-waiting compatible ops (FIFO; batches consecutive
  // readers, stops at the first writer — the no-starvation policy).
  void Release(uint64_t vid, bool write, std::vector<Opr*>* runnable) {
    auto it = vars_.find(vid);
    if (it == vars_.end()) return;
    Var& v = it->second;
    if (write)
      v.active_writer = false;
    else
      --v.active_readers;
    while (!v.queue.empty()) {
      VarWaiter w = v.queue.front();
      if (w.write) {
        if (v.active_readers == 0 && !v.active_writer) {
          v.active_writer = true;
          v.queue.pop_front();
          Grant(w.opr, runnable);
        }
        break;
      }
      if (v.active_writer) break;
      ++v.active_readers;
      v.queue.pop_front();
      Grant(w.opr, runnable);
    }
    if (v.dead && v.queue.empty() && v.active_readers == 0 &&
        !v.active_writer)
      vars_.erase(it);
  }

  void OnComplete(Opr* op) {
    std::vector<Opr*> runnable;
    {
      std::lock_guard<std::mutex> lk(state_mu_);
      for (uint64_t v : op->const_vars) Release(v, false, &runnable);
      for (uint64_t v : op->mutable_vars) Release(v, true, &runnable);
    }
    for (Opr* r : runnable) Schedule(r);
    delete op;
    if (pending_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      std::lock_guard<std::mutex> lk(pending_mu_);
      pending_cv_.notify_all();
    }
  }

  void WorkerLoop() {
    for (;;) {
      Opr* op;
      {
        std::unique_lock<std::mutex> lk(ready_mu_);
        ready_cv_.wait(lk, [this] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        op = ready_.front();
        ready_.pop_front();
      }
      op->fn();
      OnComplete(op);
    }
  }

  const bool naive_;
  std::mutex state_mu_;  // guards vars_ and all Var state
  std::unordered_map<uint64_t, Var> vars_;
  uint64_t next_var_id_ = 1;

  std::mutex ready_mu_;
  std::condition_variable ready_cv_;
  std::deque<Opr*> ready_;
  bool shutdown_ = false;

  std::atomic<long long> pending_{0};
  std::mutex pending_mu_;
  std::condition_variable pending_cv_;

  std::vector<std::thread> workers_;
};

}  // namespace mxtpu

extern "C" {

typedef void (*MXTPUEngineFn)(void*);

void* MXTPUEngineCreate(int num_workers, int naive) {
  return new mxtpu::Engine(num_workers, naive != 0);
}

void MXTPUEngineFree(void* h) { delete static_cast<mxtpu::Engine*>(h); }

uint64_t MXTPUEngineNewVariable(void* h) {
  return static_cast<mxtpu::Engine*>(h)->NewVariable();
}

void MXTPUEngineDeleteVariable(void* h, uint64_t var) {
  static_cast<mxtpu::Engine*>(h)->DeleteVariable(var);
}

void MXTPUEnginePushAsync(void* h, MXTPUEngineFn fn, void* ctx,
                          const uint64_t* const_vars, int n_const,
                          const uint64_t* mutable_vars, int n_mut) {
  static_cast<mxtpu::Engine*>(h)->Push(
      [fn, ctx] { fn(ctx); },
      std::vector<uint64_t>(const_vars, const_vars + n_const),
      std::vector<uint64_t>(mutable_vars, mutable_vars + n_mut));
}

void MXTPUEngineWaitForVar(void* h, uint64_t var) {
  static_cast<mxtpu::Engine*>(h)->WaitForVar(var);
}

void MXTPUEngineWaitForAll(void* h) {
  static_cast<mxtpu::Engine*>(h)->WaitForAll();
}

// Random-DAG equivalence fuzz (ref: tests/cpp/engine/threaded_engine_test.cc
// runs random dependency graphs on naive vs threaded engines and compares).
// Builds n_ops random ops over n_vars int64 cells; each op reads up to 3
// cells and combines them into one written cell with a deterministic mix.
// Returns 0 if the threaded engine's final state matches the naive one.
int MXTPUEngineSelfTest(uint64_t seed, int n_vars, int n_ops,
                        int num_workers) {
  std::mt19937_64 rng(seed);
  struct Step {
    std::vector<int> reads;
    int writes;
  };
  std::vector<Step> steps;
  steps.reserve(n_ops);
  for (int i = 0; i < n_ops; ++i) {
    Step s;
    std::uniform_int_distribution<int> pick(0, n_vars - 1);
    int nr = static_cast<int>(rng() % 4);
    for (int r = 0; r < nr; ++r) s.reads.push_back(pick(rng));
    s.writes = pick(rng);
    // dedup: a var both read and written must be listed once as mutable
    s.reads.erase(std::remove(s.reads.begin(), s.reads.end(), s.writes),
                  s.reads.end());
    std::sort(s.reads.begin(), s.reads.end());
    s.reads.erase(std::unique(s.reads.begin(), s.reads.end()),
                  s.reads.end());
    steps.push_back(std::move(s));
  }

  auto run = [&](bool naive) {
    std::vector<int64_t> cells(n_vars);
    for (int i = 0; i < n_vars; ++i) cells[i] = i + 1;
    mxtpu::Engine eng(num_workers, naive);
    std::vector<uint64_t> vids(n_vars);
    for (int i = 0; i < n_vars; ++i) vids[i] = eng.NewVariable();
    for (int i = 0; i < n_ops; ++i) {
      const Step& s = steps[i];
      std::vector<uint64_t> cv, mv{vids[s.writes]};
      for (int r : s.reads) cv.push_back(vids[r]);
      int64_t salt = i + 1;
      eng.Push([&cells, s, salt] {
        int64_t acc = salt;
        for (int r : s.reads) acc = acc * 1000003 + cells[r];
        cells[s.writes] = cells[s.writes] * 31 + acc;
      }, std::move(cv), std::move(mv));
    }
    eng.WaitForAll();
    return cells;
  };

  std::vector<int64_t> threaded = run(false);
  std::vector<int64_t> naive = run(true);
  return threaded == naive ? 0 : 1;
}

}  // extern "C"
