"""`make decode-smoke`: continuous-batching decode CI gate.

Starts a DecodeServer on the tiny reference decode model, pushes a
staggered 50-request burst (mixed prompt lengths, mixed generation
budgets) through a 4-slot arena, drains, and asserts the decode-tier
invariants from docs/serving.md:

    graph.post_warmup_compiles == 0            (closed compile surface)
    dispatch delta == decode_steps + batches   (exact accounting: one
                                                dispatch per token step,
                                                one per fused
                                                prefill+write admission
                                                group — nothing eager
                                                leaks into the loop)
    every admitted request resolves; streams match futures
    submitted == served + expired + failed + cancelled   (after drain)
    queue_depth == live_slots == 0             (after drain)
    disarmed fault-point + telemetry hooks are the module no-ops with
    a ~ns hot-loop budget

Exit code 0 = every invariant holds.  Runs on the CPU backend so it is
chip-independent.
"""
import json
import sys
import time


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import _imperative, engine, serve
    from mxnet_tpu.telemetry import tracer

    attempts, slots = 50, 4
    mx.random.seed(0)
    model = serve.TinyDecoder(vocab=64, embed=16)
    model.initialize(mx.init.Xavier())
    spec = serve.BucketSpec(batch_sizes=(1, 2, 4), example_shape=(None,),
                            lengths=(4, 8), dtype="int32")
    srv = serve.DecodeServer(model, spec, max_slots=slots, max_len=32,
                             max_queue=attempts + 8)
    srv.start()

    d0 = _imperative.device_dispatch_count()
    rng = np.random.RandomState(0)
    handles, budgets = [], []
    streams = {}
    for i in range(attempts):
        prompt = rng.randint(0, 64, size=int(rng.randint(2, 9))) \
            .astype(np.int32)
        mnt = int(rng.randint(1, 13))
        h = srv.submit(prompt, max_new_tokens=mnt)
        handles.append(h)
        budgets.append(mnt)
        if i % 3 == 0:
            time.sleep(0.002)       # staggered offered load
        if i == 7:
            # one streamed consumer: tokens must arrive incrementally
            # and match the future exactly
            streams[7] = h
    seqs = [h.result(timeout=300) for h in handles]
    streamed = list(streams[7]) if 7 in streams else []
    srv.drain()
    d1 = _imperative.device_dispatch_count()
    s = srv.stats()
    print(json.dumps(s, default=str))

    failures = []

    def check(name, cond):
        if not cond:
            failures.append(name)

    check("zero post-warmup compiles",
          s["graph"]["post_warmup_compiles"] == 0)
    check("exact dispatch accounting (steps + admission groups)",
          d1 - d0 == s["decode_steps"] + s["batches"])
    check("every admitted request resolved",
          s["served"] == s["submitted"] == attempts)
    check("every sequence hit its budget",
          all(len(seq) == mnt for seq, mnt in zip(seqs, budgets)))
    check("stream matches future",
          streamed == list(seqs[7]))
    check("accounting invariant",
          s["served"] + s["expired_deadline"] + s["failed"]
          + s["cancelled"] == s["submitted"])
    check("drain left zero queued work", s["queue_depth"] == 0)
    check("drain left zero live slots", s["in_flight"] == 0
          and s["slots"]["live"] == 0)
    check("warmup covered the whole prefill grid",
          s["warmup_batches"] == len(spec.bucket_shapes()))
    check("every request admitted", s["admitted"] == attempts)
    check("tokens == sum of budgets", s["tokens"] == sum(budgets))
    check("continuous batching beat one-step-per-token",
          s["decode_steps"] < s["tokens"])
    check("TTFT and per-token latency recorded",
          s["ttft"]["count"] == attempts
          and s["token_latency"]["count"] == s["decode_steps"])

    # disarmed-hook overhead budget: the decode loop calls
    # engine.fault_point + the tracer hooks once per token boundary, so
    # both must be the module no-ops with ~ns cost when nothing is armed
    check("fault point disarmed", engine.fault_point is engine._fault_noop)
    check("tracer disarmed", tracer.span_begin is tracer._noop)
    fire = engine.fault_point
    t0 = time.perf_counter()
    for _ in range(200_000):
        fire("serve.decode")
    dt = time.perf_counter() - t0
    check("disarmed fault-point budget (200k fires < 2s)", dt < 2.0)

    if failures:
        print("decode-smoke FAILED: " + "; ".join(failures),
              file=sys.stderr)
        return 1
    print(f"decode-smoke OK: {s['served']} served, {s['tokens']} tokens "
          f"in {s['decode_steps']} step dispatches "
          f"(occupancy={s['slots']['occupancy']}), "
          f"ttft_p99={s['ttft']['p99_ms']}ms, "
          f"token_p99={s['token_latency']['p99_ms']}ms, "
          f"disarmed_overhead_ns={dt / 200_000 * 1e9:.0f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
