"""External operator libraries (ref: python/mxnet/library.py —
mx.library.load() for dynamic custom-op libs).

The reference dlopens a .so that registers ops through the C API; the
TPU-native analogue is a python plugin module that calls
``mxnet_tpu.ops.registry.register`` (pure-jax kernels need no ABI).
``load`` accepts a path to such a .py file, imports it (registration
side effects run), and regenerates the nd/sym wrappers so the new ops
appear on both fronts.
"""
from __future__ import annotations

import importlib.util
import os
import sys

from .base import MXNetError


def load(path, verbose=True):
    """Import a plugin file; its register() calls add ops to the shared
    registry. Returns the loaded module."""
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        raise MXNetError(f"library not found: {path}")
    if not path.endswith(".py"):
        raise MXNetError(
            "mxnet_tpu custom-op libraries are python plugin modules "
            f"(pure-jax kernels), got {path!r}; see docs/MIGRATION.md")
    name = "mxtpu_plugin_" + os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    from .ops import registry

    before = set(registry.list_ops())
    spec.loader.exec_module(mod)
    added = sorted(set(registry.list_ops()) - before)
    # surface the new ops (and their aliases) on BOTH generated fronts,
    # mirroring the import-time codegen loops
    from .ndarray import ops as _gen
    from .ops.registry import get as _get
    from .symbol import symbol as _sym

    seen = set()
    for op in added:
        entry = _get(op)
        if id(entry) in seen:
            continue
        seen.add(id(entry))
        w = entry.wrapper or _gen.make_op_wrapper(entry)
        if entry.wrapper is not None:
            sw = _sym._unsupported_symbolically(entry)
        elif entry.name in _sym._NN_PARAM_SUFFIX:
            sw = _sym._make_nn_wrapper(entry)
        else:
            sw = _sym._sym_wrapper(entry)
        for n in (entry.name,) + entry.aliases:
            setattr(_gen, n, w)
            if not hasattr(_sym, n):
                setattr(_sym, n, sw)
    if verbose and added:
        print(f"loaded {len(added)} ops from {path}: {added}")
    return mod
