// Flat C ABI over the framework surface (multi-frontend boundary).
//
// Ref (behavioral parity, not translation): include/mxnet/c_api.h +
// src/c_api/c_api.cc — the reference exposes ~400 flat MX* functions so
// Scala/R/Julia/C++ frontends can drive the same core the Python
// frontend uses.
//
// TPU-native inversion: the reference's core is C++ with Python layered
// on top; here the core orchestration layer is Python (driving XLA/PjRt,
// which are themselves native) with C++ subsystems below it (engine,
// storage, IO).  The multi-frontend boundary therefore EMBEDS the
// orchestrator: this library hosts a CPython interpreter and exposes the
// same flat, stateless C calling convention the reference does —
// handle-based NDArrays, string-keyed op invoke against the central op
// registry, MXTPUGetLastError error protocol.  Any language with a C FFI
// gets the full op surface (260+ registered ops), not a re-binding of a
// Python API.
//
// Thread contract: every entry point takes the GIL (PyGILState_Ensure),
// so frontends may call from any thread — same guarantee as the
// reference's engine-backed C API.
//
// Build: make lib/libmxtpu_capi.so   (links libpython3.x)
// Test: tests/test_capi.py compiles+runs a C driver against this ABI.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

std::mutex g_init_mu;
bool g_initialized = false;
PyObject* g_nd_module = nullptr;      // mxnet_tpu.ndarray.ops (op table)
PyObject* g_nd_array_fn = nullptr;    // mxnet_tpu.nd.array
PyObject* g_registry = nullptr;       // mxnet_tpu.ops.registry module
PyObject* g_capi = nullptr;           // mxnet_tpu.capi helper module
PyObject* g_autograd = nullptr;       // mxnet_tpu.autograd module

thread_local std::string tl_last_error;

// Cached storage for MXTPUListAllOpNames (stable pointers after init).
std::vector<std::string> g_op_names;
std::vector<const char*> g_op_name_ptrs;

void set_error_from_python() {
  // No pending Python exception means the specific message was already
  // recorded in tl_last_error by C-side validation (e.g. capacity
  // checks) — keep it rather than clobbering with the generic string.
  if (!PyErr_Occurred()) {
    if (tl_last_error.empty()) tl_last_error = "unknown error";
    return;
  }
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  tl_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) tl_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Gil {
  PyGILState_STATE st;
  Gil() : st(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(st); }
};

// dtype codes follow the reference's mshadow enum order
// (c_api: 0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64); we add 7=bf16.
const char* dtype_name(int code) {
  switch (code) {
    case 0: return "float32";
    case 1: return "float64";
    case 2: return "float16";
    case 3: return "uint8";
    case 4: return "int32";
    case 5: return "int8";
    case 6: return "int64";
    case 7: return "bfloat16";
    default: return nullptr;
  }
}

int dtype_code(const std::string& name) {
  if (name == "float32") return 0;
  if (name == "float64") return 1;
  if (name == "float16") return 2;
  if (name == "uint8") return 3;
  if (name == "int32") return 4;
  if (name == "int8") return 5;
  if (name == "int64") return 6;
  if (name == "bfloat16") return 7;
  return -1;
}

}  // namespace

MXTPU_API const char* MXTPUGetLastError() { return tl_last_error.c_str(); }

namespace {
// Import the framework and snapshot the op table (GIL held inside).
int init_body(const char* platform) {
  Gil gil;
  do {
    if (platform && platform[0]) {
      std::string code =
          "import jax\n"
          "jax.config.update('jax_platforms', '" + std::string(platform) +
          "')\n";
      if (PyRun_SimpleString(code.c_str()) != 0) {
        tl_last_error = "failed to pin jax platform";
        return -1;
      }
    }
    PyObject* mx = PyImport_ImportModule("mxnet_tpu");
    if (!mx) break;
    PyObject* nd = PyObject_GetAttrString(mx, "nd");
    Py_DECREF(mx);
    if (!nd) break;
    g_nd_module = nd;
    g_nd_array_fn = PyObject_GetAttrString(nd, "array");
    if (!g_nd_array_fn) break;
    g_registry = PyImport_ImportModule("mxnet_tpu.ops.registry");
    if (!g_registry) break;
    g_capi = PyImport_ImportModule("mxnet_tpu.capi");
    if (!g_capi) break;
    g_autograd = PyImport_ImportModule("mxnet_tpu.autograd");
    if (!g_autograd) break;
    // snapshot op names once; pointers stay valid for the process life
    PyObject* keys = PyObject_CallMethod(g_registry, "list_ops", nullptr);
    if (!keys) break;
    PyObject* keys_list = PySequence_List(keys);
    Py_DECREF(keys);
    if (!keys_list) break;
    keys = keys_list;
    Py_ssize_t n = PyList_Size(keys);
    g_op_names.reserve(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char* c = PyUnicode_AsUTF8(PyList_GetItem(keys, i));
      if (c) g_op_names.emplace_back(c);
    }
    Py_DECREF(keys);
    for (auto& s : g_op_names) g_op_name_ptrs.push_back(s.c_str());
    g_initialized = true;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}
}  // namespace

// Initialize the embedded interpreter + framework. `platform` may be
// nullptr/"" (leave backend selection to the environment) or "cpu" /
// "tpu" to pin jax's platform before first device use.
MXTPU_API int MXTPUCAPIInit(const char* platform) {
  std::lock_guard<std::mutex> lk(g_init_mu);
  if (g_initialized) return 0;
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: the host app owns them
    we_initialized = true;
  }
  int rc = init_body(platform);
  if (we_initialized) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // other frontend threads' PyGILState_Ensure can proceed (the
    // any-thread contract in the header comment).
    PyEval_SaveThread();
  }
  return rc;
}

MXTPU_API int MXTPUListAllOpNames(int* out_size, const char*** out_array) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  *out_size = static_cast<int>(g_op_name_ptrs.size());
  *out_array = g_op_name_ptrs.data();
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray handles: an opaque pointer owning one PyObject* (the NDArray).
// ---------------------------------------------------------------------------

typedef void* NDArrayHandle;

MXTPU_API int MXTPUNDArrayCreate(const void* data, const int64_t* shape,
                                 int ndim, int dtype, const char* ctx,
                                 NDArrayHandle* out) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  const char* dt = dtype_name(dtype);
  if (!dt || ndim < 0 || ndim > 16) {
    tl_last_error = "bad dtype code or ndim";
    return -1;
  }
  Gil gil;
  do {
    // build via numpy: np.frombuffer(bytes, dtype).reshape(shape)
    PyObject* np = PyImport_ImportModule("numpy");
    if (!np) break;
    PyObject* npdt = PyObject_CallMethod(np, "dtype", "s", dt);
    if (!npdt) { Py_DECREF(np); break; }
    PyObject* itemsize_o = PyObject_GetAttrString(npdt, "itemsize");
    int64_t itemsize = PyLong_AsLongLong(itemsize_o);
    Py_DECREF(itemsize_o);
    int64_t count = 1;
    for (int i = 0; i < ndim; ++i) count *= shape[i];
    PyObject* buf = PyBytes_FromStringAndSize(
        static_cast<const char*>(data), count * itemsize);
    PyObject* flat = buf ? PyObject_CallMethod(np, "frombuffer", "OO",
                                               buf, npdt)
                         : nullptr;
    Py_XDECREF(buf);
    Py_DECREF(npdt);
    Py_DECREF(np);
    if (!flat) break;
    PyObject* shp = PyTuple_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
    PyObject* arr = PyObject_CallMethod(flat, "reshape", "O", shp);
    Py_DECREF(flat);
    Py_DECREF(shp);
    if (!arr) break;
    PyObject* kwargs = PyDict_New();
    if (ctx && ctx[0]) {
      PyObject* mx = PyImport_ImportModule("mxnet_tpu");
      PyObject* ctx_mod = mx ? PyObject_GetAttrString(mx, "Context")
                             : nullptr;
      Py_XDECREF(mx);
      if (!ctx_mod) { Py_DECREF(arr); Py_DECREF(kwargs); break; }
      // ctx strings look like "cpu(0)" / "xla(0)"
      std::string s(ctx);
      auto lp = s.find('(');
      std::string dev = s.substr(0, lp);
      int idx = lp == std::string::npos
                    ? 0
                    : std::atoi(s.c_str() + lp + 1);
      PyObject* ctx_obj = PyObject_CallFunction(ctx_mod, "si",
                                                dev.c_str(), idx);
      Py_DECREF(ctx_mod);
      if (!ctx_obj) { Py_DECREF(arr); Py_DECREF(kwargs); break; }
      PyDict_SetItemString(kwargs, "ctx", ctx_obj);
      Py_DECREF(ctx_obj);
    }
    PyObject* args = PyTuple_Pack(1, arr);
    PyObject* nd_arr = PyObject_Call(g_nd_array_fn, args, kwargs);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    Py_DECREF(arr);
    if (!nd_arr) break;
    *out = nd_arr;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUNDArrayFree(NDArrayHandle h) {
  if (!h) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(h));
  return 0;
}

MXTPU_API int MXTPUNDArrayGetShape(NDArrayHandle h, int* out_ndim,
                                   int64_t* out_shape /* >=16 slots */) {
  Gil gil;
  do {
    PyObject* shp = PyObject_GetAttrString(static_cast<PyObject*>(h),
                                           "shape");
    if (!shp) break;
    Py_ssize_t n = PyTuple_Size(shp);
    if (n > 16) { Py_DECREF(shp); tl_last_error = "ndim > 16"; return -1; }
    *out_ndim = static_cast<int>(n);
    for (Py_ssize_t i = 0; i < n; ++i)
      out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
    Py_DECREF(shp);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUNDArrayGetDType(NDArrayHandle h, int* out_dtype) {
  Gil gil;
  do {
    PyObject* dt = PyObject_GetAttrString(static_cast<PyObject*>(h),
                                          "dtype");
    if (!dt) break;
    PyObject* nm = PyObject_GetAttrString(dt, "name");
    if (!nm) {
      PyErr_Clear();  // the AttributeError must not leak into the
      nm = PyObject_Str(dt);  // fallback call or a later API call
    }
    Py_DECREF(dt);
    if (!nm) break;
    const char* c = PyUnicode_AsUTF8(nm);
    int code = c ? dtype_code(c) : -1;
    Py_DECREF(nm);
    if (code < 0) { tl_last_error = "unmapped dtype"; return -1; }
    *out_dtype = code;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// Synchronously copy device data out to a host buffer (asnumpy +
// memcpy) — the MXNDArraySyncCopyToCPU equivalent.
MXTPU_API int MXTPUNDArraySyncCopyToCPU(NDArrayHandle h, void* out,
                                        int64_t nbytes) {
  Gil gil;
  do {
    PyObject* npy = PyObject_CallMethod(static_cast<PyObject*>(h),
                                        "asnumpy", nullptr);
    if (!npy) break;
    PyObject* contig = PyObject_CallMethod(npy, "tobytes", nullptr);
    Py_DECREF(npy);
    if (!contig) break;
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(contig, &buf, &len) != 0) {
      Py_DECREF(contig);
      break;
    }
    if (len != nbytes) {
      Py_DECREF(contig);
      tl_last_error = "size mismatch: have " + std::to_string(len) +
                      " bytes, caller asked " + std::to_string(nbytes);
      return -1;
    }
    std::memcpy(out, buf, len);
    Py_DECREF(contig);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// ---------------------------------------------------------------------------
// Op invoke: the MXImperativeInvokeEx equivalent. Inputs are NDArray
// handles; kwargs arrive as parallel string arrays and are parsed as
// Python literals (so "(2, 2)" / "1e-5" / "'valid'" all work — same
// stringly-typed convention as the reference's C API).
// ---------------------------------------------------------------------------

MXTPU_API int MXTPUImperativeInvoke(const char* op_name,
                                    NDArrayHandle* inputs, int num_inputs,
                                    const char** keys, const char** vals,
                                    int num_kwargs,
                                    NDArrayHandle* outputs,
                                    int* num_outputs /* in: capacity */) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  Gil gil;
  do {
    PyObject* fn = PyObject_GetAttrString(g_nd_module, op_name);
    if (!fn) break;
    PyObject* args = PyTuple_New(num_inputs);
    for (int i = 0; i < num_inputs; ++i) {
      PyObject* o = static_cast<PyObject*>(inputs[i]);
      Py_INCREF(o);
      PyTuple_SET_ITEM(args, i, o);
    }
    PyObject* kwargs = PyDict_New();
    PyObject* ast = PyImport_ImportModule("ast");
    PyObject* lit = ast ? PyObject_GetAttrString(ast, "literal_eval")
                        : nullptr;
    Py_XDECREF(ast);
    bool kw_ok = true;
    for (int i = 0; i < num_kwargs && kw_ok; ++i) {
      PyObject* v = lit ? PyObject_CallFunction(lit, "s", vals[i])
                        : nullptr;
      if (!v) {  // not a literal -> pass the raw string (e.g. act_type)
        PyErr_Clear();
        v = PyUnicode_FromString(vals[i]);
      }
      if (!v || PyDict_SetItemString(kwargs, keys[i], v) != 0)
        kw_ok = false;
      Py_XDECREF(v);
    }
    Py_XDECREF(lit);
    PyObject* res = kw_ok ? PyObject_Call(fn, args, kwargs) : nullptr;
    Py_DECREF(fn);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    if (!res) break;
    // normalize to a list of outputs
    PyObject* res_list;
    if (PyTuple_Check(res) || PyList_Check(res)) {
      res_list = PySequence_Fast(res, "op outputs");
      Py_DECREF(res);
    } else {
      res_list = PyTuple_Pack(1, res);
      Py_DECREF(res);
    }
    if (!res_list) break;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(res_list);
    if (n > *num_outputs) {
      Py_DECREF(res_list);
      tl_last_error = "output capacity too small: need " +
                      std::to_string(n);
      return -1;
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* o = PySequence_Fast_GET_ITEM(res_list, i);
      Py_INCREF(o);
      outputs[i] = o;
    }
    *num_outputs = static_cast<int>(n);
    Py_DECREF(res_list);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// Block until all async work is visible (mx.nd.waitall).
MXTPU_API int MXTPUWaitAll() {
  Gil gil;
  do {
    PyObject* r = PyObject_CallMethod(g_nd_module, "waitall", nullptr);
    if (!r) break;
    Py_DECREF(r);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// Save/load NDArrays in the reference-compatible .params container
// (MXNDArraySave/Load equivalents; keys optional for save).
// Load a .params artifact (ref: MXNDArrayLoad). Each returned handle
// carries its own reference — free with MXTPUNDArrayFree (same caller-
// owned contract as the reference). The handle/name POINTER ARRAYS live
// in thread-local storage valid until the next Load on this thread;
// names is empty for list-form artifacts.
static thread_local std::vector<NDArrayHandle> tl_load_handles;
static thread_local std::vector<std::string> tl_load_names;
static thread_local std::vector<const char*> tl_load_name_ptrs;

MXTPU_API int MXTPUNDArrayLoad(const char* fname, int* out_size,
                               NDArrayHandle** out_handles,
                               int* out_name_size,
                               const char*** out_names) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  Gil gil;
  do {
    PyObject* r = PyObject_CallMethod(g_nd_module, "load", "s", fname);
    if (!r) break;
    tl_load_handles.clear();
    tl_load_names.clear();
    tl_load_name_ptrs.clear();
    if (PyDict_Check(r)) {
      PyObject *key, *val;
      Py_ssize_t pos = 0;
      while (PyDict_Next(r, &pos, &key, &val)) {
        const char* k = PyUnicode_AsUTF8(key);
        if (!k) {
          // drop the references taken so far — they would otherwise
          // leak when the next Load clears the vector without DECREF
          for (auto h : tl_load_handles)
            Py_DECREF(static_cast<PyObject*>(h));
          tl_load_handles.clear();
          tl_load_names.clear();
          Py_DECREF(r);
          goto fail;
        }
        tl_load_names.emplace_back(k);
        Py_INCREF(val);
        tl_load_handles.push_back(val);
      }
    } else {
      PyObject* seq = PySequence_Fast(r, "nd.load returned non-sequence");
      if (!seq) { Py_DECREF(r); break; }
      Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
      for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject* o = PySequence_Fast_GET_ITEM(seq, i);
        Py_INCREF(o);
        tl_load_handles.push_back(o);
      }
      Py_DECREF(seq);
    }
    Py_DECREF(r);
    for (auto& s : tl_load_names) tl_load_name_ptrs.push_back(s.c_str());
    *out_size = static_cast<int>(tl_load_handles.size());
    *out_handles = tl_load_handles.data();
    *out_name_size = static_cast<int>(tl_load_name_ptrs.size());
    *out_names = tl_load_name_ptrs.data();
    return 0;
  } while (false);
fail:
  set_error_from_python();
  return -1;
}

// Op self-documentation through the C boundary (ref: MXSymbolGetAtomicSymbolInfo
// role): returns the rendered docstring for a registered op. The pointer is
// owned by a thread-local string valid until the next call on the thread.
static thread_local std::string tl_op_doc;

MXTPU_API int MXTPUOpGetDoc(const char* op_name, const char** out_doc) {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return -1;
  }
  Gil gil;
  do {
    PyObject* entry = PyObject_CallMethod(g_registry, "get", "s", op_name);
    if (!entry) break;
    PyObject* doc = PyObject_CallMethod(entry, "build_doc", nullptr);
    Py_DECREF(entry);
    if (!doc) break;
    if (doc == Py_None) {  // undocumented op: legitimately empty
      tl_op_doc.clear();
    } else {
      const char* c = PyUnicode_AsUTF8(doc);
      if (!c) { Py_DECREF(doc); break; }
      tl_op_doc = c;
    }
    Py_DECREF(doc);
    *out_doc = tl_op_doc.c_str();
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// ===========================================================================
// Trainable surface (VERDICT r3 #4): symbol compose, executor
// bind/forward/backward, CachedOp, autograd, optimizer update, data
// iterators, kvstore.  Logic lives in mxnet_tpu/capi.py (embedded
// orchestrator); these entry points marshal handles and scalars only.
// All opaque handles own one PyObject*; free any of them with the
// matching *Free (they share one implementation).
// ===========================================================================

typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* CachedOpHandle;
typedef void* OptimizerHandle;
typedef void* DataIterHandle;
typedef void* KVStoreHandle;

namespace {

bool require_init() {
  if (!g_initialized) {
    tl_last_error = "MXTPUCAPIInit not called";
    return false;
  }
  return true;
}

// Build a Python list from C handles, INCREFing each element.
PyObject* handle_list(void** handles, int n) {
  PyObject* l = PyList_New(n);
  if (!l) return nullptr;
  for (int i = 0; i < n; ++i) {
    PyObject* o = static_cast<PyObject*>(handles[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(l, i, o);
  }
  return l;
}

PyObject* str_list(const char** strs, int n) {
  PyObject* l = PyList_New(n);
  if (!l) return nullptr;
  for (int i = 0; i < n; ++i) {
    PyObject* s = PyUnicode_FromString(strs[i]);
    if (!s) { Py_DECREF(l); return nullptr; }
    PyList_SET_ITEM(l, i, s);
  }
  return l;
}

// Copy a Python list of NDArrays out to caller handles (new refs).
int list_to_handles(PyObject* list, void** out, int* n_out /* in: cap */) {
  PyObject* seq = PySequence_Fast(list, "expected a sequence");
  if (!seq) return -1;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (n > *n_out) {
    Py_DECREF(seq);
    tl_last_error = "output capacity too small: need " + std::to_string(n);
    return -1;
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PySequence_Fast_GET_ITEM(seq, i);
    Py_INCREF(o);
    out[i] = o;
  }
  *n_out = static_cast<int>(n);
  Py_DECREF(seq);
  return 0;
}

// Thread-local string-list storage for List* style returns (valid until
// the next List* call on the same thread — same contract as the
// reference's MXSymbolListArguments).
thread_local std::vector<std::string> tl_strlist;
thread_local std::vector<const char*> tl_strlist_ptrs;

int return_str_list(PyObject* list, int* out_size, const char*** out) {
  PyObject* seq = PySequence_Fast(list, "expected a name list");
  if (!seq) return -1;
  tl_strlist.clear();
  tl_strlist_ptrs.clear();
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PySequence_Fast_GET_ITEM(seq, i));
    if (!c) { Py_DECREF(seq); return -1; }
    tl_strlist.emplace_back(c);
  }
  Py_DECREF(seq);
  for (auto& s : tl_strlist) tl_strlist_ptrs.push_back(s.c_str());
  *out_size = static_cast<int>(tl_strlist_ptrs.size());
  *out = tl_strlist_ptrs.data();
  return 0;
}

// Call mxnet_tpu.capi.<fn>(*args). Returns a new reference or nullptr
// (python error pending).
PyObject* capi_call(const char* fn, PyObject* args /* stolen */) {
  if (!args) return nullptr;
  PyObject* f = PyObject_GetAttrString(g_capi, fn);
  if (!f) { Py_DECREF(args); return nullptr; }
  PyObject* r = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_DECREF(args);
  return r;
}

int handle_free(void* h) {
  if (!h) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(h));
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// Symbol (ref: MXSymbolCreateVariable / CreateAtomicSymbol + Compose /
// ListArguments / SaveToJSON)

MXTPU_API int MXTPUSymbolCreateVariable(const char* name,
                                        SymbolHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call("symbol_variable",
                          Py_BuildValue("(s)", name));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

// Atomic symbol creation + composition in one call (the reference
// splits these into CreateAtomicSymbol + Compose; one shot is the same
// surface without partially-composed intermediate states).  `in_keys`
// may be NULL (positional inputs in the op's declared order).
MXTPU_API int MXTPUSymbolInvoke(const char* op_name, SymbolHandle* inputs,
                                int num_inputs, const char** in_keys,
                                const char** keys, const char** vals,
                                int num_kwargs, const char* name,
                                SymbolHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  do {
    PyObject* ins = handle_list(inputs, num_inputs);
    PyObject* ikeys = in_keys ? str_list(in_keys, num_inputs) : Py_None;
    if (ikeys == Py_None) Py_INCREF(Py_None);
    PyObject* ks = str_list(keys, num_kwargs);
    PyObject* vs = str_list(vals, num_kwargs);
    if (!ins || !ikeys || !ks || !vs) {
      Py_XDECREF(ins); Py_XDECREF(ikeys); Py_XDECREF(ks); Py_XDECREF(vs);
      break;
    }
    PyObject* r = capi_call(
        "symbol_invoke",
        Py_BuildValue("(sNNNNs)", op_name, ins, ikeys, ks, vs,
                      name ? name : ""));
    if (!r) break;
    *out = r;
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUSymbolListArguments(SymbolHandle sym, int* out_size,
                                       const char*** out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call("symbol_list_arguments",
                          Py_BuildValue("(O)",
                                        static_cast<PyObject*>(sym)));
  if (!r || return_str_list(r, out_size, out) != 0) {
    Py_XDECREF(r);
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUSymbolListAuxiliaryStates(SymbolHandle sym,
                                             int* out_size,
                                             const char*** out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call("symbol_list_aux",
                          Py_BuildValue("(O)",
                                        static_cast<PyObject*>(sym)));
  if (!r || return_str_list(r, out_size, out) != 0) {
    Py_XDECREF(r);
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

// Shape inference across the ABI (ref: MXSymbolInferShape). Known
// shapes arrive as (names, ndims, concatenated dims); results land in
// thread-local arrays valid until the next call on this thread:
// per-array ndim plus one concatenated dim vector, args first then aux.
static thread_local std::vector<int> tl_shape_ndims;
static thread_local std::vector<int64_t> tl_shape_dims;

MXTPU_API int MXTPUSymbolInferShape(SymbolHandle sym, int num_known,
                                    const char** known_names,
                                    const int* known_ndims,
                                    const int64_t* known_dims_concat,
                                    int* out_num_args, int* out_num_aux,
                                    const int** out_ndims,
                                    const int64_t** out_dims_concat) {
  if (!require_init()) return -1;
  Gil gil;
  do {
    PyObject* names = str_list(known_names, num_known);
    if (!names) break;
    PyObject* shapes = PyList_New(num_known);
    if (!shapes) { Py_DECREF(names); break; }
    int64_t off = 0;
    for (int i = 0; i < num_known; ++i) {
      PyObject* t = PyTuple_New(known_ndims[i]);
      for (int d = 0; d < known_ndims[i]; ++d)
        PyTuple_SET_ITEM(t, d,
                         PyLong_FromLongLong(known_dims_concat[off + d]));
      off += known_ndims[i];
      PyList_SET_ITEM(shapes, i, t);
    }
    PyObject* r = capi_call(
        "symbol_infer_shape",
        Py_BuildValue("(ONN)", static_cast<PyObject*>(sym), names,
                      shapes));
    if (!r) break;
    PyObject *arg_shapes, *aux_shapes;
    if (!PyArg_ParseTuple(r, "OO", &arg_shapes, &aux_shapes)) {
      Py_DECREF(r);
      break;
    }
    tl_shape_ndims.clear();
    tl_shape_dims.clear();
    int n_args = 0, n_aux = 0;
    bool ok = true;
    for (PyObject* lst : {arg_shapes, aux_shapes}) {
      Py_ssize_t n = PyList_Size(lst);
      (lst == arg_shapes ? n_args : n_aux) = static_cast<int>(n);
      for (Py_ssize_t i = 0; i < n && ok; ++i) {
        PyObject* t = PyList_GetItem(lst, i);
        PyObject* tup = PySequence_Tuple(t);
        if (!tup) { ok = false; break; }
        Py_ssize_t nd = PyTuple_Size(tup);
        tl_shape_ndims.push_back(static_cast<int>(nd));
        for (Py_ssize_t d = 0; d < nd; ++d)
          tl_shape_dims.push_back(
              PyLong_AsLongLong(PyTuple_GetItem(tup, d)));
        Py_DECREF(tup);
      }
    }
    Py_DECREF(r);
    if (!ok) break;
    *out_num_args = n_args;
    *out_num_aux = n_aux;
    *out_ndims = tl_shape_ndims.data();
    *out_dims_concat = tl_shape_dims.data();
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

// In-place device copy dst <- src (ref: MXNDArraySyncCopyFromNDArray);
// feeds new batches into bound executor args.
MXTPU_API int MXTPUNDArrayCopyFrom(NDArrayHandle dst, NDArrayHandle src) {
  Gil gil;
  PyObject* r = PyObject_CallMethod(static_cast<PyObject*>(src),
                                    "copyto", "O",
                                    static_cast<PyObject*>(dst));
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

static thread_local std::string tl_symbol_json;

MXTPU_API int MXTPUSymbolSaveToJSON(SymbolHandle sym,
                                    const char** out_json) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call("symbol_tojson",
                          Py_BuildValue("(O)",
                                        static_cast<PyObject*>(sym)));
  if (!r) { set_error_from_python(); return -1; }
  const char* c = PyUnicode_AsUTF8(r);
  if (!c) { Py_DECREF(r); set_error_from_python(); return -1; }
  tl_symbol_json = c;
  Py_DECREF(r);
  *out_json = tl_symbol_json.c_str();
  return 0;
}

MXTPU_API int MXTPUSymbolCreateFromJSON(const char* json,
                                        SymbolHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call("symbol_fromjson", Py_BuildValue("(s)", json));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUSymbolFree(SymbolHandle h) { return handle_free(h); }

// ---------------------------------------------------------------------------
// Executor (ref: MXExecutorBindEX / Forward / Backward / Outputs).
// Gradient buffers are allocated inside bind for every non-'null' arg;
// read them back per-name with MXTPUExecutorArgGrad after backward.

MXTPU_API int MXTPUExecutorBind(SymbolHandle sym, const char* ctx,
                                NDArrayHandle* args, int num_args,
                                const char* grad_req,
                                NDArrayHandle* auxs, int num_aux,
                                ExecutorHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* a = handle_list(args, num_args);
  PyObject* x = handle_list(auxs, num_aux);
  if (!a || !x) {
    Py_XDECREF(a); Py_XDECREF(x);
    set_error_from_python();
    return -1;
  }
  PyObject* r = capi_call(
      "executor_bind",
      Py_BuildValue("(OsNsN)", static_cast<PyObject*>(sym),
                    ctx ? ctx : "", a, grad_req ? grad_req : "write", x));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUExecutorForward(ExecutorHandle ex, int is_train,
                                   NDArrayHandle* outputs,
                                   int* num_outputs /* in: capacity */) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "executor_forward",
      Py_BuildValue("(Oi)", static_cast<PyObject*>(ex), is_train));
  if (!r || list_to_handles(r, outputs, num_outputs) != 0) {
    Py_XDECREF(r);
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUExecutorBackward(ExecutorHandle ex,
                                    NDArrayHandle* out_grads,
                                    int num_out_grads) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* g = out_grads ? handle_list(out_grads, num_out_grads)
                          : (Py_INCREF(Py_None), Py_None);
  if (!g) { set_error_from_python(); return -1; }
  PyObject* r = capi_call(
      "executor_backward",
      Py_BuildValue("(ON)", static_cast<PyObject*>(ex), g));
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUExecutorArgGrad(ExecutorHandle ex, const char* name,
                                   NDArrayHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "executor_arg_grad",
      Py_BuildValue("(Os)", static_cast<PyObject*>(ex), name));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUExecutorFree(ExecutorHandle h) {
  return handle_free(h);
}

// ---------------------------------------------------------------------------
// CachedOp (ref: MXCreateCachedOpEx / MXInvokeCachedOpEx): whole graph
// as ONE XLA computation, executable cache keyed by shapes+train flag.
// Inputs arrive in list_arguments order followed by aux states.

MXTPU_API int MXTPUCreateCachedOp(SymbolHandle sym, CachedOpHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "cachedop_create",
      Py_BuildValue("(O)", static_cast<PyObject*>(sym)));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUInvokeCachedOp(CachedOpHandle op,
                                  NDArrayHandle* inputs, int num_inputs,
                                  int is_train, NDArrayHandle* outputs,
                                  int* num_outputs /* in: capacity */) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* ins = handle_list(inputs, num_inputs);
  if (!ins) { set_error_from_python(); return -1; }
  PyObject* r = capi_call(
      "cachedop_invoke",
      Py_BuildValue("(ONi)", static_cast<PyObject*>(op), ins, is_train));
  if (!r || list_to_handles(r, outputs, num_outputs) != 0) {
    Py_XDECREF(r);
    set_error_from_python();
    return -1;
  }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUCachedOpFree(CachedOpHandle h) {
  return handle_free(h);
}

// ---------------------------------------------------------------------------
// Autograd (ref: MXAutogradSetIsRecording/SetIsTraining/MarkVariables/
// BackwardEx + MXNDArrayGetGrad) — the imperative training path.

MXTPU_API int MXTPUAutogradSetIsRecording(int is_recording, int* prev) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = PyObject_CallMethod(g_autograd, "set_recording", "i",
                                    is_recording);
  if (!r) { set_error_from_python(); return -1; }
  if (prev) *prev = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUAutogradSetIsTraining(int is_training, int* prev) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = PyObject_CallMethod(g_autograd, "set_training", "i",
                                    is_training);
  if (!r) { set_error_from_python(); return -1; }
  if (prev) *prev = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUAutogradMarkVariables(int num, NDArrayHandle* vars,
                                         NDArrayHandle* gradients) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* v = handle_list(vars, num);
  PyObject* g = handle_list(gradients, num);
  if (!v || !g) {
    Py_XDECREF(v); Py_XDECREF(g);
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(g_autograd, "mark_variables", "NN",
                                    v, g);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUAutogradBackward(int num_heads, NDArrayHandle* heads,
                                    NDArrayHandle* head_grads,
                                    int retain_graph) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* h = handle_list(heads, num_heads);
  PyObject* hg = head_grads ? handle_list(head_grads, num_heads)
                            : (Py_INCREF(Py_None), Py_None);
  if (!h || !hg) {
    Py_XDECREF(h); Py_XDECREF(hg);
    set_error_from_python();
    return -1;
  }
  PyObject* r = PyObject_CallMethod(g_autograd, "backward", "NNi", h, hg,
                                    retain_graph);
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUNDArrayGetGrad(NDArrayHandle h, NDArrayHandle* out) {
  Gil gil;
  PyObject* g = PyObject_GetAttrString(static_cast<PyObject*>(h), "grad");
  if (!g) { set_error_from_python(); return -1; }
  if (g == Py_None) {
    Py_DECREF(g);
    tl_last_error = "array has no gradient (mark_variables not called "
                    "or backward not run)";
    return -1;
  }
  *out = g;
  return 0;
}

// ---------------------------------------------------------------------------
// Optimizer (ref: MXOptimizerCreateOptimizer / MXOptimizerUpdate;
// per-index state lives behind the handle, as on a kvstore server).

MXTPU_API int MXTPUOptimizerCreate(const char* name, const char** keys,
                                   const char** vals, int num_kwargs,
                                   OptimizerHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* ks = str_list(keys, num_kwargs);
  PyObject* vs = str_list(vals, num_kwargs);
  if (!ks || !vs) {
    Py_XDECREF(ks); Py_XDECREF(vs);
    set_error_from_python();
    return -1;
  }
  PyObject* r = capi_call("optimizer_create",
                          Py_BuildValue("(sNN)", name, ks, vs));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUOptimizerUpdate(OptimizerHandle opt, int index,
                                   NDArrayHandle weight,
                                   NDArrayHandle grad) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "optimizer_update",
      Py_BuildValue("(OiOO)", static_cast<PyObject*>(opt), index,
                    static_cast<PyObject*>(weight),
                    static_cast<PyObject*>(grad)));
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUOptimizerFree(OptimizerHandle h) {
  return handle_free(h);
}

// ---------------------------------------------------------------------------
// Data iterators (ref: MXDataIterCreateIter / Next / GetData /
// GetLabel / BeforeFirst) — iterator registry by name, stringly-typed
// kwargs, one current batch per handle.

MXTPU_API int MXTPUDataIterCreate(const char* name, const char** keys,
                                  const char** vals, int num_kwargs,
                                  DataIterHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* ks = str_list(keys, num_kwargs);
  PyObject* vs = str_list(vals, num_kwargs);
  if (!ks || !vs) {
    Py_XDECREF(ks); Py_XDECREF(vs);
    set_error_from_python();
    return -1;
  }
  PyObject* r = capi_call("dataiter_create",
                          Py_BuildValue("(sNN)", name, ks, vs));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUDataIterNext(DataIterHandle it, int* out_more) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "dataiter_next", Py_BuildValue("(O)", static_cast<PyObject*>(it)));
  if (!r) { set_error_from_python(); return -1; }
  *out_more = PyObject_IsTrue(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUDataIterGetData(DataIterHandle it,
                                   NDArrayHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "dataiter_data", Py_BuildValue("(O)", static_cast<PyObject*>(it)));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUDataIterGetLabel(DataIterHandle it,
                                    NDArrayHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "dataiter_label",
      Py_BuildValue("(O)", static_cast<PyObject*>(it)));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUDataIterBeforeFirst(DataIterHandle it) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "dataiter_reset",
      Py_BuildValue("(O)", static_cast<PyObject*>(it)));
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXTPUDataIterFree(DataIterHandle h) {
  return handle_free(h);
}

// ---------------------------------------------------------------------------
// KVStore (ref: MXKVStoreCreate / Init / Push / Pull — int keys, the
// classic worker protocol; all types map onto the ICI/DCN collective
// facades in mxnet_tpu/kvstore.py).

MXTPU_API int MXTPUKVStoreCreate(const char* type, KVStoreHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call("kvstore_create",
                          Py_BuildValue("(s)", type ? type : "local"));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

namespace {
// Shared marshalling for every keyed kvstore call; `outs` is optional
// (push/pull/init take one handle array, pushpull takes vals+outs).
int kvstore_keyed_call(const char* fn, KVStoreHandle kv, int num,
                       const int* keys, NDArrayHandle* vals,
                       int priority, NDArrayHandle* outs = nullptr) {
  Gil gil;
  PyObject* ks = PyList_New(num);
  if (!ks) { set_error_from_python(); return -1; }
  for (int i = 0; i < num; ++i)
    PyList_SET_ITEM(ks, i, PyLong_FromLong(keys[i]));
  PyObject* vs = handle_list(vals, num);
  PyObject* os = outs ? handle_list(outs, num) : nullptr;
  if (!vs || (outs && !os)) {
    Py_DECREF(ks);
    Py_XDECREF(vs);
    Py_XDECREF(os);
    set_error_from_python();
    return -1;
  }
  PyObject* r = outs
      ? capi_call(fn, Py_BuildValue("(ONNNi)",
                                    static_cast<PyObject*>(kv), ks, vs,
                                    os, priority))
      : capi_call(fn, Py_BuildValue("(ONNi)",
                                    static_cast<PyObject*>(kv), ks, vs,
                                    priority));
  if (!r) { set_error_from_python(); return -1; }
  Py_DECREF(r);
  return 0;
}
}  // namespace

MXTPU_API int MXTPUKVStoreInit(KVStoreHandle kv, int num, const int* keys,
                               NDArrayHandle* vals) {
  if (!require_init()) return -1;
  return kvstore_keyed_call("kvstore_init", kv, num, keys, vals, 0);
}

MXTPU_API int MXTPUKVStorePush(KVStoreHandle kv, int num, const int* keys,
                               NDArrayHandle* vals, int priority) {
  if (!require_init()) return -1;
  return kvstore_keyed_call("kvstore_push", kv, num, keys, vals,
                            priority);
}

MXTPU_API int MXTPUKVStorePull(KVStoreHandle kv, int num, const int* keys,
                               NDArrayHandle* outs, int priority) {
  if (!require_init()) return -1;
  return kvstore_keyed_call("kvstore_pull", kv, num, keys, outs,
                            priority);
}

// Fused push+pull (ref: MXKVStorePushPullEx): vals in, reduced vals
// out, one call — the Trainer.step all-reduce spelling.
MXTPU_API int MXTPUKVStorePushPull(KVStoreHandle kv, int num,
                                   const int* keys, NDArrayHandle* vals,
                                   NDArrayHandle* outs, int priority) {
  if (!require_init()) return -1;
  return kvstore_keyed_call("kvstore_pushpull", kv, num, keys, vals,
                            priority, outs);
}

MXTPU_API int MXTPUKVStoreFree(KVStoreHandle h) { return handle_free(h); }

// ---------------------------------------------------------------------------
// Version + NDArray view ops (ref: MXGetVersion, MXNDArrayReshape64,
// MXNDArraySlice)

static thread_local std::string tl_version;

MXTPU_API int MXTPUGetVersion(const char** out) {
  if (!require_init()) return -1;
  Gil gil;
  do {
    PyObject* mx = PyImport_ImportModule("mxnet_tpu");
    if (!mx) break;
    PyObject* v = PyObject_GetAttrString(mx, "__version__");
    Py_DECREF(mx);
    if (!v) break;
    const char* c = PyUnicode_AsUTF8(v);
    if (!c) { Py_DECREF(v); break; }
    tl_version = c;
    Py_DECREF(v);
    *out = tl_version.c_str();
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}

MXTPU_API int MXTPUNDArrayReshape(NDArrayHandle h, int ndim,
                                  const int64_t* shape,
                                  NDArrayHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* shp = PyList_New(ndim);
  if (!shp) { set_error_from_python(); return -1; }
  for (int i = 0; i < ndim; ++i)
    PyList_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* r = capi_call(
      "ndarray_reshape",
      Py_BuildValue("(ON)", static_cast<PyObject*>(h), shp));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUNDArraySlice(NDArrayHandle h, int64_t begin,
                                int64_t end, NDArrayHandle* out) {
  if (!require_init()) return -1;
  Gil gil;
  PyObject* r = capi_call(
      "ndarray_slice",
      Py_BuildValue("(OLL)", static_cast<PyObject*>(h),
                    static_cast<long long>(begin),
                    static_cast<long long>(end)));
  if (!r) { set_error_from_python(); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXTPUNDArraySave(const char* fname, NDArrayHandle* handles,
                               const char** keys, int num) {
  Gil gil;
  do {
    PyObject* d;
    if (keys) {
      d = PyDict_New();
      for (int i = 0; i < num; ++i)
        PyDict_SetItemString(d, keys[i],
                             static_cast<PyObject*>(handles[i]));
    } else {
      d = PyList_New(num);
      for (int i = 0; i < num; ++i) {
        PyObject* o = static_cast<PyObject*>(handles[i]);
        Py_INCREF(o);
        PyList_SET_ITEM(d, i, o);
      }
    }
    PyObject* r = PyObject_CallMethod(g_nd_module, "save", "sO", fname, d);
    Py_DECREF(d);
    if (!r) break;
    Py_DECREF(r);
    return 0;
  } while (false);
  set_error_from_python();
  return -1;
}
