"""mxnet_tpu.resilience — fault-injection harness + self-healing
training supervisor.

Covers the subsystem's contract (docs/resilience.md): a disarmed fault
point is a pure no-op (zero-overhead acceptance check); an armed
FaultPlan replays deterministically; the RetryPolicy backs off
exponentially, bounded and seeded; exception classification routes
every fault class to its recovery; a kill-at-step-N SIGTERM resumes
bit-identically (params + RNG + batch sequence); a corrupt-latest
checkpoint falls back to the previous retained step loudly; the
watchdog diagnostic names the stuck phase; and the resilience profiler
section window-scopes like every other section.
"""
import json
import os
import signal
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, engine, gluon, pipeline
from mxnet_tpu import profiler, resilience
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import dist
from mxnet_tpu.resilience import (FaultPlan, FaultSpec, Preempted,
                                  ResumeRequired, RetryPolicy, Supervisor,
                                  TransientFault, WatchdogTimeout, armed,
                                  classify, resilience_stats,
                                  reset_resilience_stats)

FEAT, BS, N = 4, 4, 32


# ---------------------------------------------------------------------------
# fault harness


def test_fault_point_noop_when_disarmed_zero_overhead():
    """No plan armed: the hook IS the module no-op (nothing evaluated
    beyond the call), and a hot-loop of fires costs no measurable
    time."""
    assert engine.fault_point is engine._fault_noop
    fire = engine.fault_point
    t0 = time.perf_counter()
    for _ in range(100_000):
        fire("kvstore.pushpull")
    dt = time.perf_counter() - t0
    # ~10ns/call in practice; 1.5s is 15us/call — pure anti-flake margin
    assert dt < 1.5, f"disarmed fault point cost {dt:.3f}s / 100k calls"


def test_fault_plan_arm_disarm_rebinds_hook():
    plan = FaultPlan([{"site": "x", "action": "delay", "delay_s": 0.0}])
    plan.arm()
    try:
        assert engine.fault_points_armed()
        assert getattr(engine.fault_point, "__self__", None) is plan
    finally:
        plan.disarm()
    assert engine.fault_point is engine._fault_noop


def test_fault_plan_deterministic_replay():
    """Same plan (seed + specs) + same hit sequence => identical fire
    record, including probabilistic specs."""
    spec = [{"site": "s", "action": "delay", "delay_s": 0.0,
             "prob": 0.3, "times": None}]

    def drive(plan):
        with armed(plan):
            for _ in range(200):
                engine.fault_point("s")
        return [(f["site"], f["hit"]) for f in plan.fired()]

    a = drive(FaultPlan(spec, seed=11))
    b = drive(FaultPlan(spec, seed=11))
    c = drive(FaultPlan(spec, seed=12))
    assert a == b and len(a) > 0
    assert a != c, "different seeds should draw different fire patterns"
    # reset() rewinds counters AND per-spec RNGs: the same object replays
    plan = FaultPlan(spec, seed=11)
    assert drive(plan) == drive(plan.reset()) == a


def test_fault_spec_match_on_hit_times():
    plan = FaultPlan([
        {"site": "train.step", "action": "raise", "match": {"step": 2},
         "times": 1},
        {"site": "io", "action": "raise", "on_hit": 3},
    ])
    with armed(plan):
        engine.fault_point("train.step", step=0)
        engine.fault_point("train.step", step=1)
        with pytest.raises(TransientFault):
            engine.fault_point("train.step", step=2)
        engine.fault_point("train.step", step=2)  # times=1: exhausted
        engine.fault_point("io")
        engine.fault_point("io")
        with pytest.raises(TransientFault):
            engine.fault_point("io")
    assert plan.hits("train.step") == 4
    assert [f["site"] for f in plan.fired()] == ["train.step", "io"]


def test_fault_plan_validation_and_env_parse(tmp_path):
    with pytest.raises(MXNetError, match="unknown fault action"):
        FaultSpec("s", "explode")
    with pytest.raises(MXNetError, match="on_hit is 1-based"):
        FaultSpec("s", "raise", on_hit=0)
    with pytest.raises(MXNetError, match="prob"):
        FaultSpec("s", "raise", prob=1.5)
    with pytest.raises(MXNetError, match="neither a JSON object"):
        resilience.parse_plan("{not json")
    with pytest.raises(MXNetError, match="'faults' list"):
        resilience.parse_plan('{"seed": 1}')
    # inline JSON and file forms both parse
    blob = ('{"seed": 5, "faults": '
            '[{"site": "s", "action": "raise", "on_hit": 1}]}')
    p = tmp_path / "plan.json"
    p.write_text(blob)
    for src in (blob, str(p)):
        plan = resilience.parse_plan(src)
        assert plan.seed == 5 and len(plan._specs) == 1


# ---------------------------------------------------------------------------
# retry policy


def test_retry_policy_backoff_bounded_and_deterministic():
    p = RetryPolicy(max_retries=4, base_delay=0.1, max_delay=0.5,
                    multiplier=2.0)
    assert [p.delay_for(i) for i in (1, 2, 3, 4, 5)] == \
        [0.1, 0.2, 0.4, 0.5, 0.5]
    assert p.should_retry(4) and not p.should_retry(5)
    # jitter is drawn from the policy's own seeded RNG: replayable
    a = RetryPolicy(max_retries=3, base_delay=0.1, jitter=0.5, seed=9)
    b = RetryPolicy(max_retries=3, base_delay=0.1, jitter=0.5, seed=9)
    da = [a.delay_for(i) for i in (1, 2, 3)]
    assert da == [b.delay_for(i) for i in (1, 2, 3)]
    assert all(0.05 <= d <= 0.9 for d in da)
    with pytest.raises(MXNetError, match="max_retries"):
        RetryPolicy(max_retries=-1)


def test_retry_policy_call_retries_then_raises():
    p = RetryPolicy(max_retries=2, base_delay=0.001)
    calls = []

    def flaky(succeed_at):
        calls.append(1)
        if len(calls) < succeed_at:
            raise TransientFault("flaky")
        return "ok"

    assert p.call(flaky, 3) == "ok"
    assert len(calls) == 3
    calls.clear()
    with pytest.raises(TransientFault):
        p.call(flaky, 10)
    assert len(calls) == 3  # initial + max_retries


# ---------------------------------------------------------------------------
# classification


def test_classification_routes_every_fault_class():
    assert classify(TransientFault("x")) == "transient"
    assert classify(Preempted("x")) == "preemption"
    assert classify(WatchdogTimeout("x")) == "watchdog"
    assert classify(MXNetError(dist._peer_death_msg("barrier hung"))) \
        == "peer_death"
    assert classify(MXNetError(
        "f.params: corrupt or truncated NDArray file")) \
        == "corrupt_checkpoint"
    assert classify(MXNetError("collective UNAVAILABLE: try again")) \
        == "transient"
    assert classify(MXNetError("shape mismatch for 'w'")) == "fatal"
    assert classify(ValueError("boom")) == "fatal"
    # serving shed-don't-retry classes (ISSUE 14): their "try again"-
    # shaped messages must NOT classify as transient — a retry loop
    # would hammer an overloaded pool / re-spend an exhausted budget
    from mxnet_tpu.serve.batcher import (DeadlineExceededError,
                                         ServerOverloadedError)

    assert classify(ServerOverloadedError(
        "request queue full (8); retry with backoff")) == "overloaded"
    assert classify(DeadlineExceededError(
        "deadline passed while queued")) == "deadline"
    assert classify(MXNetError("DEADLINE_EXCEEDED: deadline exceeded")) \
        == "deadline"


def test_peer_death_msg_names_rank_and_supervisor():
    msg = dist._peer_death_msg("allreduce hung")
    assert "rank 0 of" in msg
    assert "resilience.Supervisor" in msg
    assert "resume" in msg


def test_dist_timeout_env_bounds_collectives(monkeypatch):
    """MXTPU_DIST_TIMEOUT (new spelling) bounds _bounded; the timeout
    error is the diagnosable peer-death message."""
    monkeypatch.setenv("MXTPU_DIST_TIMEOUT", "0.2")
    with pytest.raises(MXNetError) as ei:
        dist._bounded(lambda: time.sleep(10), "test collective")
    assert "MXTPU_DIST_TIMEOUT=0.2" in str(ei.value)
    assert "likely dead or partitioned" in str(ei.value)
    assert classify(ei.value) == "peer_death"
    # legacy spelling still honored as the fallback
    monkeypatch.delenv("MXTPU_DIST_TIMEOUT")
    monkeypatch.setenv("MXTPU_BARRIER_TIMEOUT_S", "0.2")
    with pytest.raises(MXNetError, match="likely dead"):
        dist._bounded(lambda: time.sleep(10), "test collective")
    # 0 = wait forever: the call just runs
    monkeypatch.setenv("MXTPU_BARRIER_TIMEOUT_S", "0")
    assert dist._bounded(lambda: 42, "fast") == 42


# ---------------------------------------------------------------------------
# supervised training: shared harness


def _make_data(n=N):
    rng = np.random.RandomState(0)
    return [(rng.rand(FEAT).astype(np.float32), np.float32(i % 2))
            for i in range(n)]


def _build_model():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=FEAT, activation="relu"),
            nn.Dense(1, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    # dist_sync (single-process it degrades to device semantics) with a
    # local update keeps the kvstore.pushpull fault point on the step
    # path
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05},
                            kvstore="dist_sync", update_on_kvstore=False)
    return net, trainer


def _params_np(net):
    return {k: v.data().asnumpy()
            for k, v in net._collect_params_with_prefix().items()}


def _supervised_run(ckdir, plan=None, save_every=1, n_data=N,
                    **sup_kwargs):
    """One full supervised training job; returns (final params, batch
    log, supervisor)."""
    if plan is not None:
        resilience.install_plan(plan)
    try:
        mgr = checkpoint.CheckpointManager(str(ckdir), keep_n=3)
        sup_kwargs.setdefault("retry",
                              RetryPolicy(max_retries=3, base_delay=0.001))
        sup = Supervisor(mgr, on_preemption="resume", max_restarts=4,
                         **sup_kwargs)
        data = _make_data(n_data)
        batches = {}

        def train(ctx):
            net, trainer = _build_model()
            pipe = (pipeline.Pipeline(data).shuffle(8, seed=5)
                    .batch(BS, last_batch="discard"))
            start = 0
            if ctx.manager.latest() is not None:
                meta = ctx.manager.restore(params=net, trainer=trainer,
                                           pipeline=pipe)
                start = meta["step"] + 1
            cur = {"step": start - 1}
            ctx.set_preemption_state(lambda: dict(
                step=cur["step"], params=net, trainer=trainer,
                pipeline=pipe))
            step = start
            for x, y in pipe:
                with autograd.record():
                    loss = ((net(x) - y.reshape((-1, 1))) ** 2).sum()
                loss.backward()
                trainer.step(BS)
                batches[step] = x.asnumpy().tobytes()
                cur["step"] = step
                save = dict(params=net, trainer=trainer, pipeline=pipe,
                            sync=True) if step % save_every == 0 else None
                ctx.step_done(step, save=save)
                step += 1
            return _params_np(net)

        return sup.run(train), batches, sup
    finally:
        if plan is not None:
            resilience.clear_plan()


# ---------------------------------------------------------------------------
# supervisor recovery paths


def test_supervisor_transient_retry(tmp_path):
    reset_resilience_stats()
    plan = FaultPlan([{"site": "kvstore.pushpull", "action": "raise",
                       "on_hit": 3}])
    ref, blog_ref, _ = _supervised_run(tmp_path / "ref")
    got, blog, _ = _supervised_run(tmp_path / "chaos", plan)
    assert [f["site"] for f in plan.fired()] == ["kvstore.pushpull"]
    stats = resilience_stats()
    assert stats["retries"].get("transient") == 1
    assert stats["restarts"] == 1
    assert stats["time_lost_ms"] > 0
    assert blog == blog_ref
    for k in ref:
        assert np.array_equal(ref[k], got[k]), f"param {k} diverged"


def test_supervisor_transient_budget_exhausts(tmp_path):
    plan = FaultPlan([{"site": "kvstore.pushpull", "action": "raise",
                       "times": None}])  # unbounded: never recovers
    with pytest.raises(MXNetError, match="persisted through"):
        _supervised_run(tmp_path, plan,
                        retry=RetryPolicy(max_retries=2, base_delay=0.001))


def test_supervisor_kill_at_step_resume_bit_identical(tmp_path):
    """The acceptance core: SIGTERM at step 3 (the PR-1 final-save hook
    fires), in-process restart, restore — final params AND the
    remaining batch sequence are bit-identical to the uninjected run,
    and recovery is visible in the profiler resilience section."""
    reset_resilience_stats()
    ref, blog_ref, _ = _supervised_run(tmp_path / "ref")
    plan = FaultPlan([{"site": "train.step", "action": "kill",
                       "match": {"step": 3}}])
    got, blog, _ = _supervised_run(tmp_path / "chaos", plan)
    assert plan.fired() and plan.fired()[0]["action"] == "kill"
    stats = resilience_stats()
    assert stats["restarts"] == 1
    assert stats["retries"].get("preemption") == 1
    assert blog.keys() == blog_ref.keys()
    assert blog == blog_ref, "batch sequence diverged after resume"
    for k in ref:
        assert np.array_equal(ref[k], got[k]), f"param {k} diverged"
    section = json.loads(profiler.dumps())["resilience"]
    assert section["restarts"] >= 1


def test_supervisor_preemption_exit_writes_resume_marker(tmp_path):
    """Default (real-preemption) policy: final save, resume marker,
    ResumeRequired — no in-process restart."""
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=2)
    sup = Supervisor(mgr, on_preemption="exit")
    w = mx.nd.ones((2, 2))

    def train(ctx):
        ctx.set_preemption_state(
            lambda: dict(step=7, params={"w": w}))
        ctx.step_done(7)
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(5)  # signal lands before this expires
        raise AssertionError("SIGTERM was swallowed")

    with pytest.raises(ResumeRequired, match="resume marker"):
        sup.run(train)
    assert mgr.latest() == 7, "final save must be committed before exit"
    marker = json.load(open(sup.resume_marker))
    assert marker["reason"] == "preemption"
    assert marker["latest_checkpoint"] == 7
    # the supervisor restored the original (default) SIGTERM disposition
    assert signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL,
                                                signal.default_int_handler)


def test_supervisor_fatal_errors_pass_through(tmp_path):
    sup = Supervisor(checkpoint.CheckpointManager(str(tmp_path)))

    def train(ctx):
        raise ValueError("a real bug, not a fault")

    with pytest.raises(ValueError, match="a real bug"):
        sup.run(train)
    assert resilience_stats() is not None  # no crash in telemetry


def test_supervisor_budgets_reset_on_progress(tmp_path):
    """Budgets are per stall point: a job making progress between
    flakes never exhausts max_retries, while a loop stuck at one step
    still trips the bound."""
    sup = Supervisor(checkpoint.CheckpointManager(str(tmp_path)),
                     on_preemption="resume",
                     retry=RetryPolicy(max_retries=1, base_delay=0.001))
    attempts = []

    def train(ctx):
        a = len(attempts)
        attempts.append(a)
        # each attempt completes one MORE step than the last, then
        # flakes: 4 transient failures total, but progress between each
        # resets the (max_retries=1) budget
        for step in range(a + 1):
            ctx.step_done(step)
        if a < 4:
            raise TransientFault(f"flake after step {a}")
        return "done"

    assert sup.run(train) == "done"
    assert len(attempts) == 5


def test_supervisor_exhausted_fallback_is_fatal(tmp_path):
    """restore()'s terminal every-step-failed error must NOT be
    classified as a restartable corrupt_checkpoint (restarting cannot
    fix it)."""
    err = MXNetError(
        f"no retained checkpoint under {tmp_path} is loadable — every "
        "step failed: step 2: corrupt or truncated NDArray file")
    assert classify(err) == "fatal"


def test_runcontext_heartbeat_feeds_watchdog(tmp_path):
    """A step-free tail longer than watchdog_sec survives when it
    heartbeats."""
    sup = Supervisor(checkpoint.CheckpointManager(str(tmp_path)),
                     watchdog_sec=0.4, max_restarts=0)

    def train(ctx):
        ctx.step_done(0)
        for _ in range(4):  # 0.8s of step-free "export" work
            time.sleep(0.2)
            ctx.heartbeat()
        return "exported"

    assert sup.run(train) == "exported"
    assert resilience_stats()["watchdog_fires"] == 0


# ---------------------------------------------------------------------------
# corrupt-latest checkpoint fallback (satellite regression, via the
# injected truncation fault)


def test_restore_falls_back_past_corrupt_latest(tmp_path):
    reset_resilience_stats()
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=3)
    w1, w2 = mx.nd.ones((3,)) * 1, mx.nd.ones((3,)) * 2
    mgr.save(1, params={"w": w1}, sync=True)
    plan = FaultPlan([{"site": "checkpoint.commit", "action": "truncate"}])
    with armed(plan):
        mgr.save(2, params={"w": w2}, sync=True)
    assert plan.fired(), "truncation fault must fire inside the commit"
    assert mgr.latest() == 2, "the truncated save still COMMITS"
    # auto-selection falls back loudly to step 1 instead of raising
    meta = mgr.restore()
    assert meta["step"] == 1
    assert np.array_equal(meta["params"]["w"].asnumpy(), w1.asnumpy())
    assert resilience_stats()["fallback_restores"] == 1
    # an explicit step= keeps strict semantics
    with pytest.raises(MXNetError, match="corrupt or truncated"):
        mgr.restore(step=2)


def test_restore_fallback_skips_component_free_steps(tmp_path):
    """Auto-resume also skips past a step that simply lacks a component
    the caller asked for (saved without trainer=): an older complete
    step still satisfies the restore."""
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=3)
    net, trainer = _build_model()
    x = mx.nd.ones((2, FEAT))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(BS)
    mgr.save(1, params=net, trainer=trainer, sync=True)
    mgr.save(2, params=net, sync=True)  # no trainer states at step 2
    net2, trainer2 = _build_model()
    meta = mgr.restore(params=net2, trainer=trainer2)
    assert meta["step"] == 1
    # explicit step= keeps strict semantics for the same condition
    with pytest.raises(MXNetError, match="saved without"):
        mgr.restore(step=2, params=net2, trainer=trainer2)


def test_restore_raises_when_every_step_corrupt(tmp_path):
    mgr = checkpoint.CheckpointManager(str(tmp_path), keep_n=3)
    plan = FaultPlan([{"site": "checkpoint.commit", "action": "truncate",
                       "times": None}])
    with armed(plan):
        mgr.save(1, params={"w": mx.nd.ones((3,))}, sync=True)
        mgr.save(2, params={"w": mx.nd.ones((3,))}, sync=True)
    with pytest.raises(MXNetError, match="every step failed"):
        mgr.restore()


# ---------------------------------------------------------------------------
# watchdog


def test_watchdog_diagnostic_names_stuck_phase(tmp_path):
    reset_resilience_stats()
    sup = Supervisor(checkpoint.CheckpointManager(str(tmp_path)),
                     watchdog_sec=0.4, max_restarts=0)

    def train(ctx):
        ctx.step_done(0)
        with profiler.op_scope("dist.allreduce", cat="operator"):
            time.sleep(30)  # interrupted by the watchdog
        raise AssertionError("watchdog never fired")

    t0 = time.monotonic()
    with pytest.raises(WatchdogTimeout) as ei:
        sup.run(train)
    assert time.monotonic() - t0 < 20
    msg = str(ei.value)
    assert "no training step completed" in msg
    assert "dist.allreduce" in msg, f"diagnostic must name the phase: {msg}"
    assert "last completed step: 0" in msg
    assert resilience_stats()["watchdog_fires"] == 1
    # tracking is disarmed after the run: scopes no longer registered
    with profiler.op_scope("after"):
        assert profiler.active_scopes() == {}


def test_watchdog_restart_counts_against_budget(tmp_path):
    reset_resilience_stats()
    calls = []
    sup = Supervisor(checkpoint.CheckpointManager(str(tmp_path)),
                     watchdog_sec=0.3, max_restarts=1)

    def train(ctx):
        calls.append(1)
        if len(calls) == 1:
            time.sleep(30)  # stall attempt 1
        return "done"

    assert sup.run(train) == "done"
    assert len(calls) == 2
    stats = resilience_stats()
    assert stats["retries"].get("watchdog") == 1


# ---------------------------------------------------------------------------
# profiler section scoping


def test_profiler_resilience_section_window_scoping():
    from mxnet_tpu.resilience import stats as rstats

    reset_resilience_stats()
    rstats.add("restarts")
    rstats.add_retry("transient", 2)
    rstats.add("time_lost_ms", 12.5)
    d = json.loads(profiler.dumps())
    assert d["resilience"]["restarts"] == 1
    assert d["resilience"]["retries"] == {"transient": 2}
    # reset=True scopes the section to the window like cachedGraph et al.
    json.loads(profiler.dumps(reset=True))
    d2 = json.loads(profiler.dumps())
    assert d2["resilience"]["restarts"] == 0
    assert d2["resilience"]["retries"] == {}
    assert d2["resilience"]["time_lost_ms"] == 0
    # table form renders the block (and resets under reset=True too)
    rstats.add_retry("watchdog")
    profiler.set_config(aggregate_stats=True)
    try:
        table = profiler.dumps(reset=True, format="table")
        assert "Resilience (supervisor):" in table
        assert "retries[watchdog]" in table
        assert json.loads(profiler.dumps())["resilience"]["retries"] == {}
    finally:
        profiler.set_config(aggregate_stats=False)


# ---------------------------------------------------------------------------
# multi-fault stress


@pytest.mark.slow
def test_multi_restart_stress_bit_identical(tmp_path):
    """Kill + two transients + a delayed h2d across one job: every
    recovery lands and the result still bit-matches the clean run."""
    reset_resilience_stats()
    ref, blog_ref, _ = _supervised_run(tmp_path / "ref", n_data=64)
    plan = FaultPlan([
        {"site": "train.step", "action": "kill", "match": {"step": 2}},
        {"site": "train.step", "action": "kill", "match": {"step": 9}},
        {"site": "kvstore.pushpull", "action": "raise", "on_hit": 7},
        {"site": "kvstore.pushpull", "action": "raise", "on_hit": 13},
        {"site": "engine.h2d", "action": "delay", "delay_s": 0.02,
         "times": 2},
    ], seed=3)
    got, blog, _ = _supervised_run(tmp_path / "chaos", plan, n_data=64)
    kinds = [f["action"] for f in plan.fired()]
    assert kinds.count("kill") == 2 and kinds.count("raise") == 2
    stats = resilience_stats()
    assert stats["restarts"] == 4
    assert stats["retries"] == {"preemption": 2, "transient": 2}
    assert blog == blog_ref
    for k in ref:
        assert np.array_equal(ref[k], got[k]), f"param {k} diverged"
