"""Resilience telemetry — the ``resilience`` profiler section.

Recovery must be OBSERVABLE to be trusted: after a chaos rehearsal (or
a real preemption) these counters answer "what did the supervisor
actually do" — how many times ``train_fn`` was re-invoked, which fault
classes forced a retry, whether a corrupt checkpoint silently fell back
to an older step, how often the progress watchdog fired, and how much
wall time recovery cost.

Window-scoped like the cachedGraph/trainerStep/dataPipeline sections:
``profiler.dumps(reset=True)`` resets them with the event buffer.
"""
from __future__ import annotations

import threading

_lock = threading.Lock()
_stats = {
    "restarts": 0,          # train_fn re-invocations (any fault class)
    "retries": {},          # fault class -> recovery count
    "fallback_restores": 0,  # restore() fell back past a corrupt newest
    "watchdog_fires": 0,    # progress watchdog expiries
    "time_lost_ms": 0.0,    # failure -> re-invocation wall time
    "resizes": 0,           # elastic world shrinks (peer death -> M)
    "ranks_lost": 0,        # ranks dropped across those resizes
    "reshard_ms": 0.0,      # checkpoint repartition wall time
}


def add(key, value=1):
    """Accumulate one scalar counter (thread-safe)."""
    with _lock:
        _stats[key] += value


def add_retry(fault_class, value=1):
    """Count one recovery under its fault class (thread-safe)."""
    with _lock:
        _stats["retries"][fault_class] = \
            _stats["retries"].get(fault_class, 0) + value


def resilience_stats():
    """Snapshot of the resilience counters since the last reset."""
    with _lock:
        s = dict(_stats)
        s["retries"] = dict(_stats["retries"])
    s["time_lost_ms"] = round(s["time_lost_ms"], 3)
    s["reshard_ms"] = round(s["reshard_ms"], 3)
    return s


def reset_resilience_stats():
    with _lock:
        for k in _stats:
            if k == "retries":
                _stats[k] = {}
            else:
                _stats[k] = 0.0 if isinstance(_stats[k], float) else 0
