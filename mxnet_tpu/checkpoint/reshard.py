"""Elastic checkpoint resharding — repartition a checkpoint saved at
world size N onto a job running at world size M (ROADMAP "elastic
world-size"; the redistribution idioms follow arXiv 2112.01075, the
ZeRO shard-file substrate arXiv 2004.13336).

Three legs, one per saved artifact kind:

- **Parameters / RNG** (``params-shard<r>.params``,
  ``rng-shard<r>.json``): data-parallel training replicates these
  across process ranks (every rank commits the same post-allreduce
  values, every rank seeds the same RNG stream), so the reshard is a
  shard-file REMAP — rank ``r`` of the new world reads saved shard
  :func:`source_rank`\\ ``(r, saved_world)``.
- **ZeRO-1 optimizer flat shards** (the ``"zero"`` snapshot inside
  ``trainer-shard<r>.states``): genuinely partitioned 1/world per
  rank.  :func:`reshard_zero_snapshot` gathers each chunk's rank
  shards on host, drops the old zero-pad, re-pads to the NEW world's
  ``zero_padded_size`` and re-slices per the new layout — pure
  reshaping, bit-exact, so N→M→N round-trips to the identical bytes.
  (Host-side gather is always possible here: the shards were
  serialized FROM host.  The device-side leg — landing the new shard
  straight on its replica — is the very next step's traced allgather
  in ``kvstore``; the restore path never materializes device copies
  of peers' shards.)
- **Input-pipeline state** (``pipeline-shard<r>.state``): the
  ``shard(num_replicas, rank)`` stage contract is rank-symmetric
  (every rank advances an identically-seeded upstream by identical
  group counts), so every rank's saved source cursor / shuffle ring /
  RNG state must AGREE.  :func:`merge_pipeline_states` verifies that
  agreement stage by stage and returns the merged (common) state,
  which loads into a pipeline rebuilt with ``shard(M, r)``.  Per-rank
  in-flight buffers that diverge (a batch-stage rollover remainder
  mid-group) cannot be repartitioned and raise loudly — checkpoint at
  a shard-group boundary (``ctx.step_done(save=...)`` does) or rebuild
  the pipeline from the epoch start.

``CheckpointManager.restore(strict_topology=True)`` disables all of
this and restores the old loud world-size rejection.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError


def source_rank(rank, saved_world):
    """The saved shard file rank ``r`` of the new world reads: its own
    when the saved world covers it, else ``r % saved_world`` (valid
    because data-parallel param/RNG shards are rank-replicated and
    pipeline state is rank-symmetric — see the module docstring)."""
    saved_world = max(int(saved_world), 1)
    rank = int(rank)
    return rank if rank < saved_world else rank % saved_world


def _book_reshard_ms(dt_s):
    """Book resharding wall time into the resilience telemetry
    (``reshard_ms`` in the profiler ``resilience`` section) when that
    tier is loaded; never a hard dependency."""
    try:
        from ..resilience import stats as _rstats

        _rstats.add("reshard_ms", float(dt_s) * 1e3)
    except Exception:  # pragma: no cover - resilience tier absent
        pass


# -- ZeRO-1 optimizer shards ------------------------------------------------


def _shard_np(s):
    """A shard slot as numpy (snapshots hold NDArrays live, numpy after
    a pickle round trip)."""
    return s.asnumpy() if hasattr(s, "asnumpy") else np.asarray(s)


def _chunk_of(rank_chunks, c):
    """Chunk ``c`` of one rank's shard dict (int or str keys — JSON
    round trips stringify them)."""
    if c in rank_chunks:
        return rank_chunks[c]
    return rank_chunks[str(c)]


def reshard_zero_snapshot(zero, new_world):
    """Repartition a ZeRO-1 optimizer-state snapshot (the ``"zero"``
    dict of ``Trainer.states_dict()``: world / chunks / per-rank flat
    shards) from its saved world onto ``new_world`` ranks.

    Per chunk: concatenate the old ranks' shard slots (host-side
    gather), drop the old zero-pad at ``total``, re-pad to the new
    world's ``zero_padded_size`` and re-slice into ``new_world`` equal
    shards — the exact layout a fresh ``new_world`` job's own plan
    allocates, so ``Trainer.load_states_dict`` adopts the shards
    directly.  Pure reshaping: bit-exact, and N→M→N round-trips to
    identical bytes.  Requires every saved rank's shards (a
    multi-process restore goes through ``CheckpointManager``, which
    merges the per-rank blobs first)."""
    from ..kvstore import zero_padded_size

    old_world = int(zero["world"])
    new_world = int(new_world)
    if new_world < 1:
        raise MXNetError(f"cannot reshard ZeRO snapshot onto "
                         f"{new_world} rank(s)")
    if old_world == new_world:
        return zero
    shards = {int(r): v for r, v in zero["shards"].items()}
    have = set(shards)
    if have != set(range(old_world)):
        raise MXNetError(
            f"ZeRO snapshot is sharded across {old_world} rank(s) but "
            f"only rank(s) {sorted(have)} are present — gather every "
            "trainer-shard<r>.states first (CheckpointManager does)")
    new_chunks, new_shards = [], {r: {} for r in range(new_world)}
    for c, chunk in enumerate(zero["chunks"]):
        total = int(chunk["total"])
        n_states = int(chunk["n_states"])
        padded = zero_padded_size(total, new_world)
        shard_n = padded // new_world
        new_chunks.append(dict(chunk, padded=padded))
        slots_per_rank = [[] for _ in range(new_world)]
        for slot in range(n_states):
            full = np.concatenate(
                [_shard_np(_chunk_of(shards[r], c)[slot])
                 for r in range(old_world)])[:total]
            pad = padded - full.shape[0]
            if pad:
                full = np.concatenate(
                    [full, np.zeros(pad, dtype=full.dtype)])
            for r in range(new_world):
                slots_per_rank[r].append(
                    full[r * shard_n:(r + 1) * shard_n])
        for r in range(new_world):
            new_shards[r][c] = slots_per_rank[r]
    return {"world": new_world, "chunks": new_chunks,
            "shards": new_shards}


# -- multi-axis mesh shapes -------------------------------------------------


def check_mesh_change(saved_shape, new_shape, source="<checkpoint>"):
    """Validate restoring a snapshot saved at spmd mesh ``saved_shape``
    into a job running at ``new_shape`` (either side: spec string,
    shape dict, or None for the single-axis default).

    Param/state leaves in spmd snapshots are FULL global arrays (the
    checkpoint readback gathers), so any mesh change is
    representationally fine — the first step at the new shape re-places
    every array per the new plan.  What must still hold is the MATH:
    the model-axis product ('mp'×'pp') partitions live layouts, and a
    restore that changes it is a deliberate model-parallelism change —
    allowed, but logged loudly so an accidental MXTPU_MESH_SHAPE drift
    never silently changes the collective pattern.  Returns the parsed
    new shape (or None)."""
    from ..log import get_logger
    from ..parallel.spmd.mesh import (format_mesh_shape, model_axes,
                                      parse_mesh_shape)

    log = get_logger("mxnet_tpu.checkpoint")
    saved = parse_mesh_shape(saved_shape) if saved_shape else None
    new = parse_mesh_shape(new_shape) if new_shape else None
    if saved == new:
        return new
    saved_txt = format_mesh_shape(saved) if saved else "<single-axis>"
    new_txt = format_mesh_shape(new) if new else "<single-axis>"
    if new is None:
        log.warning(
            "%s: snapshot was saved on spmd mesh %s but this trainer "
            "has no mesh_shape — restoring onto the single-axis path "
            "(full arrays; valid, the tensor-parallel layout is "
            "dropped)", source, saved_txt)
        return new
    old_model = int(np.prod(list(model_axes(saved or {}).values()) or [1]))
    new_model = int(np.prod(list(model_axes(new).values()) or [1]))
    if old_model != new_model:
        log.warning(
            "%s: restoring across a MODEL-parallelism change: saved "
            "mesh %s (mp*pp=%d) -> new mesh %s (mp*pp=%d). Valid "
            "(snapshots hold full arrays) but deliberate-only: the "
            "collective pattern and per-device memory change.",
            source, saved_txt, old_model, new_txt, new_model)
    else:
        log.info(
            "%s: elastic mesh reshape on restore: %s -> %s (data axes "
            "only; model axes preserved)", source, saved_txt, new_txt)
    return new


def reshard_states_blob(blob, new_world, source="<checkpoint>"):
    """Repartition one trainer states blob for a ``new_world``-rank
    job: spmd/mesh metadata is validated+remapped by
    :func:`check_mesh_change` at load time (full arrays need no data
    motion), while a legacy ZeRO flat-shard snapshot delegates to
    :func:`reshard_zero_snapshot` for the real repartition.  Returns
    the (possibly new) blob."""
    if not isinstance(blob, dict):
        return blob
    if blob.get("zero"):
        zero = blob["zero"]
        if int(zero.get("world", new_world)) != int(new_world):
            blob = dict(blob)
            blob["zero"] = reshard_zero_snapshot(zero, new_world)
    return blob


# -- pipeline state ---------------------------------------------------------


def _tree_equal(a, b):
    """Structural equality over the host trees pipeline states are made
    of (dicts/lists/tuples/numpy/scalars)."""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
                and a.shape == b.shape and a.dtype == b.dtype
                and np.array_equal(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return a.keys() == b.keys() and all(
            _tree_equal(a[k], b[k]) for k in a)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(
            _tree_equal(x, y) for x, y in zip(a, b))
    try:
        return bool(a == b)
    except Exception:  # exotic leaf: identity is the best we can do
        return a is b


def merge_pipeline_states(blobs, where="<checkpoint>"):
    """Merge the per-rank ``pipeline-shard<r>.state`` blobs of a saved
    world into the ONE rank-symmetric state a resized job loads.

    The ``shard(num_replicas, rank)`` contract makes every rank's
    state identical by construction (same source cursor, same shuffle
    ring + RNG, same rollover) — so the merge is agreement
    VERIFICATION: stage by stage, every rank's saved state must be
    equal; the common value is the merged cursor.  A disagreeing stage
    means per-rank in-flight data that cannot be repartitioned across
    a different world — that raises loudly, naming the stage."""
    if not blobs:
        raise MXNetError(f"{where}: no pipeline shard states to merge")
    first = blobs[0]
    stages0 = (first or {}).get("stages")
    if stages0 is None:
        raise MXNetError(
            f"{where}: unrecognized pipeline state (no stages) — was "
            "it saved by a newer build?")
    for r, blob in enumerate(blobs[1:], start=1):
        stages = (blob or {}).get("stages")
        if stages is None or len(stages) != len(stages0) or any(
                s["type"] != s0["type"]
                for s, s0 in zip(stages, stages0)):
            raise MXNetError(
                f"{where}: pipeline compositions differ across saved "
                f"ranks (rank 0 vs rank {r}) — the per-rank pipelines "
                "of one job must be built identically to reshard")
        for s, s0 in zip(stages, stages0):
            if not _tree_equal(s["state"], s0["state"]):
                raise MXNetError(
                    f"{where}: pipeline stage {s['type']} state "
                    f"differs between saved rank 0 and rank {r} — "
                    "per-rank in-flight data cannot be repartitioned "
                    "across world sizes. Checkpoint at a shard-group "
                    "boundary (Supervisor ctx.step_done(save=...) "
                    "saves are), or rebuild the input pipeline from "
                    "the epoch start (restore with pipeline=None and "
                    "re-create it). strict_topology=True restores the "
                    "plain world-size rejection. See "
                    "docs/checkpointing.md, 'Elastic restore'.")
    return first
