"""Contrib subpackage (ref: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
