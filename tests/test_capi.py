"""Flat C ABI (multi-frontend boundary) — compile and run a pure-C
frontend against lib/libmxtpu_capi.so.

Ref: include/mxnet/c_api.h + src/c_api/c_api.cc (the reference's ~400
MX* flat functions that Scala/R/Julia/cpp-package ride).  The TPU build
inverts the embedding (C hosts the Python orchestrator, which drives
XLA), but the frontend-facing contract is the same: opaque NDArray
handles, string-keyed imperative invoke against the op registry,
GetLastError error protocol, stateless flat calls.

The test builds the .so (make) and the C driver (gcc), then runs the
driver in a clean subprocess — a frontend with no Python of its own.
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    return shutil.which(name)


@pytest.mark.skipif(not _tool("g++") or not _tool("python3-config"),
                    reason="native toolchain unavailable")
def test_c_frontend_drives_the_framework(tmp_path):
    # 1. build the shared library
    r = subprocess.run(["make", "lib/libmxtpu_capi.so"], cwd=REPO,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]

    # 2. build the C driver (plain C, no python headers — the point)
    exe = str(tmp_path / "capi_driver")
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "tests", "capi_driver.c"),
         "-o", exe, "-L" + os.path.join(REPO, "lib"), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.join(REPO, "lib")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]

    # 3. run it: the embedded interpreter must find the venv + repo.
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + [p for p in sys.path if "site-packages" in p])
    # the driver pins jax to cpu itself (MXTPUCAPIInit("cpu")); make sure
    # the axon plugin's env pin doesn't fight that in the subprocess
    env.pop("JAX_PLATFORMS", None)
    save_path = str(tmp_path / "capi_saved.params")
    r = subprocess.run([exe, save_path], capture_output=True, text=True,
                       timeout=600, env=env, cwd=REPO)
    assert r.returncode == 0, (r.stdout[-1000:], r.stderr[-2000:])
    assert "CAPI_DRIVER_OK" in r.stdout
    # the C frontend's save must be loadable by the python frontend
    # (backend/path setup already done by conftest)
    import numpy as np

    from mxnet_tpu.ndarray import ndarray as _nd

    loaded = _nd.load(save_path)
    assert set(loaded) == {"weight_a", "weight_b"}
    assert np.allclose(loaded["weight_a"].asnumpy(),
                       np.arange(1, 7).reshape(2, 3))
