"""Native C++ IO library tests (src/recordio.cc via ctypes)."""
import ctypes
import os

import numpy as np
import pytest

from mxnet_tpu.io import ImageRecordIter, recordio
from mxnet_tpu.utils import native

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="native lib unavailable")


def test_native_recordio_roundtrip(tmp_path):
    lib = native.load()
    path = str(tmp_path / "n.rec").encode()
    w = lib.MXTPURecordIOWriterCreate(path)
    poss = []
    for i in range(5):
        payload = f"native-record-{i}".encode()
        poss.append(lib.MXTPURecordIOWrite(w, payload, len(payload)))
    lib.MXTPURecordIOWriterFree(w)
    assert poss[0] == 0 and all(p >= 0 for p in poss)

    r = lib.MXTPURecordIOReaderCreate(path)
    out = ctypes.c_char_p()
    got = []
    while True:
        n = lib.MXTPURecordIORead(r, ctypes.byref(out))
        if n <= 0:
            break
        got.append(ctypes.string_at(out, n).decode())
    lib.MXTPURecordIOReaderFree(r)
    assert got == [f"native-record-{i}" for i in range(5)]


def test_native_reads_python_written_rec(tmp_path):
    """Byte-format compatibility: python writer -> native reader."""
    lib = native.load()
    rec = str(tmp_path / "py.rec")
    w = recordio.MXRecordIO(rec, "w")
    w.write(b"hello from python")
    w.close()
    r = lib.MXTPURecordIOReaderCreate(rec.encode())
    out = ctypes.c_char_p()
    n = lib.MXTPURecordIORead(r, ctypes.byref(out))
    assert ctypes.string_at(out, n) == b"hello from python"
    lib.MXTPURecordIOReaderFree(r)


def _make_jpeg_rec(tmp_path, n=16, size=40):
    rec = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    raw = []
    for i in range(n):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        raw.append(img)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 4), i, 0), img, quality=95,
            img_fmt=".jpg"))
    w.close()
    return rec, raw


def test_native_image_pipeline_matches_python(tmp_path):
    rec, raw = _make_jpeg_rec(tmp_path)
    kw = dict(path_imgrec=rec, data_shape=(3, 32, 32), batch_size=4,
              shuffle=False, rand_crop=False, rand_mirror=False)
    it_native = ImageRecordIter(use_native=True, **kw)
    it_py = ImageRecordIter(use_native=False, **kw)
    assert it_native._native is not None
    assert it_py._native is None

    nb = pb = 0
    for b_n, b_p in zip(it_native, it_py):
        nb += 1
        dn = b_n.data[0].asnumpy()
        dp = b_p.data[0].asnumpy()
        assert dn.shape == dp.shape == (4, 3, 32, 32)
        # center-crop from the same JPEG: decoders may differ by a few
        # LSBs; mean abs diff must be tiny
        assert np.abs(dn - dp).mean() < 2.0, np.abs(dn - dp).mean()
        assert np.allclose(b_n.label[0].asnumpy(),
                           b_p.label[0].asnumpy())
    assert nb == 4
    # second epoch works
    it_native.reset()
    assert sum(1 for _ in it_native) == 4


def test_native_pipeline_augment_shapes(tmp_path):
    rec, _ = _make_jpeg_rec(tmp_path, n=8, size=48)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                         batch_size=4, shuffle=True, rand_crop=True,
                         rand_mirror=True, use_native=True)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3, 32, 32)
    labels = np.concatenate([b.label[0].asnumpy() for b in batches])
    assert ((labels >= 0) & (labels <= 3)).all()


def test_storage_pool_reuse():
    """Size-class reuse (ref: tests/cpp/storage/storage_test.cc)."""
    import numpy as np

    from mxnet_tpu import storage

    st = storage.Storage.get()
    h1 = st.alloc(1000)
    arr = h1.as_numpy(np.float32)
    arr[:] = 1.5
    assert arr.shape == (250,)
    p1 = h1.ptr
    st.free(h1)
    if st.native:
        assert p1 % 64 == 0
        h2 = st.alloc(900)  # same 1024-byte class -> pooled block
        assert h2.ptr == p1
        assert st.stats()["hits"] >= 1
        st.direct_free(h2)
        st.release_all()
        assert st.stats()["pool_bytes"] == 0
    else:
        h2 = st.alloc(900)
        st.free(h2)


def test_storage_unpooled_mode(monkeypatch):
    monkeypatch.setenv("MXTPU_MEM_POOL_TYPE", "Unpooled")
    from mxnet_tpu import storage

    st = storage.Storage()  # fresh instance, not the singleton
    h1 = st.alloc(512)
    p1 = h1.ptr
    st.free(h1)
    h2 = st.alloc(512)
    st.free(h2)  # no pooling guarantees; just must not crash
    assert st.stats()["used_bytes"] == 0 or not st.native
    del p1


def test_storage_python_fallback(monkeypatch):
    monkeypatch.setenv("MXTPU_NO_NATIVE", "1")
    from mxnet_tpu import storage

    st = storage.Storage()
    assert not st.native
    h = st.alloc(256)
    v = h.as_numpy()
    v[:] = 7
    st.free(h)
    assert st.stats()["used_bytes"] == 0


def test_storage_bad_pool_type(monkeypatch):
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu import storage

    monkeypatch.setenv("MXTPU_MEM_POOL_TYPE", "Bogus")
    with pytest.raises(mx.MXNetError):
        storage.Storage()


def test_native_reader_reassembles_chunked_records(tmp_path):
    """The C++ reader must agree with the python writer on dmlc
    magic-escape chunking (payloads containing the aligned magic word
    split into cflag chunks; readers re-insert the magic)."""
    import ctypes
    import struct

    from mxnet_tpu.io import recordio
    from mxnet_tpu.utils import native

    lib = native.load()
    if lib is None:
        pytest.skip("native io unavailable")
    magic = struct.pack("<I", recordio.KMAGIC)
    payloads = [b"plain", b"abcd" + magic + b"tail",
                magic + magic + b"x", b"last"]
    p = str(tmp_path / "esc.rec")
    w = recordio.MXRecordIO(p, "w")
    for pay in payloads:
        w.write(pay)
    w.close()
    h = lib.MXTPURecordIOReaderCreate(p.encode())
    assert h
    try:
        out = ctypes.c_char_p()
        for pay in payloads:
            n = lib.MXTPURecordIORead(h, ctypes.byref(out))
            assert n == len(pay)
            assert ctypes.string_at(out, n) == pay
        assert lib.MXTPURecordIORead(h, ctypes.byref(out)) == 0
    finally:
        lib.MXTPURecordIOReaderFree(h)


def test_native_writer_escapes_chunks(tmp_path):
    """The C ABI writer must emit the same magic-escape chunking the
    python writer does; the python reader verifies round-trip."""
    import ctypes
    import struct

    from mxnet_tpu.io import recordio
    from mxnet_tpu.utils import native

    lib = native.load()
    if lib is None:
        pytest.skip("native io unavailable")
    magic = struct.pack("<I", recordio.KMAGIC)
    payloads = [b"plain", b"abcd" + magic + b"tail", magic + b"x"]
    p = str(tmp_path / "nesc.rec")
    h = lib.MXTPURecordIOWriterCreate(p.encode())
    assert h
    for pay in payloads:
        assert lib.MXTPURecordIOWrite(h, pay, len(pay)) >= 0
    lib.MXTPURecordIOWriterFree(h)
    r = recordio.MXRecordIO(p, "r")
    for pay in payloads:
        assert r.read() == pay
    assert r.read() is None
    r.close()
